"""Optimizers (REF:python/mxnet/optimizer/optimizer.py + the fused update
kernels in REF:src/operator/optimizer_op.cc).

Design: every optimizer exposes a *pure functional core*
``update_core(weight, grad, state, lr, wd, t) -> (new_weight, new_state)`` on
raw jax arrays — the analog of the reference's fused sgd_update/adam_update
kernels, jit-able inside a compiled train step — plus the reference's
imperative face (`update(index, weight, grad, state)`) used by Trainer/KVStore.
Mixed precision: `multi_precision` keeps fp32 master weights for fp16/bf16
params, matching the reference's mp_* kernel family.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..base import Registry
from ..ndarray import NDArray
from ..ndarray.ops import (adam_update_core, sgd_mom_update_core,
                           sgd_update_core)

__all__ = ["Optimizer", "SGD", "NAG", "Adam", "AdamW", "RMSProp", "AdaGrad",
           "AdaDelta", "Ftrl", "Signum", "LAMB", "LBSGD", "create", "register", "Updater",
           "get_updater", "registry"]

registry = Registry("optimizer")
register = registry.register


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    return registry.create(name, **kwargs)


class Optimizer:
    """Base optimizer: lr scheduling, wd/lr multipliers, grad rescale/clip,
    per-index state, mixed-precision master weights."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.multi_precision = multi_precision
        self.idx2name = param_idx2name or {}
        self.param_dict = param_dict or {}
        self.lr_mult = {}
        self.wd_mult = {}

    # -- reference API --------------------------------------------------------
    def set_learning_rate(self, lr):
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    # -- state ---------------------------------------------------------------
    # True on subclasses whose update_core is purely elementwise in
    # (weight, grad, state) with scalar hyperparameters — no per-tensor
    # reductions (norms, trust ratios) and no shape dependence.  Such
    # updates may be applied to a flat concatenation of many params in
    # ONE call (CompiledTrainStep's fused single-chip update path; the
    # r4 chip profile measured ~160 per-param update op-clusters of pure
    # per-op overhead).  LAMB/LBSGD compute per-tensor statistics and
    # must stay per-param; flags are set below the class definitions.
    elementwise_update = False

    def create_state(self, index, weight):
        """Return opaque per-weight state (raw jax arrays / tuples / None)."""
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype in (jnp.float16, jnp.bfloat16):
            master = weight._data.astype(jnp.float32)
            return (master, self.create_state(index, NDArray(master)))
        return self.create_state(index, weight)

    # -- updates --------------------------------------------------------------
    def update_core(self, weight, grad, state, lr, wd, t):
        raise NotImplementedError

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        new_w, new_state = self.update_core(weight._data, grad._data, state,
                                            lr, wd, t)
        weight._rebind(new_w.astype(weight.dtype))
        return new_state

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype in (jnp.float16, jnp.bfloat16):
            self._update_count(index)
            lr, wd = self._get_lr(index), self._get_wd(index)
            t = self._index_update_count[index]
            master, inner = state
            new_master, new_inner = self.update_core(
                master, grad._data.astype(jnp.float32), inner, lr, wd, t)
            weight._rebind(new_master.astype(weight.dtype))
            return (new_master, new_inner)
        return self.update(index, weight, grad, state)

    def _preprocess(self, grad, weight, wd):
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return g


@register
class SGD(Optimizer):
    """SGD (+momentum) — fused form of REF sgd_update/sgd_mom_update."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return jnp.zeros(weight.shape, jnp.float32 if weight.dtype in
                             (jnp.float16, jnp.bfloat16) else weight.dtype)
        return None

    def update_core(self, weight, grad, state, lr, wd, t):
        if self.momentum == 0.0:
            return sgd_update_core(weight, grad, lr, wd, self.rescale_grad,
                                   self.clip_gradient), None
        return sgd_mom_update_core(weight, grad, state, lr, self.momentum, wd,
                                   self.rescale_grad, self.clip_gradient)


@register
class LBSGD(Optimizer):
    """Large-batch SGD: momentum SGD with LARS layer-wise adaptive rates
    and warmup (REF optimizer.py LBSGD — You et al., "Large Batch Training
    of Convolutional Networks")."""

    def __init__(self, momentum=0.0,
                 warmup_strategy="linear", warmup_epochs=5, batch_scale=1,
                 updates_per_epoch=32, begin_epoch=0, num_epochs=60,
                 eta=0.001, epsilon=1e-9, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.eta = eta          # LARS trust coefficient
        self.epsilon = epsilon
        self.warmup_updates = max(1, int(warmup_epochs * updates_per_epoch))
        self.warmup_strategy = warmup_strategy

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return jnp.zeros(weight.shape, jnp.float32 if weight.dtype in
                             (jnp.float16, jnp.bfloat16) else weight.dtype)
        return None

    def update_core(self, weight, grad, state, lr, wd, t):
        # linear warmup on top of the scheduler-provided lr
        warm = jnp.minimum(1.0, t / self.warmup_updates) \
            if self.warmup_strategy == "linear" else 1.0
        g = self._preprocess(grad, weight, wd)
        # LARS: scale lr by ||w|| / (||g|| + wd*||w|| + eps) per layer
        wnorm = jnp.sqrt(jnp.sum(weight.astype(jnp.float32) ** 2))
        gnorm = jnp.sqrt(jnp.sum(g.astype(jnp.float32) ** 2))
        trust = jnp.where(
            (wnorm > 0) & (gnorm > 0),
            self.eta * wnorm / (gnorm + wd * wnorm + self.epsilon), 1.0)
        eff_lr = (lr * warm * trust).astype(weight.dtype)
        g = g + wd * weight
        if self.momentum == 0.0:
            return weight - eff_lr * g, None
        new_mom = self.momentum * state + g
        return weight - eff_lr * new_mom, new_mom


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (REF nag_mom_update)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        return jnp.zeros(weight.shape, weight.dtype)

    def update_core(self, weight, grad, state, lr, wd, t):
        g = self._preprocess(grad, weight, wd) + wd * weight
        new_mom = self.momentum * state + g
        new_w = weight - lr * (g + self.momentum * new_mom)
        return new_w, new_mom


@register
class Adam(Optimizer):
    """REF adam_update fused kernel."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        dt = jnp.float32 if weight.dtype in (jnp.float16, jnp.bfloat16) \
            else weight.dtype
        return (jnp.zeros(weight.shape, dt), jnp.zeros(weight.shape, dt))

    def update_core(self, weight, grad, state, lr, wd, t):
        mean, var = state
        new_w, m, v = adam_update_core(weight, grad, mean, var, lr, self.beta1,
                                       self.beta2, self.epsilon, wd, t,
                                       self.rescale_grad, self.clip_gradient)
        return new_w, (m, v)


@register
class AdamW(Optimizer):
    """Decoupled weight decay (REF contrib adamw [ver>=1.6])."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        dt = jnp.float32 if weight.dtype in (jnp.float16, jnp.bfloat16) \
            else weight.dtype
        return (jnp.zeros(weight.shape, dt), jnp.zeros(weight.shape, dt))

    def update_core(self, weight, grad, state, lr, wd, t):
        mean, var = state
        g = self._preprocess(grad, weight, wd)
        m = self.beta1 * mean + (1 - self.beta1) * g
        v = self.beta2 * var + (1 - self.beta2) * jnp.square(g)
        mhat = m / (1 - self.beta1 ** t)
        vhat = v / (1 - self.beta2 ** t)
        new_w = weight - lr * (mhat / (jnp.sqrt(vhat) + self.epsilon) +
                               wd * weight)
        return new_w, (m, v)


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2 = gamma1, gamma2
        self.epsilon = epsilon
        self.centered = centered

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, weight.dtype)
        return (z, z, z) if self.centered else z

    def update_core(self, weight, grad, state, lr, wd, t):
        g = self._preprocess(grad, weight, wd) + wd * weight
        if self.centered:
            n, mg, delta = state
            n = (1 - self.gamma1) * jnp.square(g) + self.gamma1 * n
            mg = (1 - self.gamma1) * g + self.gamma1 * mg
            delta = self.gamma2 * delta - lr * g / jnp.sqrt(
                n - jnp.square(mg) + self.epsilon)
            return weight + delta, (n, mg, delta)
        n = (1 - self.gamma1) * jnp.square(g) + self.gamma1 * state
        return weight - lr * g / jnp.sqrt(n + self.epsilon), n


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return jnp.zeros(weight.shape, weight.dtype)

    def update_core(self, weight, grad, state, lr, wd, t):
        g = self._preprocess(grad, weight, wd) + wd * weight
        hist = state + jnp.square(g)
        return weight - lr * g / jnp.sqrt(hist + self.float_stable_eps), hist


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, weight.dtype)
        return (z, z)

    def update_core(self, weight, grad, state, lr, wd, t):
        acc_g, acc_delta = state
        g = self._preprocess(grad, weight, wd) + wd * weight
        acc_g = self.rho * acc_g + (1 - self.rho) * jnp.square(g)
        delta = jnp.sqrt(acc_delta + self.epsilon) / \
            jnp.sqrt(acc_g + self.epsilon) * g
        acc_delta = self.rho * acc_delta + (1 - self.rho) * jnp.square(delta)
        return weight - delta, (acc_g, acc_delta)


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, weight.dtype)
        return (z, z)  # z, n

    def update_core(self, weight, grad, state, lr, wd, t):
        z, n = state
        g = self._preprocess(grad, weight, wd)
        sigma = (jnp.sqrt(n + jnp.square(g)) - jnp.sqrt(n)) / lr
        z = z + g - sigma * weight
        n = n + jnp.square(g)
        new_w = jnp.where(
            jnp.abs(z) > self.lamda1,
            -(z - jnp.sign(z) * self.lamda1) /
            ((self.beta + jnp.sqrt(n)) / lr + wd),
            0.0)
        return new_w.astype(weight.dtype), (z, n)


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        return jnp.zeros(weight.shape, weight.dtype) if self.momentum else None

    def update_core(self, weight, grad, state, lr, wd, t):
        g = self._preprocess(grad, weight, wd)
        if self.momentum:
            mom = self.momentum * state - (1 - self.momentum) * g
            new_w = (1 - lr * self.wd_lh) * weight + lr * jnp.sign(mom)
            return new_w, mom
        return (1 - lr * self.wd_lh) * weight - lr * jnp.sign(g), None


@register
class LAMB(Optimizer):
    """Layer-wise adaptive large-batch optimizer (REF lamb_update [ver>=1.6];
    the BERT path)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lower_bound, self.upper_bound = lower_bound, upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        dt = jnp.float32 if weight.dtype in (jnp.float16, jnp.bfloat16) \
            else weight.dtype
        return (jnp.zeros(weight.shape, dt), jnp.zeros(weight.shape, dt))

    def update_core(self, weight, grad, state, lr, wd, t):
        mean, var = state
        g = self._preprocess(grad, weight, wd)
        m = self.beta1 * mean + (1 - self.beta1) * g
        v = self.beta2 * var + (1 - self.beta2) * jnp.square(g)
        if self.bias_correction:
            mhat = m / (1 - self.beta1 ** t)
            vhat = v / (1 - self.beta2 ** t)
        else:
            mhat, vhat = m, v
        update = mhat / (jnp.sqrt(vhat) + self.epsilon) + wd * weight
        wnorm = jnp.linalg.norm(weight)
        unorm = jnp.linalg.norm(update)
        ratio = jnp.where(
            (wnorm > 0) & (unorm > 0),
            wnorm / unorm, 1.0)
        if self.lower_bound is not None:
            ratio = jnp.maximum(ratio, self.lower_bound)
        if self.upper_bound is not None:
            ratio = jnp.minimum(ratio, self.upper_bound)
        return weight - lr * ratio * update, (m, v)


class Updater:
    """KVStore server-side updater (REF optimizer.py:Updater / get_updater):
    applies optimizer updates keyed by parameter index."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = \
                self.optimizer.create_state_multi_precision(index, weight)
        self.states[index] = self.optimizer.update_multi_precision(
            index, weight, grad, self.states[index])

    def set_states(self, states):
        self.states = states

    def get_states(self):
        return self.states


def get_updater(optimizer):
    return Updater(optimizer)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (REF:src/operator/optimizer_op
    dcasgd; Zheng et al. 2016): the reference's async-worker staleness
    compensation — kept for API parity (our dist is bulk-synchronous, so
    the previous-weight term sees a 1-step-old copy)."""

    def __init__(self, learning_rate=0.01, momentum=0.0, lamda=0.04,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.lamda = lamda

    def create_state(self, index, weight):
        w = weight._data if hasattr(weight, "_data") else weight
        return (jnp.zeros(w.shape, w.dtype), jnp.asarray(w))

    def update_core(self, weight, grad, state, lr, wd, t):
        g = self._preprocess(grad, weight, wd)
        mom, prev_w = state
        comp = g + self.lamda * g * g * (weight - prev_w)
        mom = self.momentum * mom - lr * comp
        new_w = weight + mom
        return new_w, (mom, new_w)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (REF optimizer.py:SGLD):
    SGD + sqrt(lr) gaussian noise — Bayesian posterior sampling."""

    def __init__(self, learning_rate=0.01, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)

    def create_state(self, index, weight):
        return None

    def update_core(self, weight, grad, state, lr, wd, t):
        g = self._preprocess(grad, weight, wd)
        # deterministic per-(t, shape) draw keyed off the framework stream
        # contract: traced inside the step, keyed on the step counter
        # tpumx-lint: disable=determinism -- traced constant key folded with
        # t: the noise is a pure function of the step counter, so a resume
        # capsule replays it exactly without carrying any stream state
        key = jax.random.fold_in(jax.random.PRNGKey(0),
                                 jnp.asarray(t, jnp.int32))
        noise = jax.random.normal(key, weight.shape, jnp.float32)
        return (weight - 0.5 * lr * g +
                jnp.sqrt(lr).astype(weight.dtype) *
                noise.astype(weight.dtype)), None


@register
class Adamax(Optimizer):
    """Adam with infinity norm (REF optimizer.py:Adamax)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        w = weight._data if hasattr(weight, "_data") else weight
        return (jnp.zeros(w.shape, jnp.float32),
                jnp.zeros(w.shape, jnp.float32))

    def update_core(self, weight, grad, state, lr, wd, t):
        g = self._preprocess(grad, weight, wd).astype(jnp.float32)
        m, u = state
        m = self.beta1 * m + (1 - self.beta1) * g
        u = jnp.maximum(self.beta2 * u, jnp.abs(g))
        lr_t = lr / (1 - self.beta1 ** t)
        new_w = weight - (lr_t * m / (u + 1e-8)).astype(weight.dtype)
        return new_w, (m, u)


@register
class Nadam(Optimizer):
    """Nesterov Adam (REF optimizer.py:Nadam; Dozat 2016)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay

    def create_state(self, index, weight):
        w = weight._data if hasattr(weight, "_data") else weight
        return (jnp.zeros(w.shape, jnp.float32),
                jnp.zeros(w.shape, jnp.float32),
                jnp.ones((), jnp.float32))  # m_schedule product

    def update_core(self, weight, grad, state, lr, wd, t):
        g = self._preprocess(grad, weight, wd).astype(jnp.float32)
        m, v, m_sched = state
        mu_t = self.beta1 * (1 - 0.5 * 0.96 ** (t * self.schedule_decay))
        mu_t1 = self.beta1 * (1 - 0.5 * 0.96 **
                              ((t + 1) * self.schedule_decay))
        m_sched_new = m_sched * mu_t
        g_prime = g / (1 - m_sched_new)
        m = self.beta1 * m + (1 - self.beta1) * g
        m_prime = m / (1 - m_sched_new * mu_t1)
        v = self.beta2 * v + (1 - self.beta2) * g * g
        v_prime = v / (1 - self.beta2 ** t)
        m_bar = (1 - mu_t) * g_prime + mu_t1 * m_prime
        new_w = weight - (lr * m_bar /
                          (jnp.sqrt(v_prime) + self.epsilon)).astype(
                              weight.dtype)
        return new_w, (m, v, m_sched_new)


@register
class FTML(Optimizer):
    """Follow the moving leader (REF ftml_update; Zheng & Kwok 2017)."""

    def __init__(self, learning_rate=0.0025, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        w = weight._data if hasattr(weight, "_data") else weight
        # three DISTINCT buffers: donation rejects one buffer bound to
        # several arguments (f(donate(a), donate(a)))
        return (jnp.zeros(w.shape, jnp.float32),
                jnp.zeros(w.shape, jnp.float32),
                jnp.zeros(w.shape, jnp.float32))  # d, v, z

    def update_core(self, weight, grad, state, lr, wd, t):
        g = self._preprocess(grad, weight, wd).astype(jnp.float32)
        d_prev, v_prev, z_prev = state
        v = self.beta2 * v_prev + (1 - self.beta2) * g * g
        d = (1 - self.beta1 ** t) / lr * (
            jnp.sqrt(v / (1 - self.beta2 ** t)) + self.epsilon)
        sigma = d - self.beta1 * d_prev
        z = self.beta1 * z_prev + (1 - self.beta1) * g - sigma * weight
        new_w = (-z / d).astype(weight.dtype)
        return new_w, (d, v, z)


# update_core verified elementwise (no per-tensor reductions / shape
# dependence) — eligible for the fused flat-update path.  SGLD is NOT
# listed: its update draws normal(key, weight.shape), and a draw over the
# flat concatenation yields different noise than per-param draws, so the
# fused path could not be bit-identical.
for _cls in (SGD, NAG, Adam, AdamW, RMSProp, AdaGrad, AdaDelta, Signum,
             Adamax, Nadam, FTML):
    _cls.elementwise_update = True
del _cls
