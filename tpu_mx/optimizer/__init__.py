"""mx.optimizer — optimizers + LR schedulers."""
from .optimizer import *  # noqa: F401,F403
from . import lr_scheduler
from .lr_scheduler import (CosineScheduler, FactorScheduler, LRScheduler,
                           MultiFactorScheduler, PolyScheduler)
