"""RNG state: the `mx.random.seed()` layer over JAX's splittable PRNG.

The reference keeps per-device sampler states inside the ResourceManager
(REF:src/resource.cc kRandom).  Here a process-global key is split per draw in
eager mode; inside a `hybridize()` trace the active `KeyHolder` (installed by
Block.apply) supplies *traced* subkeys so compiled graphs stay pure and
reproducible — keys become explicit step-function inputs, the XLA-correct way.

State is DATA (docs/robustness.md "Deterministic resume"): the stream is
observable and restorable, not just reseedable.  :func:`get_state` returns an
opaque token covering BOTH generators the framework draws from — the global
JAX key and numpy's global state — and :func:`set_state` restores them
bit-exactly, which is what lets a training-state capsule (`tpu_mx/resume.py`)
make a crash-recovered run replay the exact RNG stream of the run that died.
:func:`seed` returns the prior token so tests (and capsule writers) can
save/restore the stream around themselves.

The global key is genuinely process-global (one lock-guarded stream): a step
function running on a watchdog daemon thread (`supervisor.run_with_deadline`)
draws from the SAME stream the main thread would — a thread-local key would
silently hand every watchdog thread its own fresh `PRNGKey(0)` replay.
"""
from __future__ import annotations

import contextlib
import threading

import jax

__all__ = ["seed", "get_state", "set_state", "take_key", "host_rng",
           "KeyHolder", "key_scope"]


class _GlobalRNG:
    def __init__(self):
        self.lock = threading.Lock()
        self.key = jax.random.PRNGKey(0)


_GLOBAL = _GlobalRNG()
_HOLDER = threading.local()


class KeyHolder:
    """Mutable holder threading one traced key through a functional forward."""

    def __init__(self, key):
        self.key = key

    def take(self):
        self.key, sub = jax.random.split(self.key)
        return sub


@contextlib.contextmanager
def key_scope(key):
    """Route `take_key()` to splits of `key` (used during functional apply)."""
    holder = KeyHolder(key)
    prev = getattr(_HOLDER, "holder", None)
    _HOLDER.holder = holder
    try:
        yield holder
    finally:
        _HOLDER.holder = prev


def take_key():
    holder = getattr(_HOLDER, "holder", None)
    if holder is not None:
        return holder.take()
    with _GLOBAL.lock:
        _GLOBAL.key, sub = jax.random.split(_GLOBAL.key)
    return sub


def host_rng():
    """The framework's blessed HOST-side RNG: numpy's global generator.

    Library code that samples on the host (data-augmentation transforms,
    host-path initializers, shufflers) must draw through this accessor
    rather than calling ``np.random.*`` directly — same stream, but the
    dependence on the capsule-covered state becomes explicit and
    statically checkable (tools/tpumx_lint.py's determinism pass flags
    direct global draws).  The returned generator is exactly what
    :func:`seed` seeds and :func:`get_state`/:func:`set_state` snapshot
    and restore, so every draw through it replays bit-exactly under a
    resume capsule.  Iterators with their OWN ``RandomState(seed)`` plus
    ``state_dict()`` coverage should keep it — a private stream is
    stronger isolation, not a violation."""
    import numpy as _np
    # the module-level singleton behind np.random.* — NOT a new stream
    return _np.random.mtrand._rand


def get_state():
    """Snapshot BOTH framework RNG streams as an opaque, picklable token.

    Covers the global JAX key (device sampling — ``nd.random.*``, on-device
    init, the compiled train step's per-step subkeys) and numpy's global
    state (host-path initializers and any ``np.random``-backed iterator).
    Per-iterator private ``RandomState``s are NOT included — each
    ``DataIter.state_dict()`` carries its own.  Pass the token to
    :func:`set_state` to restore the streams bit-exactly."""
    import numpy as _np
    with _GLOBAL.lock:
        key = _np.asarray(_GLOBAL.key)
    return {"jax_key": key, "numpy": _np.random.get_state()}


def set_state(state):
    """Restore a :func:`get_state` / :func:`seed` token.

    Tolerant of JSON round-trips (lists where the token had arrays/tuples):
    a capsule that serialized the token can hand it straight back."""
    import numpy as _np
    key = _np.asarray(state["jax_key"], dtype=_np.uint32)
    st = state["numpy"]
    np_state = (str(st[0]), _np.asarray(st[1], dtype=_np.uint32),
                int(st[2]), int(st[3]), float(st[4]))
    with _GLOBAL.lock:
        _GLOBAL.key = jax.numpy.asarray(key)
    _np.random.set_state(np_state)


def seed(seed_state, ctx="all"):
    """mx.random.seed (REF:python/mxnet/random.py).

    Seeds BOTH generators the framework draws from: the JAX key (device
    sampling — `nd.random.*`, on-device parameter init) and numpy's
    global state (the host-path initializers, e.g. Orthogonal/Bilinear,
    sample from np.random the way the reference's initializers sample
    from its own engine RNG — one seed call must make either path
    deterministic).

    Returns the PRIOR state token (see :func:`get_state`) so a caller can
    save/restore the streams around itself::

        tok = mx.random.seed(7)
        ... deterministic block ...
        mx.random.set_state(tok)        # outer stream continues untouched
    """
    import numpy as _np
    prior = get_state()
    with _GLOBAL.lock:
        _GLOBAL.key = jax.random.PRNGKey(int(seed_state))
    _np.random.seed(int(seed_state) % (2 ** 32))
    return prior
