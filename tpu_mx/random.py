"""RNG state: the `mx.random.seed()` layer over JAX's splittable PRNG.

The reference keeps per-device sampler states inside the ResourceManager
(REF:src/resource.cc kRandom).  Here a process-global key is split per draw in
eager mode; inside a `hybridize()` trace the active `KeyHolder` (installed by
Block.apply) supplies *traced* subkeys so compiled graphs stay pure and
reproducible — keys become explicit step-function inputs, the XLA-correct way.
"""
from __future__ import annotations

import contextlib
import threading

import jax

__all__ = ["seed", "take_key", "KeyHolder", "key_scope"]


class _GlobalRNG(threading.local):
    def __init__(self):
        self.key = jax.random.PRNGKey(0)


_GLOBAL = _GlobalRNG()
_HOLDER = threading.local()


class KeyHolder:
    """Mutable holder threading one traced key through a functional forward."""

    def __init__(self, key):
        self.key = key

    def take(self):
        self.key, sub = jax.random.split(self.key)
        return sub


@contextlib.contextmanager
def key_scope(key):
    """Route `take_key()` to splits of `key` (used during functional apply)."""
    holder = KeyHolder(key)
    prev = getattr(_HOLDER, "holder", None)
    _HOLDER.holder = holder
    try:
        yield holder
    finally:
        _HOLDER.holder = prev


def take_key():
    holder = getattr(_HOLDER, "holder", None)
    if holder is not None:
        return holder.take()
    _GLOBAL.key, sub = jax.random.split(_GLOBAL.key)
    return sub


def seed(seed_state, ctx="all"):
    """mx.random.seed (REF:python/mxnet/random.py).

    Seeds BOTH generators the framework draws from: the JAX key (device
    sampling — `nd.random.*`, on-device parameter init) and numpy's
    global state (the host-path initializers, e.g. Orthogonal/Bilinear,
    sample from np.random the way the reference's initializers sample
    from its own engine RNG — one seed call must make either path
    deterministic)."""
    import numpy as _np
    _GLOBAL.key = jax.random.PRNGKey(int(seed_state))
    _np.random.seed(int(seed_state) % (2 ** 32))
