"""Functional-trace scope shared between the op layer and gluon.

When a HybridBlock's forward is being traced as a pure function (hybridize /
CompiledTrainStep), ops must stay in raw-jax land: no NDArray wrapping, no
tape recording, creation ops return raw arrays.  gluon.parameter's
substitution scope pushes here; ndarray.ops checks here.  Lives in its own
module so ops.py doesn't import gluon.
"""
from __future__ import annotations

import threading


class _Stack(threading.local):
    def __init__(self):
        self.stack = []


_STACK = _Stack()


def push(entry):
    _STACK.stack.append(entry)


def pop():
    return _STACK.stack.pop()


def active():
    return bool(_STACK.stack)


def top():
    return _STACK.stack[-1] if _STACK.stack else None
