"""Minimal protobuf wire-format codec (encoder + decoder).

The hermetic environment has no `onnx`/`protobuf` packages, so
contrib.onnx writes and reads the ONNX protobuf wire format directly
(REF:python/mxnet/contrib/onnx used the onnx package; the format itself is
the stable public protobuf encoding: https://protobuf.dev/programming-guides/encoding/).

Only what ONNX needs: varint (wire 0), 64-bit (wire 1, unused), and
length-delimited (wire 2) fields; float scalars ride as fixed32 (wire 5).
Messages are built bottom-up as bytes; the decoder returns a
{field_number: [values]} multimap with raw bytes for nested messages.
"""
from __future__ import annotations

import struct

__all__ = ["Msg", "decode", "varint", "zigzag_ok"]


def varint(n: int) -> bytes:
    """Unsigned LEB128 (negative ints are 10-byte two's-complement, as
    protobuf encodes int32/int64)."""
    if n < 0:
        n += 1 << 64
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


class Msg:
    """Append-only protobuf message builder."""

    def __init__(self):
        self._parts = []

    def _tag(self, field, wire):
        self._parts.append(varint((field << 3) | wire))

    def int(self, field, value):
        """varint field (int32/int64/uint64/bool/enum)."""
        self._tag(field, 0)
        self._parts.append(varint(int(value)))
        return self

    def float(self, field, value):
        """float field (fixed 32-bit)."""
        self._tag(field, 5)
        self._parts.append(struct.pack("<f", float(value)))
        return self

    def bytes(self, field, value):
        """length-delimited field: bytes, str, or a nested Msg."""
        if isinstance(value, Msg):
            value = value.tobytes()
        elif isinstance(value, str):
            value = value.encode("utf-8")
        self._tag(field, 2)
        self._parts.append(varint(len(value)))
        self._parts.append(value)
        return self

    def ints(self, field, values):
        """repeated int64, packed encoding."""
        payload = b"".join(varint(int(v)) for v in values)
        return self.bytes(field, payload)

    def tobytes(self) -> bytes:
        return b"".join(self._parts)


def _read_varint(buf, i):
    shift, val = 0, 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7


def decode(buf) -> dict:
    """Parse one message into {field_number: [raw values]}.  Varints come
    back as ints, length-delimited fields as bytes (decode nested messages
    recursively; decode packed int64 lists with decode_packed_ints)."""
    fields = {}
    i = 0
    n = len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, i = _read_varint(buf, i)
        elif wire == 1:
            val = struct.unpack("<q", buf[i:i + 8])[0]
            i += 8
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            val = bytes(buf[i:i + ln])
            i += ln
        elif wire == 5:
            val = struct.unpack("<f", buf[i:i + 4])[0]
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        fields.setdefault(field, []).append(val)
    return fields


def decode_packed_ints(raw) -> list:
    """Packed repeated int64 payload -> [int] (also accepts a list of
    already-unpacked varints, the non-packed encoding)."""
    if isinstance(raw, list):
        out = []
        for r in raw:
            out.extend(decode_packed_ints(r) if isinstance(r, (bytes,
                       bytearray)) else [r])
        return out
    out, i = [], 0
    while i < len(raw):
        v, i = _read_varint(raw, i)
        if v >= 1 << 63:
            v -= 1 << 64
        out.append(v)
    return out


def zigzag_ok():  # pragma: no cover - marker for API completeness
    """ONNX uses no sint fields; zigzag is deliberately unimplemented."""
    return False
