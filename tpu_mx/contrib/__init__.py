"""mx.contrib — auxiliary capabilities (REF:python/mxnet/contrib/)."""
from . import compression
from . import amp
from . import quantization
from . import text
from . import onnx
from . import tensorrt
from . import chaos
