"""AMP cast lists (REF:python/mxnet/contrib/amp/lists/symbol_fp16.py).

Ops routed to the low-precision dtype are the MXU-bound ones (matmul/conv
families — bf16 is the TPU-native precision for the systolic array); ops
kept in float32 are the numerically sensitive reductions/exponentials.
Everything not listed runs in whatever dtype its inputs already have
(XLA's type promotion plays the reference's "widest type cast" role).
"""

# run in the AMP target dtype (bfloat16 by default): MXU-heavy ops
TARGET_DTYPE_OPS = [
    "FullyConnected",
    "Convolution",
    "Deconvolution",
    "dot",
    "batch_dot",
]

# always promoted to float32: loss / normalization / exponential families
FP32_OPS = [
    "softmax",
    "log_softmax",
    "softmax_cross_entropy",
    "SoftmaxActivation",
    "SoftmaxOutput",
    "norm",
    "L2Normalization",
    "LayerNorm",
    "InstanceNorm",
    "GroupNorm",
    "masked_softmax",
    "masked_log_softmax",
    "RMSNorm",
    "BatchNorm",
    "exp",
    "log",
    "log2",
    "log10",
    "log1p",
    "expm1",
]

# ops whose float inputs are cast to the *widest* float dtype present
WIDEST_TYPE_CASTS = [
    "add_n",
    "concat",
    "stack",
    "where",
]
