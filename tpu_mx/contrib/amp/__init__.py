"""AMP: automatic mixed precision (REF:python/mxnet/contrib/amp/)."""
from . import lists
from .amp import (LossScaler, convert_model, init, init_trainer, scale_loss,
                  unscale)
