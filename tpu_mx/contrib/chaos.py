"""Deterministic fault injection for the durability layer (chaos harness).

The elastic-lite `--resume` contract (SURVEY §5.3, docs/robustness.md) is
only as good as its behavior under the faults it claims to survive:
preemption mid-write, torn writes that `os.replace` happily commits,
transient filesystem errors, and dead collective peers.  This module gives
tests — and manual runs, via the ``TPUMX_CHAOS`` env var — *seedable,
deterministic* injection points so the recovery path is exercised, not
asserted.

Injection kinds (all one process, no root, no LD_PRELOAD):

- ``crash_after_bytes=N``: the Nth byte written through a chaos-wrapped
  file raises :class:`ChaosCrash` (or, with ``hard=1``, calls
  ``os._exit(137)`` — a true mid-syscall death for subprocess tests).
  One-shot: disarms after firing so the *recovery* save can succeed.
- ``torn_write=N``: only the first N bytes reach the file; the tail is
  silently dropped but reported as written — the classic short-write /
  power-loss tear that size+sha256 manifest verification must catch.
- ``slow_io=S``: every write sleeps a seed-deterministic duration in
  [0, S) seconds (races saves against preemption timers).
- ``transient_oserror=K``: the next K chaos-checked filesystem operations
  raise ``OSError`` (exercises ``checkpoint.retry`` backoff).
- ``kill_peer=1``: ``elastic.barrier`` sees a dead peer and raises
  ``WorkerFailure`` deterministically, without a real 2-process run.
- ``nan_after=N``: the Nth loss observed through :func:`poison_loss` (the
  supervisor's numeric sentinel calls it on every supervised step) comes
  back NaN; ``nan_streak=K`` (default 1) poisons K consecutive losses
  before disarming — set K past the sentinel's skip budget to *provoke*
  the rollback path, not just a skipped batch.
- ``hang_step=N``: the Nth supervised step blocks for ``hang_seconds``
  (default 3600 — "forever" at test scale) before running, simulating a
  stalled collective/compile; the supervisor's hung-step watchdog must
  convert it into a catchable ``WorkerFailure``.  One-shot.
- ``crash_at_step=N``: raise :class:`ChaosCrash` (or ``os._exit(137)``
  with ``hard=1``) immediately AFTER the Nth supervised step *commits* —
  its update applied and its capsule (tpu_mx/resume.py) written — the
  mid-epoch process death the deterministic-resume proof provokes: a
  capsule resume must continue at batch N+1 with the exact RNG stream,
  never re-feeding batch N.  One-shot.
- ``slow_decode_step=N``: the Nth serving *decode* step (counted since
  arming) blocks for ``slow_decode_seconds`` (default 3600 — "forever"
  at test scale) inside the serving engine's watchdog thread, simulating
  a wedged decode dispatch; the server must convert it into a classified
  engine restart with every queued request surviving
  (tpu_mx/serving/server.py, docs/serving.md).  One-shot.
- ``kill9_at_decode_step=N``: ``os._exit(137)`` inside the Nth serving
  decode step since arming — a REAL cross-process death mid-step, no
  emergency save, no atexit.  The committed-token journal
  (tpu_mx/serving/journal.py) is the only thing that survives; the
  recovery run must resume every stream from it with zero lost,
  duplicated, or re-yielded tokens (docs/robustness.md).  One-shot by
  construction (the process is gone).
- ``restart_storm=K``: the next K serving decode steps each raise
  :class:`ChaosCrash` (classified transient) — K *back-to-back*
  engine restarts, the compounding-failure shape the prefill-replay
  recovery path must keep O(1 prefill) per request per restart.
  Decrementing budget, like ``reject_storm``.
- ``reject_storm=K``: the next K scheduler admissions are force-rejected
  with reason ``"reject_storm"`` — drives the front-end's backpressure /
  reject-with-reason path and the client resubmit loop without needing a
  genuinely full queue.
- ``preempt_worker_at_step=N``: SIGTERM this process at the Nth fleet
  step (counted since arming) IF its member rank matches
  ``preempt_rank`` (default 0) — a real preemption for the elastic-fleet
  kill-and-rejoin proof (tpu_mx/parallel/fleet.py calls
  :func:`maybe_preempt` at every step boundary).  One-shot.  The
  existing SIGTERM emergency save handles the save; the fleet
  supervisor (``tools/launch.py --supervise``) handles the restart.
- ``partition_worker=K``: the fleet member with rank K stops writing
  heartbeats WITHOUT dying (:func:`partitioned` returns True for it) —
  a network partition, not a crash: the membership runtime must evict
  it on lease expiry and the zombie must be refused at the next
  generation-tagged barrier.  Counted once, on the first suppressed
  beat.
- ``slow_worker_rank=R`` / ``slow_worker_seconds=S``: the fleet member
  with rank R sleeps S seconds at EVERY train step (deterministic,
  NOT one-shot — persistence is exactly what the fleet's windowed
  straggler detector keys on, tpu_mx/parallel/fleet_obs.py).  The
  compiled train step calls :func:`maybe_slow_worker` inside its
  ``data_wait`` phase window, so the injected delay lands in a
  MEASURED phase and the cross-rank attribution can name it.  Counted
  per fire.
- ``bitflip_grad_rank=R``: the fleet member with rank R gets ONE bit
  flipped in its very next committed optimizer update — the silent
  data corruption a defective chip injects into gradient sync.  The
  flip is applied host-side to the post-update parameter tree (the
  observable effect of a corrupted gradient: a low-order mantissa bit
  of one seeded parameter element), so rank R's state silently
  diverges from its replicas without tripping the numeric sentinel —
  exactly what the cross-replica fingerprint vote
  (tpu_mx/parallel/integrity.py) must detect and attribute.  One-shot.
- ``bitflip_param_at_step=N`` / ``bitflip_rank=R`` (default 0): flip
  one seeded bit in rank R's parameter tree after its Nth committed
  train step since arming — the scheduled variant for seeded SDC-storm
  runs where the detection latency (vote cadence K) is part of the
  assertion.  One-shot.
- ``flaky_recompute=K``: the next K shadow-step recomputes (the
  sampled audit in tpu_mx/parallel/integrity.py, or the serving
  decode self-check) return a perturbed result — flaky hardware that
  computes the same program twice and gets different bits.
  Decrementing budget, like ``reject_storm``.
- ``match=SUBSTR``: scope file-level faults to paths containing SUBSTR
  (e.g. ``match=.params`` tears the params file but not the manifest).

Programmatic use (tests)::

    from tpu_mx.contrib import chaos
    with chaos.enable(crash_after_bytes=100, match=".params", seed=7):
        net_save_that_should_die()

Env use (manual runs; parsed lazily on the first checkpoint write)::

    TPUMX_CHAOS="torn_write=4096,match=.params,seed=7" python train.py

The reference had no fault-injection tier at all — recovery was assumed
(docs/DIVERGENCES.md).  Keeping the harness in-tree, next to the code it
attacks, is the point: every durability claim in ``tpu_mx/checkpoint.py``
has a chaos test that falsifies the naive implementation.
"""
from __future__ import annotations

import contextlib
import logging
import os
import random
import re
import threading
import time

from .. import telemetry as _telemetry
from .. import tracing as _tracing

__all__ = ["ChaosCrash", "enable", "active", "configure_from_env",
           "wrap_file", "maybe_oserror", "peer_killed", "poison_loss",
           "maybe_hang", "maybe_crash_step", "maybe_slow_decode",
           "maybe_kill9_decode", "storm_restart",
           "forced_reject", "maybe_preempt", "partitioned",
           "maybe_slow_worker", "maybe_bitflip", "maybe_flaky_recompute"]


def _count_injection(kind):
    """Every fault actually FIRED lands in the telemetry registry tagged by
    kind — chaos tests assert the *observability* of faults, not just
    survival (ISSUE 3) — and on the flight-recorder timeline with the
    step-scoped trace context, so the injection and the recovery it
    provokes correlate in the black box (docs/observability.md)."""
    _telemetry.counter("chaos.injections", kind=kind).inc()
    _tracing.emit("chaos.inject", kind=kind)

log = logging.getLogger(__name__)


class ChaosCrash(Exception):
    """Simulated process death mid-write (soft mode).

    Deliberately NOT an OSError: ``checkpoint.retry`` must never swallow a
    crash — a real kill would not be retried either.  ``atomic_write``
    recognizes it and leaves the partial tmp file on disk, exactly the
    debris a real crash leaves behind."""


class _Config:
    _KINDS = ("crash_after_bytes", "torn_write", "slow_io",
              "transient_oserror", "kill_peer", "nan_after", "nan_streak",
              "hang_step", "hang_seconds", "crash_at_step",
              "slow_decode_step", "slow_decode_seconds", "reject_storm",
              "kill9_at_decode_step", "restart_storm",
              "preempt_worker_at_step", "preempt_rank", "partition_worker",
              "slow_worker_rank", "slow_worker_seconds",
              "bitflip_grad_rank", "bitflip_param_at_step", "bitflip_rank",
              "flaky_recompute",
              "seed", "hard", "match")

    def __init__(self, crash_after_bytes=None, torn_write=None, slow_io=None,
                 transient_oserror=0, kill_peer=False, nan_after=None,
                 nan_streak=1, hang_step=None, hang_seconds=3600.0,
                 crash_at_step=None, slow_decode_step=None,
                 slow_decode_seconds=3600.0, reject_storm=0,
                 kill9_at_decode_step=None, restart_storm=0,
                 preempt_worker_at_step=None, preempt_rank=0,
                 partition_worker=None, slow_worker_rank=None,
                 slow_worker_seconds=1.0, bitflip_grad_rank=None,
                 bitflip_param_at_step=None, bitflip_rank=0,
                 flaky_recompute=0, seed=None,
                 hard=False, match=None):
        if seed is None:
            seed = int(os.environ.get("TPUMX_CHAOS_SEED", "0"))
        self.crash_after_bytes = crash_after_bytes
        self.torn_write = torn_write
        self.slow_io = slow_io
        self.transient_oserror = int(transient_oserror)
        self.kill_peer = bool(kill_peer)
        self.nan_after = None if nan_after is None else int(nan_after)
        self.nan_streak = max(1, int(nan_streak))
        self.hang_step = None if hang_step is None else int(hang_step)
        self.hang_seconds = float(hang_seconds)
        self.crash_at_step = None if crash_at_step is None \
            else int(crash_at_step)
        self.slow_decode_step = None if slow_decode_step is None \
            else int(slow_decode_step)
        self.slow_decode_seconds = float(slow_decode_seconds)
        self.reject_storm = int(reject_storm)
        self.kill9_at_decode_step = None if kill9_at_decode_step is None \
            else int(kill9_at_decode_step)
        self.restart_storm = int(restart_storm)
        self.preempt_worker_at_step = None if preempt_worker_at_step is None \
            else int(preempt_worker_at_step)
        self.preempt_rank = int(preempt_rank)
        self.partition_worker = None if partition_worker is None \
            else int(partition_worker)
        self.slow_worker_rank = None if slow_worker_rank is None \
            else int(slow_worker_rank)
        self.slow_worker_seconds = float(slow_worker_seconds)
        self.bitflip_grad_rank = None if bitflip_grad_rank is None \
            else int(bitflip_grad_rank)
        self.bitflip_param_at_step = None if bitflip_param_at_step is None \
            else int(bitflip_param_at_step)
        self.bitflip_rank = int(bitflip_rank)
        self.flaky_recompute = int(flaky_recompute)
        self.seed = seed
        self.hard = bool(hard)
        self.match = match
        self.rng = random.Random(seed)
        self.lock = threading.Lock()
        # mutable counters (under lock)
        self.bytes_written = 0       # cumulative across matched writes
        self.oserrors_left = self.transient_oserror
        self.crashes = 0             # how many times a fault actually fired
        self.tears = 0
        self.oserrors_fired = 0
        self.losses_seen = 0         # losses observed while nan_after armed
        self.steps_seen = 0          # steps observed while hang_step armed
        self.commits_seen = 0        # committed steps while crash_at_step armed
        self.nans_fired = 0
        self.hangs = 0
        self.step_crashes = 0
        self.decode_steps_seen = 0   # decode steps while slow_decode armed
        self.slow_decodes = 0
        self.kill9_steps_seen = 0    # decode steps while kill9 armed
        self.storms_left = self.restart_storm
        self.storms_fired = 0        # back-to-back restarts provoked
        self.rejects_left = self.reject_storm
        self.rejects_forced = 0
        self.fleet_steps_seen = 0    # fleet steps while preempt armed
        self.preempts = 0
        self.partitions = 0          # heartbeats suppressed by partition
        self.slow_worker_fires = 0   # per-step straggler delays injected
        self.bitflip_commits_seen = 0  # commits while bitflip_param armed
        self.bitflips = 0            # parameter bits actually flipped
        self.flaky_left = self.flaky_recompute
        self.flaky_fired = 0         # shadow recomputes perturbed

    def matches(self, path):
        return self.match is None or (path is not None
                                      and self.match in str(path))

    def __repr__(self):
        on = {k: getattr(self, k) for k in self._KINDS
              if getattr(self, k) not in (None, 0, False)}
        return f"ChaosConfig({on})"


_config = None
_env_parsed = False


def active():
    """The currently-enabled chaos config, or None (the common case)."""
    return _config


@contextlib.contextmanager
def enable(**kwargs):
    """Enable chaos for the dynamic extent of the with-block (tests).

    Nesting replaces the outer config for the inner block.  Yields the
    config object so tests can assert on fire counters
    (``cfg.crashes``, ``cfg.tears``, ``cfg.oserrors_fired``)."""
    global _config
    prev = _config
    cfg = _Config(**kwargs)
    _config = cfg
    try:
        yield cfg
    finally:
        _config = prev


def configure_from_env():
    """Arm chaos from ``TPUMX_CHAOS`` (comma/space-separated k=v pairs).

    Called lazily by the first durability-layer operation; a programmatic
    `enable()` always wins over the env, and the env is parsed at most
    once per process."""
    global _config, _env_parsed
    if _env_parsed or _config is not None:
        return _config
    _env_parsed = True
    spec = os.environ.get("TPUMX_CHAOS")
    if not spec:
        return None
    kwargs = {}
    for part in re.split(r"[,\s]+", spec.strip()):
        if not part:
            continue
        key, _, val = part.partition("=")
        if key not in _Config._KINDS:
            log.warning("TPUMX_CHAOS: unknown knob %r ignored "
                        "(known: %s)", key, ", ".join(_Config._KINDS))
            continue
        if key == "match":
            kwargs[key] = val
        elif key in ("slow_io", "hang_seconds", "slow_decode_seconds",
                     "slow_worker_seconds"):
            kwargs[key] = float(val)
        elif key in ("kill_peer", "hard"):
            kwargs[key] = val in ("", "1", "true", "yes", "on")
        else:
            kwargs[key] = int(val)
    _config = _Config(**kwargs)
    log.warning("chaos armed from TPUMX_CHAOS: %r", _config)
    return _config


# ---------------------------------------------------------------------------
# injection points (called by tpu_mx/checkpoint.py and tpu_mx/elastic.py)
# ---------------------------------------------------------------------------
class _ChaosFile:
    """File proxy that applies byte-level faults to .write().

    Wraps the *real* (innermost) file object: the durability layer's
    sha256-of-intended-bytes accounting sits above this wrapper, so a torn
    write records the digest the caller *meant* — which is exactly what
    lets manifest verification flag the tear."""

    def __init__(self, f, cfg, path):
        self._f = f
        self._cfg = cfg
        self._path = path

    def _partial(self, data, allowed):
        """First `allowed` BYTES of `data`, in the underlying file's type.
        Text mode: slice the utf-8 encoding so the fault boundary is a true
        byte offset even for multi-byte characters (a split character's
        partial bytes are dropped — the nearest char boundary at-or-before
        the cut, deterministic for a given payload)."""
        if isinstance(data, str):
            return data.encode("utf-8")[:allowed].decode("utf-8", "ignore")
        return data[:allowed]

    def write(self, data):
        cfg = self._cfg
        if isinstance(data, str):
            nbytes = len(data.encode("utf-8"))
        else:
            nbytes = memoryview(data).nbytes
        with cfg.lock:
            if cfg.slow_io:
                _count_injection("slow_io")
                time.sleep(cfg.rng.uniform(0.0, float(cfg.slow_io)))
            start = cfg.bytes_written
            if (cfg.crash_after_bytes is not None
                    and start + nbytes >= cfg.crash_after_bytes):
                allowed = max(0, cfg.crash_after_bytes - start)
                self._f.write(self._partial(data, allowed))
                self._f.flush()
                cfg.bytes_written += allowed
                cfg.crash_after_bytes = None  # one-shot: recovery may save
                cfg.crashes += 1
                _count_injection("crash")
                if cfg.hard:  # pragma: no cover - exercised via subprocess
                    os._exit(137)
                raise ChaosCrash(
                    f"chaos: simulated crash after {cfg.bytes_written} bytes "
                    f"into {self._path}")
            if cfg.torn_write is not None:
                allowed = max(0, cfg.torn_write - start)
                if allowed < nbytes:
                    cfg.tears += 1
                    _count_injection("torn_write")
                self._f.write(self._partial(data, allowed))
                # the caller is told the whole write landed — that is the tear
                cfg.bytes_written += nbytes
                return len(data)
            cfg.bytes_written += nbytes
        self._f.write(data)
        return len(data)

    def __getattr__(self, name):  # flush/fileno/close/seek/tell/...
        return getattr(self._f, name)


def wrap_file(f, path=None):
    """Wrap a writable file object with the active byte-level faults.

    Returns `f` unchanged when chaos is off, no byte-level fault is armed,
    or `path` does not match the config's ``match`` filter."""
    cfg = _config
    if cfg is None or not cfg.matches(path):
        return f
    if (cfg.crash_after_bytes is None and cfg.torn_write is None
            and not cfg.slow_io):
        return f
    return _ChaosFile(f, cfg, path)


def maybe_oserror(op="io", path=None):
    """Raise a transient OSError if the fault budget says so (else no-op)."""
    cfg = _config
    if cfg is None or not cfg.matches(path):
        return
    with cfg.lock:
        if cfg.oserrors_left > 0:
            cfg.oserrors_left -= 1
            cfg.oserrors_fired += 1
            _count_injection("transient_oserror")
            raise OSError(
                f"chaos: transient {op} failure on {path or '<fs>'} "
                f"({cfg.oserrors_left} more armed)")


def peer_killed():
    """True when `kill_peer` chaos is armed (elastic.barrier checks this)."""
    cfg = _config
    if cfg is not None and cfg.kill_peer:
        _count_injection("kill_peer")
        return True
    return False


def poison_loss(value):
    """Return `value`, or NaN when the ``nan_after`` fault says this loss is
    poisoned (the supervisor's numeric sentinel routes every observed loss
    through here).  Counting starts when the fault is armed: the Nth loss
    seen *since arming* — and the next ``nan_streak - 1`` after it — come
    back NaN; the fault then disarms so recovery can converge."""
    cfg = _config
    if cfg is None or cfg.nan_after is None:
        return value
    with cfg.lock:
        if cfg.nan_after is None:
            return value
        cfg.losses_seen += 1
        if cfg.losses_seen >= cfg.nan_after:
            cfg.nans_fired += 1
            _count_injection("nan")
            if cfg.losses_seen >= cfg.nan_after + cfg.nan_streak - 1:
                cfg.nan_after = None  # streak complete: disarm
            return float("nan")
    return value


def maybe_crash_step():
    """Raise :class:`ChaosCrash` after the Nth supervised step COMMITS —
    the supervisor calls this right after a step's update and its capsule
    write have both landed (``crash_at_step``).  Counting starts when the
    fault is armed; one-shot, so the recovered run completes.  With
    ``hard=1`` it is ``os._exit(137)`` — a true mid-epoch process death
    for the cross-process deterministic-resume proof."""
    cfg = _config
    if cfg is None or cfg.crash_at_step is None:
        return
    with cfg.lock:
        if cfg.crash_at_step is None:
            return
        cfg.commits_seen += 1
        if cfg.commits_seen < cfg.crash_at_step:
            return
        cfg.crash_at_step = None  # one-shot: the resumed run finishes
        cfg.step_crashes += 1
        _count_injection("crash_step")
        if cfg.hard:  # pragma: no cover - exercised via subprocess
            os._exit(137)
    raise ChaosCrash(
        "chaos: simulated process death after supervised step "
        f"{cfg.commits_seen} committed (crash_at_step fired) — resume "
        "must continue at the NEXT batch with the exact RNG stream")


def maybe_slow_decode():
    """Block for ``slow_decode_seconds`` when the ``slow_decode_step``
    fault says this is the wedged decode step (the serving engine calls
    this at the top of every decode step, INSIDE the server's watchdog
    thread — the sleep simulates a stalled decode dispatch the server
    must convert into a classified engine restart with zero lost
    requests, docs/serving.md).  One-shot; counting starts when armed."""
    cfg = _config
    if cfg is None or cfg.slow_decode_step is None:
        return
    secs = None
    with cfg.lock:
        if cfg.slow_decode_step is None:
            return
        cfg.decode_steps_seen += 1
        if cfg.decode_steps_seen >= cfg.slow_decode_step:
            cfg.slow_decode_step = None  # one-shot: the retried step runs
            cfg.slow_decodes += 1
            _count_injection("slow_decode_step")
            secs = cfg.slow_decode_seconds
    if secs:
        log.warning("chaos: stalling this decode step for %.0fs "
                    "(slow_decode_step fired)", secs)
        time.sleep(secs)


def maybe_kill9_decode():
    """``os._exit(137)`` when ``kill9_at_decode_step`` says the Nth
    serving decode step since arming has arrived (the serving engine
    calls this at the top of every decode step, right after
    :func:`maybe_slow_decode`).  A TRUE mid-step process death — no
    exception, no emergency save, no atexit — for the cross-process
    journal-recovery proof (tpu_mx/serving/journal.py): everything not
    already fsync'd is gone, exactly like a real kill −9."""
    cfg = _config
    if cfg is None or cfg.kill9_at_decode_step is None:
        return
    with cfg.lock:
        if cfg.kill9_at_decode_step is None:
            return
        cfg.kill9_steps_seen += 1
        if cfg.kill9_steps_seen < cfg.kill9_at_decode_step:
            return
        cfg.kill9_at_decode_step = None
        _count_injection("kill9_decode")
    log.warning("chaos: killing this process inside decode step %d "
                "(kill9_at_decode_step fired)", cfg.kill9_steps_seen)
    _telemetry.flush()   # the injection count must outlive the process
    os._exit(137)  # pragma: no cover - exercised via subprocess


def storm_restart():
    """Raise :class:`ChaosCrash` (classified transient — a guaranteed
    engine restart) once per serving decode step while the
    ``restart_storm`` budget lasts: K back-to-back restarts, the
    compounding shape the prefill-replay recovery path must keep flat.
    Decrementing budget like ``reject_storm``; the (K+1)th decode step
    runs clean so the storm drains."""
    cfg = _config
    if cfg is None or not cfg.restart_storm:
        return
    with cfg.lock:
        if cfg.storms_left <= 0:
            return
        cfg.storms_left -= 1
        cfg.storms_fired += 1
        _count_injection("restart_storm")
        n = cfg.storms_fired
    raise ChaosCrash(
        f"chaos: restart_storm fired ({n}/{cfg.restart_storm}) — "
        f"classified engine restart, every stream must replay in "
        f"one prefill")


def forced_reject():
    """True when the ``reject_storm`` fault says this admission must be
    rejected (the scheduler checks it before its real admission logic and
    rejects with reason ``"reject_storm"``).  Decrements the storm budget;
    returns False once exhausted so resubmitted requests get through."""
    cfg = _config
    if cfg is None or not cfg.reject_storm:
        return False
    with cfg.lock:
        if cfg.rejects_left > 0:
            cfg.rejects_left -= 1
            cfg.rejects_forced += 1
            _count_injection("reject_storm")
            return True
    return False


def maybe_hang():
    """Block for ``hang_seconds`` when the ``hang_step`` fault says this is
    the hung step (the supervisor calls this at the top of every supervised
    step, INSIDE the watchdog thread — the sleep simulates a stalled
    collective/compile the hung-step watchdog must convert into a
    ``WorkerFailure``).  One-shot; counting starts when armed."""
    cfg = _config
    if cfg is None or cfg.hang_step is None:
        return
    secs = None
    with cfg.lock:
        if cfg.hang_step is None:
            return
        cfg.steps_seen += 1
        if cfg.steps_seen >= cfg.hang_step:
            cfg.hang_step = None  # one-shot: the retried step runs clean
            cfg.hangs += 1
            _count_injection("hang")
            secs = cfg.hang_seconds
    if secs:
        log.warning("chaos: hanging this step for %.0fs (hang_step fired)",
                    secs)
        time.sleep(secs)


def maybe_preempt(rank):
    """SIGTERM this process when ``preempt_worker_at_step`` says the Nth
    fleet step has arrived and `rank` matches ``preempt_rank`` (the fleet
    runtime calls this at every step boundary with the worker's member
    rank).  A REAL signal, not an exception: the existing SIGTERM
    emergency-save path runs, the process dies, and the fleet supervisor
    must detect the loss, reshard the survivors, and restart the worker.
    Counting starts when armed; steps are counted only on the matching
    rank so ``preempt_worker_at_step=N`` means "rank ``preempt_rank``'s
    Nth step", whichever processes share the config.  One-shot."""
    cfg = configure_from_env()  # fleet workers may have no supervisor
    if cfg is None or cfg.preempt_worker_at_step is None:
        return
    with cfg.lock:
        if cfg.preempt_worker_at_step is None:
            return
        if rank is None or int(rank) != cfg.preempt_rank:
            return
        cfg.fleet_steps_seen += 1
        if cfg.fleet_steps_seen < cfg.preempt_worker_at_step:
            return
        cfg.preempt_worker_at_step = None  # one-shot: the restart survives
        cfg.preempts += 1
        _count_injection("preempt_worker")
    import signal
    log.warning("chaos: preempting rank %s at fleet step %d "
                "(preempt_worker_at_step fired)", rank, cfg.fleet_steps_seen)
    os.kill(os.getpid(), signal.SIGTERM)


def maybe_slow_worker(rank=None):
    """Sleep ``slow_worker_seconds`` when ``slow_worker_rank`` says this
    process is the injected straggler (the compiled train step calls
    this at the top of every step, INSIDE its ``data_wait`` phase
    window — the delay lands in a measured phase so cross-rank
    attribution, tpu_mx/parallel/fleet_obs.py, can name both the rank
    and the phase).  Deterministic and NOT one-shot: the windowed
    persistent-straggler detector keys on the delay repeating.  `rank`
    defaults to the ``TPUMX_FLEET_MEMBER`` env rank — fleet workers
    know their member slot before any Fleet object exists."""
    cfg = configure_from_env()  # fleet workers may have no supervisor
    if cfg is None or cfg.slow_worker_rank is None:
        return
    if rank is None:
        rank = os.environ.get("TPUMX_FLEET_MEMBER")
    if rank is None or int(rank) != cfg.slow_worker_rank:
        return
    with cfg.lock:
        if cfg.slow_worker_rank is None:
            return
        cfg.slow_worker_fires += 1
        _count_injection("slow_worker")
        secs = cfg.slow_worker_seconds
    time.sleep(secs)


def maybe_bitflip(rank=None):
    """Return the mantissa bit (0–22) to flip in this rank's parameter
    tree, or None.  The compiled train step calls this right after each
    step COMMITS; a non-None return means one of the SDC knobs fired:

    - ``bitflip_grad_rank=R``: rank R's next committed update is
      corrupted (one-shot) — the flip lands immediately after the
      post-sync state the replicas are supposed to agree on, so the
      cross-replica fingerprint vote must name rank R.
    - ``bitflip_param_at_step=N`` (+ ``bitflip_rank``, default 0): the
      scheduled variant — fires after the matching rank's Nth committed
      step since arming (one-shot).

    The bit index is drawn from the seeded chaos RNG so a red run
    reproduces; `rank` defaults to the ``TPUMX_FLEET_MEMBER`` env rank
    like :func:`maybe_slow_worker`."""
    cfg = configure_from_env()  # fleet workers may have no supervisor
    if cfg is None or (cfg.bitflip_grad_rank is None
                       and cfg.bitflip_param_at_step is None):
        return None
    if rank is None:
        rank = os.environ.get("TPUMX_FLEET_MEMBER", 0)
    rank = int(rank)
    with cfg.lock:
        if cfg.bitflip_grad_rank is not None \
                and rank == cfg.bitflip_grad_rank:
            cfg.bitflip_grad_rank = None  # one-shot
            cfg.bitflips += 1
            _count_injection("bitflip_grad")
            return cfg.rng.randrange(23)
        if cfg.bitflip_param_at_step is not None \
                and rank == cfg.bitflip_rank:
            cfg.bitflip_commits_seen += 1
            if cfg.bitflip_commits_seen >= cfg.bitflip_param_at_step:
                cfg.bitflip_param_at_step = None  # one-shot
                cfg.bitflips += 1
                _count_injection("bitflip_param")
                return cfg.rng.randrange(23)
    return None


def maybe_flaky_recompute():
    """True when the ``flaky_recompute`` budget says this shadow
    recompute must come back with different bits (the sampled audit in
    tpu_mx/parallel/integrity.py — and the serving decode self-check —
    call this on every recompute).  Flaky hardware by construction: the
    program is deterministic, so only a faulty chip can make two runs
    disagree, and that is exactly what the caller simulates when this
    returns True.  Decrementing budget like ``reject_storm``."""
    cfg = configure_from_env()
    if cfg is None or not cfg.flaky_recompute:
        return False
    with cfg.lock:
        if cfg.flaky_left > 0:
            cfg.flaky_left -= 1
            cfg.flaky_fired += 1
            _count_injection("flaky_recompute")
            return True
    return False


def partitioned(rank):
    """True when the ``partition_worker`` fault says member `rank` is
    network-partitioned: the fleet runtime suppresses its heartbeat writes
    (the process stays alive — that is the point: lease expiry, not exit
    codes, must evict it).  Counted in ``injections{kind=partition_worker}``
    once, on the first suppressed beat; stays armed until the config is
    torn down so the zombie keeps missing beats."""
    cfg = configure_from_env()  # fleet workers may have no supervisor
    if cfg is None or cfg.partition_worker is None:
        return False
    if rank is None or int(rank) != cfg.partition_worker:
        return False
    with cfg.lock:
        if cfg.partition_worker is None:
            return False
        cfg.partitions += 1
        if cfg.partitions == 1:
            _count_injection("partition_worker")
    return True
