"""ONNX export/import for Symbol graphs, self-contained
(REF:python/mxnet/contrib/onnx/{mx2onnx,onnx2mx} — the reference delegated
serialization to the `onnx` package; this environment has none, so the
ONNX protobuf wire format is written/read directly via contrib._protobuf).

Covered op set: the model-zoo CNN surface — Convolution, BatchNorm,
Activation, LeakyReLU, Pooling (incl. global), FullyConnected, Flatten,
reshape, transpose, Concat, broadcast add/sub/mul/div, add_n, softmax,
SoftmaxOutput, Dropout, Embedding.  Opset 13, default domain.

    from tpu_mx.contrib import onnx as onnx_mxnet
    onnx_mxnet.export_model(sym, params, [(1, 3, 224, 224)], "net.onnx")
    sym2, arg2, aux2 = onnx_mxnet.import_model("net.onnx")

StableHLO (`HybridBlock.export`) remains the full-fidelity deployment
artifact; ONNX is the interchange format for the graph-level op subset.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..ndarray import NDArray
from ._protobuf import Msg, decode, decode_packed_ints

__all__ = ["export_model", "import_model", "get_model_metadata"]

# TensorProto.DataType
_DT_FLOAT, _DT_INT32, _DT_INT64 = 1, 6, 7
_NP2ONNX = {np.dtype(np.float32): _DT_FLOAT, np.dtype(np.int32): _DT_INT32,
            np.dtype(np.int64): _DT_INT64}
_ONNX2NP = {v: k for k, v in _NP2ONNX.items()}
# AttributeProto.AttributeType
_AT_FLOAT, _AT_INT, _AT_STRING, _AT_TENSOR, _AT_INTS = 1, 2, 3, 4, 7


# ---------------------------------------------------------------------------
# proto builders
# ---------------------------------------------------------------------------
def _tensor(name, arr):
    arr = np.ascontiguousarray(arr)
    if arr.dtype not in _NP2ONNX:
        arr = arr.astype(np.float32)
    m = Msg()
    m.ints(1, arr.shape)                       # dims
    m.int(2, _NP2ONNX[arr.dtype])              # data_type
    m.bytes(8, name)                           # name
    m.bytes(9, arr.tobytes())                  # raw_data
    return m


def _value_info(name, shape, elem_type=_DT_FLOAT):
    shp = Msg()
    for d in shape:
        shp.bytes(1, Msg().int(1, int(d)))     # dim { dim_value }
    ttype = Msg().int(1, elem_type).bytes(2, shp)
    return Msg().bytes(1, name).bytes(2, Msg().bytes(1, ttype))


def _attr(name, value):
    m = Msg().bytes(1, name)
    if isinstance(value, float):
        m.float(2, value).int(20, _AT_FLOAT)
    elif isinstance(value, (bool, int, np.integer)):
        m.int(3, int(value)).int(20, _AT_INT)
    elif isinstance(value, str):
        m.bytes(4, value).int(20, _AT_STRING)
    elif isinstance(value, (list, tuple)):
        m.ints(8, value).int(20, _AT_INTS)
    else:
        raise MXNetError(f"unsupported attribute value {value!r}")
    return m


def _node(op_type, inputs, outputs, name, **attrs):
    m = Msg()
    for i in inputs:
        m.bytes(1, i)
    for o in outputs:
        m.bytes(2, o)
    m.bytes(3, name)
    m.bytes(4, op_type)
    for k, v in attrs.items():
        m.bytes(5, _attr(k, v))
    return m


# ---------------------------------------------------------------------------
# export: Symbol graph -> ONNX bytes
# ---------------------------------------------------------------------------
def _pair(v, default=1):
    if v is None:
        return None
    return [int(x) for x in (v if isinstance(v, (list, tuple)) else (v, v))]


class _Exporter:
    def __init__(self):
        self.nodes = []        # NodeProto Msgs
        self.extra_inits = []  # TensorProto Msgs synthesized by converters
        self.counter = 0

    def fresh(self, hint):
        self.counter += 1
        return f"_onnx_{hint}_{self.counter}"

    def const(self, hint, arr):
        name = self.fresh(hint)
        self.extra_inits.append(_tensor(name, np.asarray(arr)))
        return name

    def emit(self, op_type, inputs, outputs, name, **attrs):
        self.nodes.append(_node(op_type, inputs, outputs, name, **attrs))


def _conv_attrs(kw):
    kernel = _pair(kw.get("kernel"))
    attrs = {"kernel_shape": kernel}
    s = _pair(kw.get("stride"))
    if s:
        attrs["strides"] = s
    d = _pair(kw.get("dilate"))
    if d:
        attrs["dilations"] = d
    p = _pair(kw.get("pad"))
    if p:
        attrs["pads"] = p + p                  # symmetric begin+end
    g = int(kw.get("num_group", 1) or 1)
    if g != 1:
        attrs["group"] = g
    return attrs


def _cv_convolution(ex, node, ins, outs):
    ex.emit("Conv", ins, outs, node.name, **_conv_attrs(node.kwargs))


def _cv_fullyconnected(ex, node, ins, outs):
    data, w = ins[0], ins[1]
    bias = ins[2] if len(ins) > 2 else None
    if node.kwargs.get("flatten", True):
        flat = ex.fresh("flat")
        ex.emit("Flatten", [data], [flat], ex.fresh("Flatten"), axis=1)
        gemm_in = [flat, w] + ([bias] if bias else [])
        ex.emit("Gemm", gemm_in, outs, node.name, transB=1)
    else:
        wt = ex.fresh("wT")
        ex.emit("Transpose", [w], [wt], ex.fresh("Transpose"), perm=[1, 0])
        mm = ex.fresh("mm") if bias else outs[0]
        ex.emit("MatMul", [data, wt], [mm], ex.fresh("MatMul"))
        if bias:
            ex.emit("Add", [mm, bias], outs, node.name)


_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
        "softrelu": "Softplus", "softsign": "Softsign"}


def _cv_activation(ex, node, ins, outs):
    act = node.kwargs.get("act_type", "relu")
    if act not in _ACT:
        raise MXNetError(f"ONNX export: unsupported act_type {act!r}")
    ex.emit(_ACT[act], ins, outs, node.name)


def _cv_leakyrelu(ex, node, ins, outs):
    ex.emit("LeakyRelu", ins, outs, node.name,
            alpha=float(node.kwargs.get("slope", 0.25)))


def _cv_batchnorm(ex, node, ins, outs):
    # mxnet input order (data, gamma, beta, moving_mean, moving_var) matches
    # ONNX (X, scale, B, input_mean, input_var); fix_gamma is baked in by
    # the export loop (gamma replaced with a ones initializer)
    ex.emit("BatchNormalization", ins, outs, node.name,
            epsilon=float(node.kwargs.get("eps", 1e-5)),
            momentum=float(node.kwargs.get("momentum", 0.9)))


def _cv_pooling(ex, node, ins, outs):
    kw = node.kwargs
    ptype = kw.get("pool_type", "max")
    if ptype not in ("max", "avg"):
        raise MXNetError(f"ONNX export: unsupported pool_type {ptype!r}")
    if kw.get("global_pool"):
        ex.emit("GlobalMaxPool" if ptype == "max" else "GlobalAveragePool",
                ins, outs, node.name)
        return
    attrs = {"kernel_shape": _pair(kw.get("kernel"))}
    s = _pair(kw.get("stride"))
    if s:
        attrs["strides"] = s
    p = _pair(kw.get("pad"))
    if p:
        attrs["pads"] = p + p
    if ptype == "avg":
        attrs["count_include_pad"] = int(bool(kw.get("count_include_pad",
                                                     True)))
    ex.emit("MaxPool" if ptype == "max" else "AveragePool", ins, outs,
            node.name, **attrs)


def _cv_reshape(ex, node, ins, outs):
    shape = ex.const("shape", np.asarray(node.kwargs["shape"], np.int64))
    ex.emit("Reshape", [ins[0], shape], outs, node.name)


def _cv_dropout(ex, node, ins, outs):
    ratio = ex.const("ratio", np.asarray(node.kwargs.get("p", 0.5),
                                         np.float32))
    ex.emit("Dropout", [ins[0], ratio], outs, node.name)


def _cv_embedding(ex, node, ins, outs):
    # mxnet Embedding(data, weight); ONNX Gather(data=weight, indices)
    ex.emit("Gather", [ins[1], ins[0]], outs, node.name, axis=0)


_SIMPLE = {
    "Flatten": ("Flatten", {"axis": 1}), "flatten": ("Flatten", {"axis": 1}),
    "broadcast_add": ("Add", {}), "elemwise_add": ("Add", {}),
    "broadcast_sub": ("Sub", {}), "broadcast_mul": ("Mul", {}),
    "broadcast_div": ("Div", {}), "add_n": ("Sum", {}),
    "relu": ("Relu", {}), "sigmoid": ("Sigmoid", {}), "tanh": ("Tanh", {}),
}

_CONVERTERS = {
    "Convolution": _cv_convolution,
    "FullyConnected": _cv_fullyconnected,
    "Activation": _cv_activation,
    "LeakyReLU": _cv_leakyrelu,
    "BatchNorm": _cv_batchnorm,
    "Pooling": _cv_pooling,
    "reshape": _cv_reshape,
    "Reshape": _cv_reshape,
    "Dropout": _cv_dropout,
    "Embedding": _cv_embedding,
}


def _cv_transpose(ex, node, ins, outs):
    axes = node.kwargs.get("axes")
    ex.emit("Transpose", ins, outs, node.name,
            **({"perm": [int(a) for a in axes]} if axes else {}))


def _cv_concat(ex, node, ins, outs):
    ex.emit("Concat", ins, outs, node.name,
            axis=int(node.kwargs.get("dim", 1)))


def _cv_softmax(ex, node, ins, outs):
    ex.emit("Softmax", [ins[0]], outs, node.name,
            axis=int(node.kwargs.get("axis", -1)))


_CONVERTERS.update({
    "transpose": _cv_transpose, "Concat": _cv_concat, "concat": _cv_concat,
    "softmax": _cv_softmax, "SoftmaxOutput": _cv_softmax,
})


def export_model(sym, params, input_shapes=None, onnx_file_path="model.onnx",
                 input_dtypes=None, opset=13):
    """Serialize a Symbol graph + params to an ONNX file.

    sym — tpu_mx Symbol (single- or multi-output)
    params — {name: NDArray|ndarray} for every parameter/aux variable
    input_shapes — [(shape…)] for the remaining (data) variables, in
        list_arguments order, or {name: shape}
    input_dtypes — matching dtypes (list or {name: dtype}); default
        float32 — int inputs (token ids) MUST declare int32/int64 or
        foreign runtimes will reject the feed
    Returns the path written.  Raises MXNetError on unsupported ops."""
    from ..symbol.symbol import _topo

    params = {k: (v.asnumpy() if isinstance(v, NDArray) else np.asarray(v))
              for k, v in (params or {}).items()}
    data_names = [n for n in sym.list_inputs() if n not in params]
    if isinstance(input_shapes, dict):
        shape_map = dict(input_shapes)
    else:
        shape_map = dict(zip(data_names, input_shapes or []))
    missing = [n for n in data_names if n not in shape_map]
    if missing:
        raise MXNetError(f"ONNX export: missing input shapes for {missing}")
    if isinstance(input_dtypes, dict):
        dtype_map = dict(input_dtypes)
    else:
        dtype_map = dict(zip(data_names, input_dtypes or []))

    def elem_type_of(name):
        dt = np.dtype(dtype_map.get(name, np.float32))
        if dt not in _NP2ONNX:
            raise MXNetError(f"ONNX export: unsupported input dtype {dt} "
                             f"for {name!r}")
        return _NP2ONNX[dt]

    ex = _Exporter()
    order = _topo(sym._entries)
    out_of = {}                       # id(node) -> [output value names]
    inits, graph_inputs = [], []
    for node in order:
        if node.is_variable():
            out_of[id(node)] = [node.name]
            if node.name in params:
                arr = params[node.name]
                inits.append(_tensor(node.name, arr))
            else:
                graph_inputs.append(_value_info(node.name,
                                                shape_map[node.name],
                                                elem_type_of(node.name)))
            continue
        if node.num_outputs != 1:
            raise MXNetError(
                f"ONNX export: multi-output op {node.op} unsupported")
        ins = [out_of[id(c)][i] for c, i in node.inputs]
        outs = [node.name + "_output"]
        out_of[id(node)] = outs
        cv = _CONVERTERS.get(node.op)
        if cv is not None:
            # fix_gamma needs the gamma shape: synthesize ones lazily here
            if node.op == "BatchNorm" and node.kwargs.get("fix_gamma", True):
                gname = ins[1]
                garr = params.get(gname)
                if garr is not None:
                    ins = list(ins)
                    ins[1] = ex.const("fixed_gamma", np.ones_like(garr))
            cv(ex, node, ins, outs)
        elif node.op in _SIMPLE:
            op_type, attrs = _SIMPLE[node.op]
            ex.emit(op_type, ins, outs, node.name, **attrs)
        else:
            raise MXNetError(f"ONNX export: unsupported op {node.op!r} "
                             f"(node {node.name})")

    graph = Msg()
    for n in ex.nodes:
        graph.bytes(1, n)
    graph.bytes(2, "tpu_mx")
    for t in inits + ex.extra_inits:
        graph.bytes(5, t)
    for vi in graph_inputs:
        graph.bytes(11, vi)
    for node, idx in sym._entries:
        nm = node.name if node.is_variable() else node.name + "_output"
        graph.bytes(12, _value_info(nm, ()))   # shape left unspecified

    model = Msg()
    model.int(1, 8)                            # ir_version
    model.bytes(2, "tpu_mx")                   # producer_name
    model.bytes(3, "3.0")                      # producer_version
    model.bytes(7, graph)
    model.bytes(8, Msg().bytes(1, "").int(2, opset))  # opset_import
    from ..checkpoint import atomic_write
    with atomic_write(onnx_file_path) as f:
        f.write(model.tobytes())
    return onnx_file_path


# ---------------------------------------------------------------------------
# import: ONNX bytes -> (Symbol, arg_params, aux_params)
# ---------------------------------------------------------------------------
def _parse_tensor(raw):
    f = decode(raw)
    dims = decode_packed_ints(f.get(1, []))
    dtype = _ONNX2NP.get(f.get(2, [_DT_FLOAT])[0], np.dtype(np.float32))
    name = f.get(8, [b""])[0].decode()
    if 9 in f:
        arr = np.frombuffer(f[9][0], dtype=dtype).reshape(dims).copy()
    elif 4 in f:                              # float_data (packed or not)
        vals = []
        for v in f[4]:
            if isinstance(v, (bytes, bytearray)):
                vals.extend(np.frombuffer(v, np.float32))
            else:
                vals.append(v)
        arr = np.asarray(vals, np.float32).reshape(dims)
    elif 7 in f:
        arr = np.asarray(decode_packed_ints(f[7]), np.int64).reshape(dims)
    else:
        arr = np.zeros(dims, dtype)
    return name, arr


def _parse_attrs(raws):
    out = {}
    for raw in raws:
        f = decode(raw)
        name = f[1][0].decode()
        atype = f.get(20, [0])[0]
        if atype == _AT_FLOAT:
            out[name] = f[2][0]
        elif atype == _AT_INT:
            v = f[3][0]
            out[name] = v - (1 << 64) if v >= 1 << 63 else v
        elif atype == _AT_STRING:
            out[name] = f[4][0].decode()
        elif atype == _AT_INTS:
            out[name] = decode_packed_ints(f.get(8, []))
        elif atype == _AT_TENSOR:
            out[name] = _parse_tensor(f[5][0])[1]
    return out


def _sym_pads(attrs, nd=2):
    p = attrs.get("pads")
    if not p:
        return None
    begin, end = p[:nd], p[nd:]
    if list(begin) != list(end):
        raise MXNetError(f"ONNX import: asymmetric pads {p} unsupported")
    return tuple(begin)


def import_model(model_file):
    """Load an ONNX file into (sym, arg_params, aux_params) — the
    reference's contrib.onnx.import_model contract."""
    import tpu_mx.symbol as S

    with open(model_file, "rb") as f:
        model = decode(f.read())
    graph = decode(model[7][0])
    inits = dict(_parse_tensor(t) for t in graph.get(5, []))
    values = {}                                # value name -> Symbol
    aux_names = set()
    for vi_raw in graph.get(11, []):           # graph inputs
        name = decode(vi_raw)[1][0].decode()
        if name not in inits:
            values[name] = S.Variable(name)

    def sym_of(name):
        if name not in values:
            values[name] = S.Variable(name)
        return values[name]

    for node_raw in graph.get(1, []):
        f = decode(node_raw)
        ins = [b.decode() for b in f.get(1, [])]
        outs = [b.decode() for b in f.get(2, [])]
        name = f.get(3, [b""])[0].decode() or None
        op = f[4][0].decode()
        attrs = _parse_attrs(f.get(5, []))
        out = _import_node(S, op, ins, outs, name, attrs, inits, sym_of,
                           aux_names)
        values[outs[0]] = out

    entries = []
    for vi_raw in graph.get(12, []):
        name = decode(vi_raw)[1][0].decode()
        entries.append(values[name])
    sym = entries[0] if len(entries) == 1 else S.Group(entries)
    used = set(sym.list_inputs())
    arg_params = {k: NDArray(np.asarray(v)) for k, v in inits.items()
                  if k in used and k not in aux_names}
    aux_params = {k: NDArray(np.asarray(v)) for k, v in inits.items()
                  if k in used and k in aux_names}
    return sym, arg_params, aux_params


def _import_node(S, op, ins, outs, name, attrs, inits, sym_of, aux_names):
    def kernel_kwargs(nd=2):
        kw = {}
        if "kernel_shape" in attrs:
            kw["kernel"] = tuple(attrs["kernel_shape"])
        if attrs.get("strides"):
            kw["stride"] = tuple(attrs["strides"])
        if attrs.get("dilations"):
            kw["dilate"] = tuple(attrs["dilations"])
        p = _sym_pads(attrs, nd)
        if p:
            kw["pad"] = p
        return kw

    if op == "Conv":
        w = inits.get(ins[1])
        if w is None:
            raise MXNetError("ONNX import: Conv weight must be initializer")
        kw = kernel_kwargs(len(w.shape) - 2)
        kw["num_filter"] = int(w.shape[0])
        kw["num_group"] = int(attrs.get("group", 1))
        if len(ins) < 3:
            kw["no_bias"] = True
        return S.Convolution(sym_of(ins[0]), weight=sym_of(ins[1]),
                             bias=sym_of(ins[2]) if len(ins) > 2 else None,
                             name=name, **kw)
    if op == "Gemm":
        if attrs.get("transB", 0) != 1 or attrs.get("transA", 0):
            raise MXNetError("ONNX import: only Gemm(transB=1) supported")
        w = inits.get(ins[1])
        if w is None:
            raise MXNetError("ONNX import: Gemm weight must be initializer")
        return S.FullyConnected(
            sym_of(ins[0]), weight=sym_of(ins[1]),
            bias=sym_of(ins[2]) if len(ins) > 2 else None,
            num_hidden=int(w.shape[0]), flatten=False,
            no_bias=len(ins) < 3, name=name)
    if op == "BatchNormalization":
        aux_names.update(ins[3:5])
        return S.BatchNorm(sym_of(ins[0]), gamma=sym_of(ins[1]),
                           beta=sym_of(ins[2]), moving_mean=sym_of(ins[3]),
                           moving_var=sym_of(ins[4]),
                           eps=float(attrs.get("epsilon", 1e-5)),
                           momentum=float(attrs.get("momentum", 0.9)),
                           fix_gamma=False, name=name)
    if op in ("MaxPool", "AveragePool"):
        kw = kernel_kwargs()
        kw.pop("dilate", None)
        if op == "AveragePool":
            kw["count_include_pad"] = bool(attrs.get("count_include_pad", 0))
        return S.Pooling(sym_of(ins[0]),
                         pool_type="max" if op == "MaxPool" else "avg",
                         name=name, **kw)
    if op in ("GlobalMaxPool", "GlobalAveragePool"):
        return S.Pooling(sym_of(ins[0]), global_pool=True, kernel=(1, 1),
                         pool_type="max" if op == "GlobalMaxPool" else "avg",
                         name=name)
    if op in ("Relu", "Sigmoid", "Tanh", "Softplus", "Softsign"):
        act = {"Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh",
               "Softplus": "softrelu", "Softsign": "softsign"}[op]
        return S.Activation(sym_of(ins[0]), act_type=act, name=name)
    if op == "LeakyRelu":
        return S.LeakyReLU(sym_of(ins[0]),
                           slope=float(attrs.get("alpha", 0.01)), name=name)
    if op == "Flatten":
        return S.Flatten(sym_of(ins[0]), name=name)
    if op == "Reshape":
        shape = inits.get(ins[1])
        if shape is None:
            raise MXNetError("ONNX import: dynamic Reshape unsupported")
        return S.reshape(sym_of(ins[0]), shape=tuple(int(s) for s in shape),
                         name=name)
    if op == "Transpose":
        kw = {"axes": tuple(attrs["perm"])} if attrs.get("perm") else {}
        return S.transpose(sym_of(ins[0]), name=name, **kw)
    if op in ("Add", "Sub", "Mul", "Div"):
        fn = {"Add": S.broadcast_add, "Sub": S.broadcast_sub,
              "Mul": S.broadcast_mul, "Div": S.broadcast_div}[op]
        return fn(sym_of(ins[0]), sym_of(ins[1]), name=name)
    if op == "Sum":
        return S.add_n(*[sym_of(i) for i in ins], name=name)
    if op == "Concat":
        return S.Concat(*[sym_of(i) for i in ins],
                        dim=int(attrs.get("axis", 1)), name=name)
    if op == "Softmax":
        return S.softmax(sym_of(ins[0]), axis=int(attrs.get("axis", -1)),
                         name=name)
    if op == "Dropout":
        ratio = inits.get(ins[1]) if len(ins) > 1 else None
        p = float(ratio) if ratio is not None else 0.5
        return S.Dropout(sym_of(ins[0]), p=p, name=name)
    if op == "Gather":
        w = inits.get(ins[0])
        if w is None or int(attrs.get("axis", 0)) != 0:
            raise MXNetError("ONNX import: Gather supported only as "
                             "Embedding (initializer table, axis 0)")
        return S.Embedding(sym_of(ins[1]), weight=sym_of(ins[0]),
                           input_dim=int(w.shape[0]),
                           output_dim=int(w.shape[1]), name=name)
    if op == "MatMul":
        return S.dot(sym_of(ins[0]), sym_of(ins[1]), name=name)
    raise MXNetError(f"ONNX import: unsupported op {op!r}")


def get_model_metadata(model_file):
    """{input/output names} — the reference contrib API's metadata probe."""
    with open(model_file, "rb") as f:
        model = decode(f.read())
    graph = decode(model[7][0])
    inits = {_parse_tensor(t)[0] for t in graph.get(5, [])}
    def names(field):
        return [decode(v)[1][0].decode() for v in graph.get(field, [])]
    return {"input_tensor_data": [n for n in names(11) if n not in inits],
            "output_tensor_data": names(12)}
