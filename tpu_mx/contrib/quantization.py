"""INT8 quantization (REF:python/mxnet/contrib/quantization.py,
REF:src/operator/quantization/**).

The reference rewrites symbols to quantized ops with min/max calibration.
TPU-natively int8 matmuls run on the MXU with int32 accumulation —
``lax.dot_general(preferred_element_type=int32)`` — so the same three
pieces exist here: the quantize/dequantize ops (affine, symmetric int8 as
in the reference's `quantize` with `out_type='int8'`), a calibration pass
(min/max or entropy-free percentile over a calibration iterator), and
``quantize_net``, which swaps Gluon Dense layers for int8 versions.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..ndarray import NDArray

__all__ = ["quantize", "dequantize", "calib_minmax", "QuantizedDense",
           "quantize_net"]


def quantize(data, min_range=None, max_range=None, out_type="int8"):
    """Affine-symmetric int8 quantization (REF quantize op): scale =
    max(|min|,|max|)/127.  Returns (q, min_range, max_range)."""
    import jax.numpy as jnp
    if out_type != "int8":
        raise MXNetError("only int8 quantization is supported on TPU")
    x = data._data if isinstance(data, NDArray) else jnp.asarray(data)
    if min_range is None:
        min_range = float(jnp.min(x))
    if max_range is None:
        max_range = float(jnp.max(x))
    amax = max(abs(min_range), abs(max_range), 1e-8)
    scale = 127.0 / amax
    q = jnp.clip(jnp.round(x.astype(jnp.float32) * scale), -127, 127
                 ).astype(jnp.int8)
    return NDArray(q), min_range, max_range


def dequantize(q, min_range, max_range):
    """Inverse of :func:`quantize` (REF dequantize op)."""
    import jax.numpy as jnp
    x = q._data if isinstance(q, NDArray) else jnp.asarray(q)
    amax = max(abs(min_range), abs(max_range), 1e-8)
    return NDArray(x.astype(jnp.float32) * (amax / 127.0))


def calib_minmax(net, calib_iter, num_batches=10):
    """Min/max calibration (REF calib_mode='naive'): run the iterator
    through the net recording per-layer input ranges via forward hooks."""
    ranges = {}
    handles = []

    def make_hook(name):
        def hook(blk, inputs, output):
            x = inputs[0]
            if isinstance(x, NDArray):
                lo, hi = float(x.min().asnumpy()), float(x.max().asnumpy())
                old = ranges.get(name, (lo, hi))
                ranges[name] = (min(old[0], lo), max(old[1], hi))
        return hook

    from ..gluon import nn
    for name, blk in _named_dense(net):
        handles.append(blk.register_forward_hook(make_hook(name)))
    for i, batch in enumerate(calib_iter):
        if i >= num_batches:
            break
        data = batch.data[0] if hasattr(batch, "data") else batch
        net(data)
    for h in handles:
        h.detach()
    return ranges


def _named_dense(block, prefix=""):
    from ..gluon import nn
    if isinstance(block, nn.Dense):
        yield prefix or "dense", block
        return
    children = getattr(block, "_children", {})
    items = children.items() if isinstance(children, dict) \
        else enumerate(children)
    for key, child in items:
        sub = f"{prefix}.{key}" if prefix else str(key)
        yield from _named_dense(child, sub)


class QuantizedDense:
    """Int8 inference Dense: int8×int8 → int32 on the MXU, rescaled to
    float (REF quantized_fully_connected)."""

    def __init__(self, dense, input_range):
        import jax.numpy as jnp
        w = dense.weight.data()
        self._wq, self._wmin, self._wmax = quantize(w)
        self._bias = dense.bias.data()._data \
            if getattr(dense, "bias", None) is not None else None
        self._act = dense.act  # activation fused in Dense stays applied
        self._in_range = input_range

    def __call__(self, x):
        import jax.numpy as jnp
        from jax import lax
        xq, xmin, xmax = quantize(x, *self._in_range)
        acc = lax.dot_general(
            xq._data, self._wq._data,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)
        x_amax = max(abs(xmin), abs(xmax), 1e-8)
        w_amax = max(abs(self._wmin), abs(self._wmax), 1e-8)
        out = acc.astype(jnp.float32) * (x_amax / 127.0) * (w_amax / 127.0)
        if self._bias is not None:
            out = out + self._bias
        out = NDArray(out)
        return self._act(out) if self._act is not None else out


class _QuantizedNet:
    """Inference wrapper produced by quantize_net."""

    def __init__(self, net, qdense):
        self._net = net
        self._qdense = qdense

    def __call__(self, x):
        # single-Dense nets run fully quantized; mixed nets re-dispatch
        # layer by layer through the original structure
        return self._forward(self._net, "", x)

    def _forward(self, block, prefix, x):
        from ..gluon import nn
        if isinstance(block, nn.Dense):
            name = prefix or "dense"
            return self._qdense[name](x) if name in self._qdense \
                else block(x)
        children = getattr(block, "_children", {})
        if not children:
            return block(x)
        items = children.items() if isinstance(children, dict) \
            else enumerate(children)
        for key, child in items:
            sub = f"{prefix}.{key}" if prefix else str(key)
            x = self._forward(child, sub, x)
        return x


def quantize_net(net, calib_iter=None, calib_data=None, num_batches=10):
    """Swap every Dense for an int8 QuantizedDense using calibrated input
    ranges (REF quantize_model / quantize_net).  Sequential-structured
    nets only — the conv path stays float (bf16 IS the TPU fast path for
    convs; int8 wins on the Dense-heavy inference the reference targeted)."""
    if calib_iter is None:
        if calib_data is None:
            raise MXNetError("need calib_iter or calib_data")
        calib_iter = [calib_data]
    ranges = calib_minmax(net, calib_iter, num_batches)
    qdense = {name: QuantizedDense(blk, ranges[name])
              for name, blk in _named_dense(net) if name in ranges}
    return _QuantizedNet(net, qdense)
