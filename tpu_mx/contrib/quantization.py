"""INT8 quantization (REF:python/mxnet/contrib/quantization.py,
REF:src/operator/quantization/**).

The reference rewrites symbols to quantized ops with min/max calibration.
TPU-natively int8 matmuls run on the MXU with int32 accumulation —
``lax.dot_general(preferred_element_type=int32)`` — so the same three
pieces exist here: the quantize/dequantize ops (affine, symmetric int8 as
in the reference's `quantize` with `out_type='int8'`), a calibration pass
(min/max or entropy-free percentile over a calibration iterator), and
``quantize_net``, which swaps Gluon Dense layers for int8 versions.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..ndarray import NDArray

__all__ = ["quantize", "dequantize", "calib_minmax", "QuantizedDense",
           "QuantizedConv", "quantize_net"]


def quantize(data, min_range=None, max_range=None, out_type="int8"):
    """Affine-symmetric int8 quantization (REF quantize op): scale =
    max(|min|,|max|)/127.  Returns (q, min_range, max_range)."""
    import jax.numpy as jnp
    if out_type != "int8":
        raise MXNetError("only int8 quantization is supported on TPU")
    x = data._data if isinstance(data, NDArray) else jnp.asarray(data)
    if min_range is None:
        min_range = float(jnp.min(x))
    if max_range is None:
        max_range = float(jnp.max(x))
    amax = max(abs(min_range), abs(max_range), 1e-8)
    scale = 127.0 / amax
    q = jnp.clip(jnp.round(x.astype(jnp.float32) * scale), -127, 127
                 ).astype(jnp.int8)
    return NDArray(q), min_range, max_range


def dequantize(q, min_range, max_range):
    """Inverse of :func:`quantize` (REF dequantize op)."""
    import jax.numpy as jnp
    x = q._data if isinstance(q, NDArray) else jnp.asarray(q)
    amax = max(abs(min_range), abs(max_range), 1e-8)
    return NDArray(x.astype(jnp.float32) * (amax / 127.0))


import contextlib


@contextlib.contextmanager
def _forced_eager(net):
    """Temporarily de-hybridize every block: both calibration (leaf
    forward hooks) and the int8 leaf patching only take effect on the
    eager path — a cached jit program was traced with the float leaves
    and would silently bypass them."""
    saved = [blk for blk in _all_blocks(net)
             if getattr(blk, "_active", False)]
    for blk in saved:
        blk._active = False
    try:
        yield
    finally:
        for blk in saved:
            blk._active = True


def calib_minmax(net, calib_iter, num_batches=10):
    """Min/max calibration (REF calib_mode='naive'): run the iterator
    through the net recording per-layer input ranges via forward hooks."""
    ranges = {}
    handles = []

    def make_hook(name):
        def hook(blk, inputs, output):
            x = inputs[0]
            if isinstance(x, NDArray):
                lo, hi = float(x.min().asnumpy()), float(x.max().asnumpy())
                old = ranges.get(name, (lo, hi))
                ranges[name] = (min(old[0], lo), max(old[1], hi))
        return hook

    for name, blk in _named_quantizable(net):
        handles.append(blk.register_forward_hook(make_hook(name)))
    with _forced_eager(net):
        for i, batch in enumerate(calib_iter):
            if i >= num_batches:
                break
            data = batch.data[0] if hasattr(batch, "data") else batch
            net(data)
    for h in handles:
        h.detach()
    return ranges


def _is_quantizable_conv(block):
    """Forward (non-transpose) convs of any spatial rank with initialized
    weights quantize; transpose convs stay float (the reference's int8
    coverage is conv/pool/fc too — REF:src/operator/subgraph/mkldnn/)."""
    from ..gluon.nn.conv_layers import _Conv
    return isinstance(block, _Conv) and not block._transpose


def _named_quantizable(block, prefix=""):
    """(name, block) for every quantizable leaf: Dense + forward convs."""
    from ..gluon import nn
    if isinstance(block, nn.Dense):
        yield prefix or "dense", block
        return
    if _is_quantizable_conv(block):
        yield prefix or "conv", block
        return
    children = getattr(block, "_children", {})
    items = children.items() if isinstance(children, dict) \
        else enumerate(children)
    for key, child in items:
        sub = f"{prefix}.{key}" if prefix else str(key)
        yield from _named_quantizable(child, sub)


def _named_dense(block, prefix=""):
    """Back-compat: Dense-only view of _named_quantizable."""
    from ..gluon import nn
    for name, blk in _named_quantizable(block, prefix):
        if isinstance(blk, nn.Dense):
            yield name, blk


class QuantizedDense:
    """Int8 inference Dense: int8×int8 → int32 on the MXU, rescaled to
    float (REF quantized_fully_connected)."""

    def __init__(self, dense, input_range):
        import jax.numpy as jnp
        w = dense.weight.data()
        self._wq, self._wmin, self._wmax = quantize(w)
        self._bias = dense.bias.data()._data \
            if getattr(dense, "bias", None) is not None else None
        self._act = dense.act  # activation fused in Dense stays applied
        self._flatten = getattr(dense, "_flatten", True)
        self._in_range = input_range

    def __call__(self, x):
        import jax.numpy as jnp
        from jax import lax
        xq, xmin, xmax = quantize(x, *self._in_range)
        xd = xq._data
        # Dense's input contract: flatten trailing dims (default) or
        # contract the last axis only
        xd = xd.reshape(xd.shape[0], -1) if self._flatten \
            else xd.reshape(-1, xd.shape[-1])
        acc = lax.dot_general(
            xd, self._wq._data,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)
        if not self._flatten and len(xq.shape) > 2:
            acc = acc.reshape(xq.shape[:-1] + (acc.shape[-1],))
        x_amax = max(abs(xmin), abs(xmax), 1e-8)
        w_amax = max(abs(self._wmin), abs(self._wmax), 1e-8)
        out = acc.astype(jnp.float32) * (x_amax / 127.0) * (w_amax / 127.0)
        if self._bias is not None:
            out = out + self._bias
        out = NDArray(out)
        return self._act(out) if self._act is not None else out


class QuantizedConv:
    """Int8 inference conv: int8×int8 → int32 on the MXU via
    nd.quantized_conv, rescaled to float (REF quantized_conv +
    subgraph/mkldnn conv int8 path).  Weights quantized once at build;
    inputs quantized per call with the calibrated range.  Pooling and
    activations around it pass through float — both are range-preserving,
    so the reference's conv→pool int8 chains lose nothing by rescaling at
    the conv boundary."""

    def __init__(self, conv, input_range):
        w = conv.weight.data()
        self._wq, self._wmin, self._wmax = quantize(w)
        self._bias = conv.bias.data() \
            if getattr(conv, "bias", None) is not None else None
        self._act = conv.act
        self._in_range = input_range
        self._conv = conv

    def __call__(self, x):
        import jax.numpy as jnp
        from ..ndarray import quantized_ops as Q
        c = self._conv
        xq, xmin, xmax = quantize(x, *self._in_range)
        out, mn, mx = Q.quantized_conv(
            xq, self._wq, None,
            NDArray(jnp.float32(xmin)), NDArray(jnp.float32(xmax)),
            NDArray(jnp.float32(self._wmin)),
            NDArray(jnp.float32(self._wmax)),
            kernel=c._kernel, stride=c._strides, pad=c._padding,
            dilate=c._dilation, num_filter=c._channels,
            num_group=c._groups, no_bias=True, layout=c._layout)
        x_amax = max(abs(xmin), abs(xmax), 1e-8)
        w_amax = max(abs(self._wmin), abs(self._wmax), 1e-8)
        y = out._data.astype(jnp.float32) * \
            ((x_amax / 127.0) * (w_amax / 127.0))
        if self._bias is not None:
            b = self._bias._data.astype(jnp.float32)
            if not c._channels_last:
                b = b.reshape((1, -1) + (1,) * len(c._kernel))
            y = y + b
        y = NDArray(y)
        return self._act(y) if self._act is not None else y


class _QuantizedNet:
    """Inference wrapper produced by quantize_net.  Structure-agnostic:
    for the duration of a call, each quantizable leaf's `forward` is
    shadowed by its int8 version (instance attribute over the class
    method), then the ORIGINAL net forward runs — residual/branchy
    architectures (ResNet blocks) keep their exact control flow, only the
    leaf compute is swapped.  The wrapped net itself is left untouched
    between calls.

    Calls are jit-compiled by default with the wrapper's OWN jax.jit —
    never the float net's `_cached_fns` (a cached float program was
    traced with the float leaves and would silently bypass the int8
    patching; that is why hybridize is force-disabled during the trace).
    The first r4 chip run of the eager path measured 16 img/s — pure
    per-op dispatch over the tunneled backend; the jitted program runs
    the same int8 ops as one XLA program (146 img/s same config).
    TPUMX_QUANT_JIT=0 restores the eager behavior (debugging).

    The traced program freezes ALL live params — the int8 leaves' ranges
    AND every non-quantized leaf's float weights — as constants at first
    call.  This is an inference-only snapshot: after ANY weight change,
    call `quantize_net` again for a fresh wrapper (the eager path would
    pick up new values, the jitted one will not)."""

    def __init__(self, net, qmap):
        self._net = net
        self._qmap = qmap
        self._jit = None

    def _run_patched(self, x):
        patched = []
        patched_ids = set()
        with _forced_eager(self._net):
            try:
                for name, blk in _named_quantizable(self._net):
                    q = self._qmap.get(name)
                    # a SHARED layer appears under several names — patch
                    # (and later unpatch) each instance exactly once
                    if q is not None and id(blk) not in patched_ids:
                        blk.forward = q  # instance attr shadows the method
                        patched.append(blk)
                        patched_ids.add(id(blk))
                return self._net(x)
            finally:
                for blk in patched:
                    del blk.forward

    def __call__(self, x):
        import os
        if os.environ.get("TPUMX_QUANT_JIT", "1") != "1":
            return self._run_patched(x)
        import jax
        xd = x._data if isinstance(x, NDArray) else x
        if self._jit is None:
            def raw(xj):
                out = self._run_patched(NDArray(xj))
                # multi-output nets return tuples/lists of NDArray
                return jax.tree.map(
                    lambda o: o._data if isinstance(o, NDArray) else o,
                    out, is_leaf=lambda o: isinstance(o, NDArray))

            # one jax.jit: its own signature cache retraces per
            # shape/dtype; no hand-rolled key dict needed
            self._jit = jax.jit(raw)
        out = self._jit(xd)
        return jax.tree.map(NDArray, out)


def _all_blocks(block):
    yield block
    children = getattr(block, "_children", {})
    items = children.values() if isinstance(children, dict) else children
    for child in items:
        yield from _all_blocks(child)


def quantize_net(net, calib_iter=None, calib_data=None, num_batches=10,
                 quantize_convs=True):
    """Swap every Dense — and, by default, every forward conv — for its
    int8 version using calibrated input ranges (REF quantize_model /
    quantize_net; conv coverage per REF:src/operator/subgraph/mkldnn/).
    Pooling/activation layers pass through float (range-preserving)."""
    from ..gluon import nn
    if calib_iter is None:
        if calib_data is None:
            raise MXNetError("need calib_iter or calib_data")
        calib_iter = [calib_data]
    ranges = calib_minmax(net, calib_iter, num_batches)
    qmap = {}
    for name, blk in _named_quantizable(net):
        if name not in ranges:
            continue
        if isinstance(blk, nn.Dense):
            qmap[name] = QuantizedDense(blk, ranges[name])
        elif quantize_convs:
            qmap[name] = QuantizedConv(blk, ranges[name])
    return _QuantizedNet(net, qmap)
