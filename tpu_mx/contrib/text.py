"""Text utilities: vocabulary + token embeddings
(REF:python/mxnet/contrib/text/{vocab.py,embedding.py,utils.py}).

Same API family as the reference: count_tokens_from_str → Vocabulary →
embedding lookup matrices ready for `nn.Embedding`/`ops.Embedding`.
Pretrained downloads (GloVe/fastText) are not available in this hermetic
zero-egress environment; `CustomEmbedding` loads the same
`token<space>vec...` text format from a local file, and
`get_pretrained_file_names` documents the divergence loudly.
"""
from __future__ import annotations

import collections
import re

import numpy as np

from ..base import MXNetError
from ..ndarray import NDArray

__all__ = ["count_tokens_from_str", "Vocabulary", "CustomEmbedding",
           "CompositeEmbedding", "get_pretrained_file_names"]


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """Token frequency counter (REF:contrib/text/utils.py)."""
    source_str = re.sub(re.escape(seq_delim), token_delim, source_str)
    if to_lower:
        source_str = source_str.lower()
    counter = counter_to_update if counter_to_update is not None \
        else collections.Counter()
    counter.update(t for t in source_str.split(token_delim) if t)
    return counter


class Vocabulary:
    """Indexed vocabulary with reserved tokens; index 0 is the unknown
    token (REF:contrib/text/vocab.py Vocabulary)."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise MXNetError("min_freq must be >= 1")
        reserved_tokens = list(reserved_tokens or [])
        if len(set(reserved_tokens)) != len(reserved_tokens) or \
                unknown_token in reserved_tokens:
            raise MXNetError("reserved tokens must be unique and must not "
                             "contain the unknown token")
        self._unknown_token = unknown_token
        self._idx_to_token = [unknown_token] + reserved_tokens
        self._reserved_tokens = reserved_tokens or None
        if counter is not None:
            pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
            taken = set(self._idx_to_token)
            # most_freq_count bounds COUNTER tokens only (reference
            # contract): unknown/reserved tokens ride on top
            budget = most_freq_count if most_freq_count is not None else None
            for tok, freq in pairs:
                if freq < min_freq or tok in taken:
                    continue
                if budget is not None and budget <= 0:
                    break
                self._idx_to_token.append(tok)
                taken.add(tok)
                if budget is not None:
                    budget -= 1
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idx = [self._token_to_idx.get(t, 0) for t in toks]
        return idx[0] if single else idx

    def to_tokens(self, indices):
        single = not isinstance(indices, (list, tuple))
        idxs = [indices] if single else indices
        for i in idxs:
            if not 0 <= i < len(self):
                raise MXNetError(f"token index {i} out of range")
        toks = [self._idx_to_token[i] for i in idxs]
        return toks[0] if single else toks


class _TokenEmbedding(Vocabulary):
    """Base: maps tokens to vectors; unknown tokens get init_unknown_vec."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._vec_len = 0
        self._idx_to_vec = None
        self._host_cache = None  # lazy host copy for token lookups

    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def _load_embedding(self, path, elem_delim, init_unknown_vec,
                        encoding="utf8", restrict_to_vocab=False):
        tokens, vecs = [], []
        with open(path, encoding=encoding) as f:
            for line_num, line in enumerate(f):
                parts = line.rstrip().split(elem_delim)
                if len(parts) <= 2:
                    continue  # header or malformed line (fastText header)
                tok, elems = parts[0], parts[1:]
                if restrict_to_vocab and tok not in self._token_to_idx:
                    continue  # vocabulary filter: don't index OOV file rows
                if self._vec_len and len(elems) != self._vec_len:
                    raise MXNetError(
                        f"line {line_num + 1}: dim {len(elems)} != "
                        f"{self._vec_len}")
                self._vec_len = self._vec_len or len(elems)
                tokens.append(tok)
                vecs.append(np.asarray(elems, np.float32))
        table = {t: v for t, v in zip(tokens, vecs)}
        if not restrict_to_vocab:
            for tok in tokens:
                if tok not in self._token_to_idx:
                    self._token_to_idx[tok] = len(self._idx_to_token)
                    self._idx_to_token.append(tok)
        mat = np.empty((len(self), self._vec_len), np.float32)
        unk = init_unknown_vec((self._vec_len,)) if init_unknown_vec \
            else np.zeros((self._vec_len,), np.float32)
        for i, tok in enumerate(self._idx_to_token):
            mat[i] = table.get(tok, unk)
        self._idx_to_vec = NDArray(mat)

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        if lower_case_backup:
            toks = [t if t in self._token_to_idx else t.lower()
                    for t in toks]
        idx = [self._token_to_idx.get(t, 0) for t in toks]
        if self._host_cache is None:
            # one host copy, reused across lookups (a 400k-row table would
            # otherwise ride device->host on every call)
            self._host_cache = self._idx_to_vec.asnumpy()
        vecs = self._host_cache[idx]
        return NDArray(vecs[0] if single else vecs)

    def update_token_vectors(self, tokens, new_vectors):
        toks = [tokens] if isinstance(tokens, str) else tokens
        arr = new_vectors.asnumpy() if isinstance(new_vectors, NDArray) \
            else np.asarray(new_vectors, np.float32)
        arr = arr.reshape(len(toks), self._vec_len)
        mat = np.array(self._idx_to_vec.asnumpy())  # writable copy
        for t, v in zip(toks, arr):
            if t not in self._token_to_idx:
                raise MXNetError(f"token {t!r} is unknown; only known "
                                 "tokens can be updated")
            mat[self._token_to_idx[t]] = v
        self._idx_to_vec = NDArray(mat)
        self._host_cache = None


class CustomEmbedding(_TokenEmbedding):
    """Embedding from a local `token<delim>v1<delim>...vn` text file
    (REF:contrib/text/embedding.py CustomEmbedding)."""

    def __init__(self, pretrained_file_path, elem_delim=" ", encoding="utf8",
                 init_unknown_vec=None, vocabulary=None, **kwargs):
        if vocabulary is not None:
            kwargs.setdefault("counter", collections.Counter(
                vocabulary.idx_to_token))
        super().__init__(**kwargs)
        if vocabulary is not None:
            self._idx_to_token = list(vocabulary.idx_to_token)
            self._token_to_idx = dict(vocabulary.token_to_idx)
        self._load_embedding(pretrained_file_path, elem_delim,
                             init_unknown_vec, encoding,
                             restrict_to_vocab=vocabulary is not None)


class CompositeEmbedding(_TokenEmbedding):
    """Concatenate several embeddings over one vocabulary
    (REF:contrib/text/embedding.py CompositeEmbedding)."""

    def __init__(self, vocabulary, token_embeddings):
        if not isinstance(token_embeddings, (list, tuple)):
            token_embeddings = [token_embeddings]
        self._unknown_token = vocabulary.unknown_token
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        self._reserved_tokens = vocabulary.reserved_tokens
        parts = [e.get_vecs_by_tokens(self._idx_to_token).asnumpy()
                 for e in token_embeddings]
        mat = np.concatenate(parts, axis=1)
        self._vec_len = mat.shape[1]
        self._idx_to_vec = NDArray(mat)
        self._host_cache = None


def get_pretrained_file_names(embedding_name=None):
    """The reference listed downloadable GloVe/fastText files; this
    hermetic environment has no egress, so pretrained catalogs are
    unavailable by design — use CustomEmbedding with a local file."""
    raise MXNetError(
        "pretrained embedding downloads are unavailable in this hermetic "
        "environment (zero egress); load local vectors via "
        "contrib.text.CustomEmbedding(path) instead")
