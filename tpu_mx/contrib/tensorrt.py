"""mx.contrib.tensorrt (REF:python/mxnet/contrib/tensorrt.py).

DIVERGENCE, stated loudly: TensorRT is an NVIDIA inference runtime; the
TPU deployment artifact here is a serialized StableHLO program
(`HybridBlock.export()` -> `SymbolBlock.imports`), which is what XLA-AOT
consumes.  Every entry point raises with that pointer instead of
silently no-op'ing.
"""
from ..base import MXNetError

__all__ = ["init_tensorrt_params", "optimize_graph", "get_optimized_symbol"]

_MSG = ("TensorRT is CUDA-only; on TPU export the model with "
        "HybridBlock.export() (StableHLO) and load it with "
        "SymbolBlock.imports - see docs/migration.md")


def init_tensorrt_params(*a, **k):
    raise MXNetError(_MSG)


def optimize_graph(*a, **k):
    raise MXNetError(_MSG)


def get_optimized_symbol(*a, **k):
    raise MXNetError(_MSG)
