"""2-bit gradient compression with error feedback
(REF:src/kvstore/gradient_compression.{cc,cu,h}).

The reference quantizes gradients to 2 bits around ±threshold before the PS
push and keeps the quantization error as a residual added to the next
gradient.  TPU-native form: the same quantize→dequantize+residual math as a
pure jax function (jit-able, so it can also ride inside a compiled train step
as a quantized-allreduce building block — SURVEY §2.3 stretch goal).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..ndarray import NDArray

__all__ = ["GradientCompression", "quantize_2bit_core", "quantize_int8_core", "quantize_fp8_core"]


def quantize_2bit_core(grad, residual, threshold):
    """Returns (dequantized_grad, new_residual): values snap to
    {-threshold, 0, +threshold}; the rounding error feeds back."""
    acc = grad + residual
    q = jnp.where(acc >= threshold, threshold,
                  jnp.where(acc <= -threshold, -threshold, 0.0)).astype(acc.dtype)
    return q, acc - q


def quantize_int8_core(grad, residual):
    """int8 per-tensor max-abs quantization with error feedback: returns
    (dequantized_grad, new_residual).  The wire value is round(acc/scale)
    in [-127, 127]; scale = max|acc|/127 rides alongside (simulated here by
    dequantizing immediately, as the reference's kvstore compression did)."""
    acc = grad + residual
    scale = jnp.maximum(jnp.max(jnp.abs(acc)), 1e-8) / 127.0
    deq = jnp.clip(jnp.round(acc / scale), -127, 127) * scale
    return deq, acc - deq


class GradientCompression:
    def __init__(self, type="2bit", threshold=0.5):
        if type not in ("2bit", "int8", "fp8"):
            raise ValueError(f"unsupported compression type {type!r} "
                             "(have: 2bit, int8, fp8)")
        self.type = type
        self.threshold = float(threshold)
        self._residuals = {}

    def compress_decompress(self, grad, key=None):
        """Round-trip a gradient through the 2-bit wire format (what a worker
        would push and then receive back aggregated)."""
        raw = grad._data if isinstance(grad, NDArray) else grad
        rkey = key if key is not None else id(grad)
        residual = self._residuals.get(rkey)
        if residual is None or residual.shape != raw.shape:
            residual = jnp.zeros_like(raw)
        if self.type == "2bit":
            q, new_residual = quantize_2bit_core(raw, residual,
                                                 self.threshold)
        elif self.type == "fp8":
            q, new_residual = quantize_fp8_core(raw, residual)
        else:
            q, new_residual = quantize_int8_core(raw, residual)
        self._residuals[rkey] = new_residual
        return NDArray(q) if isinstance(grad, NDArray) else q

    def get_params(self):
        return {"type": self.type, "threshold": self.threshold}


def quantize_fp8_core(grad, residual):
    """float8 (e4m3) per-tensor scaled quantization with error feedback:
    returns (dequantized_grad, new_residual).  The wire value is
    (acc/scale) cast to e4m3 (range ±448) with scale = max|acc|/448 —
     4x fewer bytes than f32 on the reduction wire (EQuARX-style,
    PAPERS.md; no reference analog, its kvstore wire had 2bit only)."""
    acc = grad + residual
    amax = jnp.max(jnp.abs(acc))
    scale = jnp.where(amax > 0, amax / 448.0, 1.0)
    wire = (acc / scale).astype(jnp.float8_e4m3fn)
    deq = wire.astype(jnp.float32) * scale
    return deq, acc - deq
