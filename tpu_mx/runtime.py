"""Runtime feature detection (REF:src/libinfo.cc, REF:python/mxnet/runtime.py).

The reference exposes its build-time feature matrix (CUDA? CUDNN? MKLDNN?
DIST_KVSTORE? ...) via ``mx.runtime.feature_list()``.  Here features are
determined live from the JAX installation: backend platforms, device counts,
and which optional subsystems of this framework are importable.
"""
from __future__ import annotations

__all__ = ["Feature", "Features", "feature_list", "fetch_sync"]


def fetch_sync(x):
    """Synchronize on device work by FETCHING data to the host, returning
    the fetched numpy array.

    The one reliable execution barrier on the tunneled axon backend:
    ``jax.block_until_ready`` there returns before execution finishes
    (bench.py measured 0.04 ms "steps" for 44 ms of work), so every
    timing loop in this repo bounds itself with a device->host copy —
    programs execute in submission order on the single stream, so
    fetching the LAST result proves all prior work completed.  Pass a
    small slice/scalar (e.g. ``loss`` or ``out[:1]``), not a big tensor:
    the fetch itself rides the tunnel.  Used by tools/longctx_bench.py
    and tools/bandwidth.py; bench.py and tools/tpu_validate.py keep
    equivalent inline fetches (bench's outer supervisor imports no
    tpu_mx by design)."""
    import numpy as np
    return np.asarray(x)


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self.enabled = bool(enabled)

    def __repr__(self):
        return "[%s: %s]" % ("✔" if self.enabled else "✖", self.name)


def _probe():
    feats = {"JAX": False, "TPU": False, "GPU": False, "CPU": True,
             "PALLAS": False, "X64": False, "DIST_KVSTORE": False}
    try:
        import jax
        feats["JAX"] = True
    except Exception:
        jax = None
    if jax is not None:
        try:
            platform = jax.default_backend()
            feats["TPU"] = platform == "tpu"
            feats["GPU"] = platform in ("gpu", "cuda", "rocm")
        except Exception:
            pass
        try:
            feats["PALLAS"] = bool(__import__("jax.experimental.pallas",
                                              fromlist=["pallas"]))
        except Exception:
            pass
        try:
            feats["X64"] = bool(jax.config.read("jax_enable_x64"))
        except Exception:
            pass
        try:
            import jax.distributed  # noqa: F401
            feats["DIST_KVSTORE"] = True
        except Exception:
            pass
    for mod, name in [("cv2", "OPENCV"),
                      ("PIL", "PIL"),            # image decode path
                      ("orbax.checkpoint", "ORBAX")]:
        try:
            __import__(mod)
            feats[name] = True
        except Exception:
            feats[name] = False
    # native C++ components of this framework (RecordIO fast path)
    try:
        from .lib import recordio_cpp  # noqa: F401
        feats["CPP_RECORDIO"] = True
    except Exception:
        feats["CPP_RECORDIO"] = False
    feats["BF16"] = feats["JAX"]
    feats["INT8_QUANTIZATION"] = True
    feats["PROFILER"] = True
    return feats


class Features(dict):
    """dict of name -> Feature, like the reference's LibInfo wrapper."""

    def __init__(self):
        super().__init__({k: Feature(k, v) for k, v in _probe().items()})

    def is_enabled(self, name):
        feat = self.get(name.upper())
        return bool(feat and feat.enabled)

    def __repr__(self):
        return "[%s]" % ", ".join(map(str, self.values()))


def feature_list():
    """Check the library for compile-time/runtime features it supports."""
    return list(Features().values())


def set_compilation_cache(directory, min_compile_time_secs=1.0):
    """Enable XLA's persistent compilation cache (REF analog: the
    reference's CachedOp graphs lived in-process only; on TPU the first
    compile of a big train step costs tens of seconds, and this cache
    carries it across PROCESSES/restarts — essential for the die-and-
    restart elastic contract in tpu_mx.elastic).

    directory: cache dir (created if missing).  Programs whose compile
    took less than min_compile_time_secs are not cached (they would only
    add disk churn)."""
    import jax
    jax.config.update("jax_compilation_cache_dir", str(directory))
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_time_secs))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    # jax latches the cache-used decision at the FIRST compile of the
    # process (compilation_cache._cache_checked); if anything compiled
    # before this call — an earlier train step, another test — the new
    # dir would be silently ignored forever.  reset_cache() unlatches so
    # the next compile re-evaluates with the dir configured.
    try:
        from jax._src import compilation_cache
        compilation_cache.reset_cache()
    except (ImportError, AttributeError):  # private API moved: next
        pass  # process picks the dir up at first compile as before


def enable_shared_compilation_cache():
    """The bench/validate/mfu tools' shared opt-out-able cache: enables
    the persistent cache at the repo-local `.jax_cache` unless
    BENCH_COMPILE_CACHE=0 (one knob disables it for ALL three tools —
    e.g. when the directory is corrupted/unwritable).  Returns the dir
    or None when disabled."""
    import os
    if os.environ.get("BENCH_COMPILE_CACHE", "1") != "1":
        return None
    directory = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache")
    set_compilation_cache(directory)
    return directory


def clear_compilation_cache():
    """Drop the in-memory jit cache (the persistent dir is untouched)."""
    import jax
    jax.clear_caches()
