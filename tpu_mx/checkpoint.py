"""Durable checkpointing: atomic writes, manifests, verification, retention.

Why this exists (ISSUE 2 / docs/robustness.md): every state writer in the
tree used to do a bare in-place ``open(fname, "wb")`` — a preemption or
crash mid-write left a truncated file that ``elastic.latest_checkpoint``
happily selected as newest, so ``auto_resume`` loaded garbage.  On
preemptible TPU pods that is the *dominant* failure mode.  This module is
the single durability layer every writer routes through:

- :func:`atomic_write` — write to ``<path>.tmp.<pid>``, flush, ``fsync``,
  then ``os.replace`` onto the destination.  A death at ANY instant leaves
  either the old complete file or ignorable tmp debris, never a truncated
  destination.  The sha256/size of the intended byte stream is recorded so
  manifests can later detect torn writes (bytes the app wrote that never
  reached disk).
- a per-checkpoint JSON **manifest** (``prefix-NNNN.manifest.json``: file
  list, sizes, sha256 digests, git HEAD, wall time) written *last*, as the
  commit point — a checkpoint without a readable, matching manifest is not
  a checkpoint.
- :func:`verify_checkpoint` — checks the manifest against the files and
  names the torn/missing/corrupt one explicitly.
- :func:`apply_retention` — keep the newest K epochs, never deleting the
  newest *verified* one (a retention pass must not be able to destroy the
  only good recovery point).
- :func:`retry` — jittered exponential backoff for transient filesystem
  errors (NFS/gcsfuse hiccups); simulated crashes are deliberately not
  retryable.
- :func:`preemption_handler` — SIGTERM/SIGINT hooks that trigger one
  emergency atomic save before exit (the preemptible-pod contract).

All fault paths are exercised, not assumed: ``tpu_mx/contrib/chaos.py``
injects crashes/tears/transient errors at the exact byte boundaries this
module must survive (see tests/test_checkpoint.py, tests/test_elastic.py).
"""
from __future__ import annotations

import collections
import contextlib
import glob
import hashlib
import json
import logging
import os
import random
import re
import signal
import socket
import subprocess
import sys
import threading
import time

from .base import MXNetError
from . import telemetry as _telemetry
from . import tracing as _tracing

__all__ = ["atomic_write", "retry", "sha256_file", "manifest_path",
           "write_manifest", "update_manifest", "read_manifest",
           "verify_checkpoint", "newest_verified_epoch", "list_epochs",
           "checkpoint_files", "apply_retention", "preemption_handler",
           "CheckpointCorrupt", "MANIFEST_FORMAT"]

log = logging.getLogger(__name__)

MANIFEST_FORMAT = "tpu_mx-manifest-v1"


class CheckpointCorrupt(MXNetError):
    """A checkpoint failed manifest verification (torn/missing/corrupt)."""


def _chaos():
    """The fault-injection module (lazy: contrib must not load at import
    of the core package, and env-armed chaos parses on first use)."""
    from .contrib import chaos
    chaos.configure_from_env()
    return chaos


# ---------------------------------------------------------------------------
# atomic_write
# ---------------------------------------------------------------------------
# abspath -> {"size": int, "sha256": hex} for the most recent atomic_write;
# manifest writers prefer this *intended* digest over re-hashing the disk
# file, which is what makes a torn write (disk != intent) detectable.
_intended = collections.OrderedDict()
_INTENDED_MAX = 256
_intended_lock = threading.Lock()


class _HashingFile:
    """Counts and sha256-hashes the bytes the caller writes (the *intent*),
    independent of what the chaos layer lets reach disk below it."""

    def __init__(self, f):
        self._f = f
        self.nbytes = 0
        self.sha = hashlib.sha256()
        self.seeked = False  # a seek invalidates linear stream hashing

    def write(self, data):
        buf = data.encode("utf-8") if isinstance(data, str) else data
        self.sha.update(buf)
        self.nbytes += memoryview(buf).nbytes
        return self._f.write(data)

    def seek(self, *a, **kw):
        self.seeked = True
        return self._f.seek(*a, **kw)

    def __getattr__(self, name):
        return getattr(self._f, name)


def _fsync_dir(dirname):
    """fsync the directory so the rename itself is durable (best effort —
    not every filesystem/platform supports opening a directory)."""
    try:
        fd = os.open(dirname or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


@contextlib.contextmanager
def atomic_write(path, mode="wb", fsync=True):
    """Crash-safe file write: all-or-nothing commit via tmp + rename.

    ::

        with atomic_write(fname) as f:
            f.write(payload)          # one-shot writes keep intent == stream

    Writes go to ``<path>.tmp.<pid>`` in the same directory (same
    filesystem, so the final ``os.replace`` is atomic); on clean exit the
    stream is flushed, fsync'd, renamed over ``path``, and the directory
    fsync'd.  On an ordinary exception the tmp is removed and the old
    ``path`` (if any) is untouched.  On a simulated crash
    (``chaos.ChaosCrash``) the tmp is *left behind*, exactly like a real
    kill — recovery code must (and does) ignore ``*.tmp.*`` debris.

    ``mode`` is ``"wb"`` or ``"w"`` (text, utf-8).  The intended size and
    sha256 of the written stream are recorded for :func:`write_manifest`;
    writers that seek (invalidating linear hashing) fall back to hashing
    the committed file from disk.
    """
    if mode not in ("wb", "w"):
        raise ValueError(f"atomic_write: mode must be 'wb' or 'w', got {mode}")
    t_start = time.perf_counter()
    chaos = _chaos()
    path = os.fspath(path)
    ap = os.path.abspath(path)
    dirname = os.path.dirname(ap)
    if dirname and not os.path.isdir(dirname):
        os.makedirs(dirname, exist_ok=True)
    chaos.maybe_oserror("open", path)
    tmp = f"{ap}.tmp.{os.getpid()}"
    raw = open(tmp, mode, encoding="utf-8" if mode == "w" else None)
    wrapper = _HashingFile(chaos.wrap_file(raw, path))
    try:
        yield wrapper
        raw.flush()
        if fsync:
            os.fsync(raw.fileno())
        raw.close()
        chaos.maybe_oserror("replace", path)
        info = {"size": wrapper.nbytes, "sha256": wrapper.sha.hexdigest()}
        if wrapper.seeked:
            info = {"size": os.path.getsize(tmp), "sha256": sha256_file(tmp)}
        os.replace(tmp, ap)
        if fsync:
            _fsync_dir(dirname)
        with _intended_lock:
            _intended[ap] = info
            while len(_intended) > _INTENDED_MAX:
                _intended.popitem(last=False)
        _telemetry.counter("checkpoint.atomic_writes").inc()
        # per-FILE commit latency; whole-checkpoint save latency is the
        # checkpoint.save_seconds span at the save call sites
        _telemetry.histogram("checkpoint.write_seconds").observe(
            time.perf_counter() - t_start)
    except BaseException as e:
        try:
            raw.close()
        except OSError:
            pass
        from .contrib.chaos import ChaosCrash
        if not isinstance(e, ChaosCrash):
            # ordinary failure: clean up; a (simulated) crash leaves debris
            try:
                os.remove(tmp)
            except OSError:
                pass
        raise


def sha256_file(path, chunk=1 << 20):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                return h.hexdigest()
            h.update(buf)


# ---------------------------------------------------------------------------
# retry
# ---------------------------------------------------------------------------
def retry(fn, attempts=4, backoff=0.05, max_backoff=2.0, jitter=0.5,
          exceptions=(OSError,), seed=None):
    """Call ``fn()`` with jittered exponential backoff on transient errors.

    Retries only ``exceptions`` (default ``OSError`` — the transient
    filesystem class).  ``chaos.ChaosCrash`` is intentionally outside that
    set: a crash is not transient.  The jitter stream is seedable for
    deterministic tests; sleep durations are
    ``backoff * 2**k * (1 + jitter*U[0,1))`` capped at ``max_backoff``.
    Raises the last error after ``attempts`` tries."""
    rng = random.Random(seed)
    delay = float(backoff)
    for attempt in range(1, int(attempts) + 1):
        try:
            return fn()
        except exceptions as e:
            if attempt >= attempts:
                raise
            _telemetry.counter("checkpoint.retries").inc()
            _tracing.emit("checkpoint.retry", attempt=attempt,
                          error=f"{type(e).__name__}: {e}")
            sleep = delay * (1.0 + float(jitter) * rng.random())
            log.warning("retry %d/%d: %s: %s (backing off %.3fs)",
                        attempt, attempts, type(e).__name__, e, sleep)
            time.sleep(sleep)
            delay = min(delay * 2.0, float(max_backoff))


# ---------------------------------------------------------------------------
# manifests
# ---------------------------------------------------------------------------
_git_head_cache = None


def _git_head():
    global _git_head_cache
    if _git_head_cache is None:
        try:
            _git_head_cache = subprocess.run(
                ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
                timeout=5,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            ).stdout.strip() or "unknown"
        except (OSError, subprocess.SubprocessError):
            _git_head_cache = "unknown"
    return _git_head_cache


def manifest_path(prefix, epoch):
    return f"{prefix}-{int(epoch):04d}.manifest.json"


def _file_entry(path):
    ap = os.path.abspath(path)
    with _intended_lock:
        info = _intended.get(ap)
    if info is None:  # written outside atomic_write: trust the disk bytes
        info = {"size": os.path.getsize(ap), "sha256": sha256_file(ap)}
    return dict(info)


def write_manifest(prefix, epoch, files, extra=None):
    """Write ``prefix-NNNN.manifest.json`` over `files` — the COMMIT POINT.

    Call strictly after every data file of the checkpoint has been
    atomically committed; an epoch whose manifest is missing/unreadable/
    mismatched is treated as not-a-checkpoint by the elastic path.  Digests
    come from the recorded intent of each file's `atomic_write` (falling
    back to hashing disk for files written by other means)."""
    entries = {}
    for p in files:
        entries[os.path.basename(os.fspath(p))] = _file_entry(p)
    man = {
        "format": MANIFEST_FORMAT,
        "prefix": os.path.basename(os.fspath(prefix)),
        "epoch": int(epoch),
        "files": entries,
        "git_head": _git_head(),
        "wall_time": time.time(),
        "written_at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "host": socket.gethostname(),
        "pid": os.getpid(),
    }
    if extra:
        man.update(extra)
    with atomic_write(manifest_path(prefix, epoch), "w") as f:
        f.write(json.dumps(man, indent=1, sort_keys=True))
    _record_bytes_on_disk(man)
    return man


def _record_bytes_on_disk(man):
    """Publish the manifest's committed payload bytes as the
    ``checkpoint.bytes_on_disk`` gauge (ISSUE 14 capacity twin): each
    epoch's save stamps its total, so the telemetry timeline carries
    bytes-on-disk per epoch without a filesystem walk."""
    total = sum(int(e.get("size", 0)) for e in man.get("files", {}).values())
    _telemetry.gauge("checkpoint.bytes_on_disk").set(float(total))


def update_manifest(prefix, epoch, add_files, extra=None):
    """Add `add_files` to an existing manifest (atomic rewrite), or create
    one if the epoch has none yet — for multi-phase checkpoints where e.g.
    optimizer states land after the params commit."""
    mp = manifest_path(prefix, epoch)
    man = None
    if os.path.exists(mp):
        try:
            man = read_manifest(prefix, epoch)
        except (OSError, ValueError, CheckpointCorrupt):
            man = None  # unreadable: rebuild from scratch below
    if man is None:
        return write_manifest(prefix, epoch, add_files, extra=extra)
    for p in add_files:
        man["files"][os.path.basename(os.fspath(p))] = _file_entry(p)
    if extra:
        man.update(extra)
    man["wall_time"] = time.time()
    man["written_at"] = time.strftime("%Y-%m-%dT%H:%M:%S%z")
    with atomic_write(mp, "w") as f:
        f.write(json.dumps(man, indent=1, sort_keys=True))
    _record_bytes_on_disk(man)
    return man


def read_manifest(prefix, epoch):
    """Parse the epoch's manifest; raises CheckpointCorrupt if unreadable."""
    mp = manifest_path(prefix, epoch)
    try:
        with open(mp, encoding="utf-8") as f:
            man = json.load(f)
    except ValueError as e:
        raise CheckpointCorrupt(f"manifest {mp} unreadable: {e}") from e
    if not isinstance(man, dict) or "files" not in man:
        raise CheckpointCorrupt(f"manifest {mp} malformed (no file table)")
    return man


def verify_checkpoint(prefix, epoch):
    """Check epoch `epoch` of `prefix` against its manifest.

    Returns ``(status, problems)``:

    - ``("verified", [])`` — manifest present, every file exists with the
      recorded size and sha256;
    - ``("legacy", [])`` — no manifest but checkpoint files exist (written
      by a pre-durability writer): loadable, but unverifiable;
    - ``("corrupt", [...])`` — manifest unreadable, or a file is missing /
      torn (size mismatch) / content-corrupt (digest mismatch); each
      problem string names the offending file and the failure mode.
    """
    t_start = time.perf_counter()
    status, problems = _verify_checkpoint(prefix, epoch)
    _telemetry.histogram("checkpoint.verify_seconds").observe(
        time.perf_counter() - t_start)
    if status == "corrupt":
        _telemetry.counter("checkpoint.corrupt_detected").inc()
    _tracing.emit("checkpoint.verify", prefix=os.path.basename(str(prefix)),
                  epoch=int(epoch), status=status)
    return status, problems


def _verify_checkpoint(prefix, epoch):
    mp = manifest_path(prefix, epoch)
    if not os.path.exists(mp):
        legacy = [p for p in glob.glob(f"{prefix}-{int(epoch):04d}.*")
                  if ".tmp." not in p]
        if legacy:
            return "legacy", []
        return "corrupt", [f"epoch {epoch}: no manifest and no files"]
    try:
        man = read_manifest(prefix, epoch)
    except CheckpointCorrupt as e:
        return "corrupt", [str(e)]
    problems = []
    d = os.path.dirname(os.path.abspath(mp))
    for name, info in man["files"].items():
        p = os.path.join(d, name)
        if not os.path.exists(p):
            problems.append(f"{name}: missing")
            continue
        size = os.path.getsize(p)
        if size != info.get("size"):
            problems.append(
                f"{name}: torn/truncated write — size on disk {size} != "
                f"manifest {info.get('size')}")
            continue
        if sha256_file(p) != info.get("sha256"):
            problems.append(f"{name}: sha256 mismatch (corrupt content)")
    return ("verified" if not problems else "corrupt"), problems


def newest_verified_epoch(prefix):
    """Newest epoch of `prefix` whose manifest verifies, or None — the one
    recovery point retention and the training supervisor must preserve."""
    for e in reversed(list_epochs(prefix)):
        if verify_checkpoint(prefix, e)[0] == "verified":
            return e
    return None


# ---------------------------------------------------------------------------
# enumeration + retention
# ---------------------------------------------------------------------------
_EPOCH_FILE_RE = re.compile(
    r"-(\d{4,})\.(?:params(?:\.npz)?|states|manifest\.json)$")


def list_epochs(prefix):
    """Sorted epochs that have any checkpoint artifact under `prefix`."""
    epochs = set()
    for path in glob.glob(f"{prefix}-*"):
        if ".tmp." in path:
            continue
        m = _EPOCH_FILE_RE.search(path)
        if m:
            epochs.add(int(m.group(1)))
    return sorted(epochs)


def checkpoint_files(prefix, epoch):
    """Every file belonging to ONE epoch: manifest-listed files carrying
    this epoch's tag, the manifest itself, plus on-disk ``prefix-NNNN.*``
    strays.  Files shared across epochs (``prefix-symbol.json``) are
    excluded — retention must never delete them."""
    tag = f"-{int(epoch):04d}."
    found = set()
    mp = manifest_path(prefix, epoch)
    if os.path.exists(mp):
        found.add(mp)
        try:
            man = read_manifest(prefix, epoch)
            d = os.path.dirname(os.path.abspath(mp))
            for name in man["files"]:
                if tag in name and os.path.exists(os.path.join(d, name)):
                    found.add(os.path.join(d, name))
        except CheckpointCorrupt:
            pass
    for p in glob.glob(f"{prefix}{tag}*"):
        if ".tmp." not in p:
            found.add(p)
    return sorted(found)


def apply_retention(prefix, keep_last, known_verified=None):
    """Delete all but the newest `keep_last` epochs' files.

    The newest *verified* epoch is always kept even when it falls outside
    the window (if the newer epochs are all corrupt, deleting the last good
    one would leave nothing to resume from).  A caller that just committed
    an epoch passes it as `known_verified` to skip the full from-disk
    re-hash of files it wrote moments ago.  Returns the epochs removed."""
    if not keep_last or int(keep_last) < 1:
        return []
    epochs = list_epochs(prefix)
    if len(epochs) <= int(keep_last):
        return []
    keep = set(epochs[-int(keep_last):])
    if known_verified is not None and int(known_verified) >= epochs[-1]:
        keep.add(int(known_verified))  # newest epoch, verified by caller
    else:
        nv = newest_verified_epoch(prefix)
        if nv is not None:
            keep.add(nv)
    removed = []
    for e in epochs:
        if e in keep:
            continue
        for p in checkpoint_files(prefix, e):
            try:
                os.remove(p)
            except OSError:
                pass
        removed.append(e)
    if removed:
        log.info("retention(prefix=%s, keep_last=%s): removed epochs %s",
                 prefix, keep_last, removed)
    return removed


# ---------------------------------------------------------------------------
# preemption handling
# ---------------------------------------------------------------------------
class PreemptionHandler:
    """Installed SIGTERM/SIGINT hook: one emergency save, then exit.

    TPU preemption delivers SIGTERM with a grace window; the hook runs
    `save_fn` exactly once (reentrancy-guarded — a second signal during the
    save does not restart it), restores the previous handlers, and exits
    with the conventional ``128+signum`` unless ``exit=False`` (tests).
    Use :func:`preemption_handler` to construct; call ``uninstall()`` when
    the training loop exits normally."""

    def __init__(self, save_fn, signals, exit, exit_code,
                 blackbox_prefix=None):
        self._save_fn = save_fn
        self._signals = tuple(signals)
        self._exit = exit
        self._exit_code = exit_code
        self._blackbox_prefix = blackbox_prefix
        self._prev = {}
        self._lock = threading.Lock()
        self.triggered = False
        self.save_ok = None

    def install(self):
        for sig in self._signals:
            self._prev[sig] = signal.signal(sig, self._handle)
        return self

    def uninstall(self):
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):  # not main thread / torn down
                pass
        self._prev = {}

    def _handle(self, signum, frame):
        with self._lock:
            if self.triggered:
                return
            self.triggered = True
        log.warning("signal %d: writing emergency checkpoint before exit",
                    signum)
        try:
            self._save_fn()
            self.save_ok = True
        except BaseException:
            self.save_ok = False
            log.exception("emergency checkpoint failed; exiting anyway")
        _tracing.emit("checkpoint.preemption", signum=int(signum),
                      save_ok=bool(self.save_ok))
        if self._blackbox_prefix:
            # the preemption black box: what the run was doing when the
            # platform killed it (a dump failure must not eat the grace
            # window's remaining seconds — the emergency save landed)
            try:
                _tracing.dump_blackbox(
                    self._blackbox_prefix,
                    reason=f"preemption signal {signum} "
                           f"(emergency save_ok={self.save_ok})")
            except Exception:
                log.exception("preemption black-box dump failed")
        self.uninstall()
        if self._exit:
            code = self._exit_code if self._exit_code is not None \
                else 128 + signum
            sys.exit(code)


def preemption_handler(save_fn, signals=(signal.SIGTERM, signal.SIGINT),
                       exit=True, exit_code=None, blackbox_prefix=None):
    """Install SIGTERM/SIGINT hooks that run one emergency atomic save.

    ``save_fn`` should be a zero-arg durable saver, e.g.::

        handle = checkpoint.preemption_handler(
            lambda: elastic.save_checkpoint(prefix, epoch_box[0],
                                            net=net, trainer=trainer))

    ``blackbox_prefix=`` additionally dumps a flight-recorder black box
    (``<prefix>-blackbox.json``, docs/observability.md) after the
    emergency save, so a preempted run leaves its last-N-steps timeline
    behind, not just its weights.  Returns the installed
    :class:`PreemptionHandler` (``.uninstall()`` on clean shutdown;
    ``.triggered`` / ``.save_ok`` for inspection)."""
    return PreemptionHandler(save_fn, signals, exit, exit_code,
                             blackbox_prefix=blackbox_prefix).install()
