"""Evaluation metrics (REF:python/mxnet/metric.py)."""
from __future__ import annotations

import math

import numpy as np

from .base import Registry
from .ndarray import NDArray

__all__ = ["EvalMetric", "Accuracy", "TopKAccuracy", "F1", "MAE", "MSE", "RMSE",
           "CrossEntropy", "Perplexity", "Loss", "PearsonCorrelation",
           "CompositeEvalMetric", "CustomMetric", "create", "np_fn"]

registry = Registry("metric")


def _as_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None):
        self.name = name
        self.output_names = output_names
        self.label_names = label_names
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name, value = [name], [value]
        return list(zip(name, value))

    @staticmethod
    def _listify(labels, preds):
        if isinstance(labels, (list, tuple)):
            return list(labels), list(preds)
        return [labels], [preds]


@registry.register(name="acc", aliases=("accuracy",))
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = self._listify(labels, preds)
        for label, pred in zip(labels, preds):
            pred_np = _as_np(pred)
            label_np = _as_np(label).astype(np.int64)
            if pred_np.ndim > label_np.ndim:
                pred_np = pred_np.argmax(axis=self.axis)
            pred_np = pred_np.astype(np.int64)
            self.sum_metric += (pred_np.flat == label_np.flat).sum()
            self.num_inst += len(label_np.flat)


@registry.register(name="top_k_accuracy", aliases=("topk",))
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        super().__init__(f"{name}_{top_k}", **kwargs)
        self.top_k = top_k

    def update(self, labels, preds):
        labels, preds = self._listify(labels, preds)
        for label, pred in zip(labels, preds):
            pred_np = _as_np(pred)
            label_np = _as_np(label).astype(np.int64)
            topk_idx = np.argsort(-pred_np, axis=-1)[..., :self.top_k]
            hits = (topk_idx == label_np[..., None]).any(-1)
            self.sum_metric += hits.sum()
            self.num_inst += label_np.size


@registry.register
class F1(EvalMetric):
    def __init__(self, name="f1", average="macro", **kwargs):
        super().__init__(name, **kwargs)
        self.average = average
        self.reset_stats()

    def reset_stats(self):
        self.tp = self.fp = self.fn = 0

    def reset(self):
        super().reset()
        if hasattr(self, "tp"):
            self.reset_stats()

    def update(self, labels, preds):
        labels, preds = self._listify(labels, preds)
        for label, pred in zip(labels, preds):
            pred_np = _as_np(pred)
            label_np = _as_np(label).astype(np.int64).flatten()
            if pred_np.ndim > 1 and pred_np.shape[-1] > 1:
                pred_lab = pred_np.argmax(-1).flatten()
            else:
                pred_lab = (pred_np.flatten() > 0.5).astype(np.int64)
            self.tp += int(((pred_lab == 1) & (label_np == 1)).sum())
            self.fp += int(((pred_lab == 1) & (label_np == 0)).sum())
            self.fn += int(((pred_lab == 0) & (label_np == 1)).sum())
            prec = self.tp / max(self.tp + self.fp, 1)
            rec = self.tp / max(self.tp + self.fn, 1)
            f1 = 2 * prec * rec / max(prec + rec, 1e-12)
            self.sum_metric = f1
            self.num_inst = 1


@registry.register
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        labels, preds = self._listify(labels, preds)
        for label, pred in zip(labels, preds):
            self.sum_metric += np.abs(_as_np(label) - _as_np(pred)).mean()
            self.num_inst += 1


@registry.register
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        labels, preds = self._listify(labels, preds)
        for label, pred in zip(labels, preds):
            self.sum_metric += ((_as_np(label) - _as_np(pred)) ** 2).mean()
            self.num_inst += 1


@registry.register
class RMSE(EvalMetric):
    def __init__(self, name="rmse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        labels, preds = self._listify(labels, preds)
        for label, pred in zip(labels, preds):
            self.sum_metric += math.sqrt(
                ((_as_np(label) - _as_np(pred)) ** 2).mean())
            self.num_inst += 1


@registry.register(name="ce", aliases=("cross-entropy",))
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = self._listify(labels, preds)
        for label, pred in zip(labels, preds):
            label_np = _as_np(label).astype(np.int64).flatten()
            pred_np = _as_np(pred).reshape(len(label_np), -1)
            prob = pred_np[np.arange(len(label_np)), label_np]
            self.sum_metric += (-np.log(prob + self.eps)).sum()
            self.num_inst += len(label_np)


@registry.register
class Perplexity(CrossEntropy):
    """The PTB metric (REF:python/mxnet/metric.py:Perplexity)."""

    def __init__(self, ignore_label=None, axis=-1, name="perplexity", **kwargs):
        super().__init__(name=name, **kwargs)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = self._listify(labels, preds)
        for label, pred in zip(labels, preds):
            label_np = _as_np(label).astype(np.int64).flatten()
            pred_np = _as_np(pred).reshape(len(label_np), -1)
            prob = pred_np[np.arange(len(label_np)), label_np]
            if self.ignore_label is not None:
                ignore = label_np == self.ignore_label
                prob = prob[~ignore]
            self.sum_metric += (-np.log(np.maximum(prob, self.eps))).sum()
            self.num_inst += len(prob)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@registry.register
class Loss(EvalMetric):
    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        if not isinstance(preds, (list, tuple)):
            preds = [preds]
        for pred in preds:
            loss = _as_np(pred)
            self.sum_metric += loss.sum()
            self.num_inst += loss.size


@registry.register(name="pearsonr")
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        labels, preds = self._listify(labels, preds)
        for label, pred in zip(labels, preds):
            x = _as_np(label).flatten()
            y = _as_np(pred).flatten()
            self.sum_metric += float(np.corrcoef(x, y)[0, 1])
            self.num_inst += 1


@registry.register(name="mcc")
class MCC(EvalMetric):
    """Matthews correlation coefficient (REF metric.py:MCC) — binary
    confusion-matrix correlation, the class-imbalance-robust F1 cousin."""

    def __init__(self, name="mcc", **kwargs):
        super().__init__(name, **kwargs)
        self.reset_stats()

    def reset_stats(self):
        self._tp = self._tn = self._fp = self._fn = 0.0

    def reset(self):
        super().reset()
        self.reset_stats()

    def update(self, labels, preds):
        labels, preds = self._listify(labels, preds)
        for label, pred in zip(labels, preds):
            y = _as_np(label).flatten().astype(np.int64)
            p = _as_np(pred)
            if p.ndim > 1 and p.shape[-1] > 1:
                p = p.reshape(-1, p.shape[-1]).argmax(axis=-1)
            else:
                p = (p.flatten() > 0.5)
            p = p.astype(np.int64)
            self._tp += float(((p == 1) & (y == 1)).sum())
            self._tn += float(((p == 0) & (y == 0)).sum())
            self._fp += float(((p == 1) & (y == 0)).sum())
            self._fn += float(((p == 0) & (y == 1)).sum())
        den = np.sqrt((self._tp + self._fp) * (self._tp + self._fn) *
                      (self._tn + self._fp) * (self._tn + self._fn))
        mcc = 0.0 if den == 0 else             (self._tp * self._tn - self._fp * self._fn) / den
        self.sum_metric = mcc
        self.num_inst = 1


@registry.register(name="nll_loss", aliases=("nll-loss",))
class NegativeLogLikelihood(EvalMetric):
    """Mean NLL of the true class (REF metric.py:NegativeLogLikelihood)."""

    def __init__(self, eps=1e-12, name="nll-loss", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = self._listify(labels, preds)
        for label, pred in zip(labels, preds):
            y = _as_np(label).flatten().astype(np.int64)
            p = _as_np(pred).reshape(len(y), -1)
            chosen = p[np.arange(len(y)), y]
            self.sum_metric += float(-np.log(chosen + self.eps).sum())
            self.num_inst += len(y)


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kwargs):
        super().__init__(name, **kwargs)
        self.metrics = [create(m) if isinstance(m, str) else m
                        for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric) if isinstance(metric, str) else metric)

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.append(n)
            values.append(v)
        return (names, values)


class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False, **kwargs):
        super().__init__(name, **kwargs)
        self._feval = feval

    def update(self, labels, preds):
        labels, preds = self._listify(labels, preds)
        for label, pred in zip(labels, preds):
            v = self._feval(_as_np(label), _as_np(pred))
            if isinstance(v, tuple):
                sm, ni = v
                self.sum_metric += sm
                self.num_inst += ni
            else:
                self.sum_metric += v
                self.num_inst += 1


def np_fn(numpy_feval, name=None, allow_extra_outputs=False):
    return CustomMetric(numpy_feval, name or numpy_feval.__name__,
                        allow_extra_outputs)


np_metric = np_fn


def create(metric, *args, **kwargs):
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        return CompositeEvalMetric([create(m) for m in metric])
    if callable(metric):
        return CustomMetric(metric)
    return registry.create(metric, *args, **kwargs)
