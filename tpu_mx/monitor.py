"""Monitor: per-layer output statistics for debugging
(REF:python/mxnet/monitor.py).

The reference installs a stat callback on every executor output whose name
matches a pattern.  Here the equivalent hooks are Gluon forward hooks: pass
a ``Block`` to :meth:`Monitor.install`, and every ``interval``-th forward
pass records ``stat_func`` of each matching child's outputs.  Works on
un-hybridized blocks (hybridized graphs are a single XLA program — use
``mx.profiler`` for those).
"""
from __future__ import annotations

import re

import numpy as np

from .ndarray import NDArray

__all__ = ["Monitor"]


def _default_stat(arr):
    return float(np.abs(arr).mean())


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        self.interval = max(1, int(interval))
        self.stat_func = stat_func or _default_stat
        self.re = re.compile(pattern)
        self.sort = sort
        self.step = 0
        self.activated = False
        self.queue = []  # (step, name, stat)
        self._handles = []

    # -- installation ------------------------------------------------------
    def install(self, block, root_name=None):
        """Register forward hooks on ``block`` and all named children."""
        def make_hook(name):
            def hook(blk, inputs, output):
                if not self.activated:
                    return
                outs = output if isinstance(output, (list, tuple)) else (output,)
                for i, o in enumerate(outs):
                    if isinstance(o, NDArray):
                        key = name if len(outs) == 1 else f"{name}_output{i}"
                        if self.re.match(key):
                            self.queue.append(
                                (self.step, key, self.stat_func(o.asnumpy())))
            return hook

        for name, child in self._walk(block, root_name or type(block).__name__.lower()):
            self._handles.append(child.register_forward_hook(make_hook(name)))
        return self

    def uninstall(self):
        """Remove every hook this monitor registered."""
        for handle in self._handles:
            handle.detach()
        self._handles = []

    def _walk(self, block, prefix):
        yield prefix, block
        children = getattr(block, "_children", {})
        items = children.items() if isinstance(children, dict) else enumerate(children)
        for key, child in items:
            yield from self._walk(child, f"{prefix}.{key}")

    # -- per-batch protocol (same as reference) ----------------------------
    def tic(self):
        """Start collecting for this batch if it is an interval batch."""
        if self.step % self.interval == 0:
            self.activated = True
            self.queue = []
        self.step += 1

    def toc(self):
        """Stop collecting and return list of (step, name, stat)."""
        if not self.activated:
            return []
        self.activated = False
        res = sorted(self.queue, key=lambda t: t[1]) if self.sort else list(self.queue)
        self.queue = []
        return res

    def toc_print(self):
        for step, name, stat in self.toc():
            print("Batch: %7d %30s %s" % (step, name, stat))
