"""Flash attention as a Pallas TPU kernel.

TPU-native replacement for the reference's attention compute (the reference
has no fused attention at all — MXNet 1.x predates it; BERT-era GluonNLP
composed it from batch_dot + softmax, materializing the full T×T score
matrix).  This kernel computes attention blockwise with online softmax:
O(T) memory per core instead of O(T²), MXU-shaped (Bq×D)·(D×Bk) matmuls,
fp32 accumulation regardless of input dtype.

Layout: q/k/v are (BH, T, D) — batch*heads collapsed.  Grid is
(BH, T/Bq, T/Bk) with the K dimension innermost; VMEM scratch carries the
running (m, l, acc) statistics across K steps, and the output block is
written on the last K step (the standard sequential-grid accumulation
pattern).  The backward pass is two more Pallas kernels (dq and dk/dv),
using the saved logsumexp — the flash attention recompute trick.

Falls back to interpret mode off-TPU so tests run anywhere.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention", "mha_flash_attention"]

NEG_INF = -1e30


def _cdiv(a, b):
    return (a + b - 1) // b


def _interpret():
    return jax.default_backend() != "tpu"


# ----------------------------------------------------------------------------
# forward
# ----------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, block_q, block_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # skip fully-masked blocks (strictly above the diagonal)
    run = True
    if causal:
        run = qi * block_q + block_q - 1 >= ki * block_k

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)                      # (Bq, D)
        k = k_ref[0].astype(jnp.float32)                      # (Bk, D)
        v = v_ref[0].astype(jnp.float32)                      # (Bk, D)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        m_prev = m_scr[:, 0]                                  # (Bq,)
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur[:, None])                       # (Bq, Bk)
        l_cur = l_scr[:, 0] * alpha + jnp.sum(p, axis=1)
        acc_scr[:] = acc_scr[:] * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_cur[:, None], m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_cur[:, None], l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:, 0]
        l_safe = jnp.maximum(l, 1e-30)
        o_ref[0] = (acc_scr[:] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0] = (m_scr[:, 0] + jnp.log(l_safe))[:, None].astype(
            jnp.float32)


def _fwd(q, k, v, scale, causal, block_q, block_k):
    bh, t, d = q.shape
    tk = k.shape[1]
    block_q = min(block_q, t)
    block_k = min(block_k, tk)
    grid = (bh, _cdiv(t, block_q), _cdiv(tk, block_k))
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            # lse rides as (BH, T, 1): TPU block rules need the last two
            # block dims divisible by (8, 128) or equal to the array dims
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # running max
            pltpu.VMEM((block_q, 128), jnp.float32),   # running denom
            pltpu.VMEM((block_q, d), jnp.float32),     # output accumulator
        ],
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


# ----------------------------------------------------------------------------
# backward: dq kernel (grid k-innermost, accumulate dq over k blocks)
# ----------------------------------------------------------------------------
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, scale, causal, block_q, block_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = True
    if causal:
        run = qi * block_q + block_q - 1 >= ki * block_k

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, 0]                                 # (Bq,)
        delta = delta_ref[0][:, 0]                             # (Bq,)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])                          # (Bq, Bk)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


# ----------------------------------------------------------------------------
# backward: dk/dv kernel (grid q-innermost, accumulate dk,dv over q blocks)
# ----------------------------------------------------------------------------
def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr,
                    *, scale, causal, block_q, block_k):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = True
    if causal:
        run = qi * block_q + block_q - 1 >= ki * block_k

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, 0]
        delta = delta_ref[0][:, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])                          # (Bq, Bk)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(scale, causal, block_q, block_k, res, do):
    q, k, v, out, lse = res
    bh, t, d = q.shape
    tk = k.shape[1]
    bq = min(block_q, t)
    bk = min(block_k, tk)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)[..., None]                        # (BH, T, 1)

    qspec = pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM)
    kspec = pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM)
    rowq = pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0),
                        memory_space=pltpu.VMEM)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk),
        grid=(bh, _cdiv(t, bq), _cdiv(tk, bk)),
        in_specs=[qspec, kspec, kspec, qspec, rowq, rowq],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    # dk/dv: swap grid so q is innermost; index maps take (b, kblk, qblk)
    qspec2 = pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0),
                          memory_space=pltpu.VMEM)
    kspec2 = pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0),
                          memory_space=pltpu.VMEM)
    rowq2 = pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0),
                         memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk),
        grid=(bh, _cdiv(tk, bk), _cdiv(t, bq)),
        in_specs=[qspec2, kspec2, kspec2, qspec2, rowq2, rowq2],
        out_specs=[kspec2, kspec2],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ----------------------------------------------------------------------------
# public entry
# ----------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_core(q, k, v, scale, causal, block_q, block_k):
    out, _ = _fwd(q, k, v, scale, causal, block_q, block_k)
    return out


def _flash_fwd_rule(q, k, v, scale, causal, block_q, block_k):
    out, lse = _fwd(q, k, v, scale, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(scale, causal, block_q, block_k, res, do):
    return _bwd(scale, causal, block_q, block_k, res, do)


_flash_core.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, scale=None, causal=False,
                    block_q=None, block_k=None):
    """softmax(q·kᵀ·scale [+causal mask])·v, blockwise.  q/k/v: (BH, T, D).
    scale defaults to 1/sqrt(D); blocks default to the tuned sizes.  T (for
    both q and k/v) must tile exactly by the chosen blocks — partial K
    blocks would feed padded garbage into the softmax."""
    t, tk = q.shape[1], k.shape[1]
    scale = 1.0 / math.sqrt(q.shape[-1]) if scale is None else scale
    block_q = block_q or _pick_block(t, 512)
    block_k = block_k or _pick_block(tk, 1024)
    if t % min(block_q, t) or tk % min(block_k, tk):
        raise ValueError(
            f"flash_attention: seq lens (q={t}, kv={tk}) must be divisible "
            f"by the block sizes ({block_q}, {block_k}); gate callers with "
            "kernels.flash_attention.supported()")
    return _flash_core(q, k, v, scale, causal, block_q, block_k)


def _pick_block(t, prefer):
    """Largest power-of-two block ≤ prefer that divides t, so blocks tile T
    exactly — partial K blocks would feed garbage columns into the softmax.
    t ≤ the smallest candidate is returned as-is (single block)."""
    if t <= 128:
        return t
    for b in (prefer, 1024, 512, 256, 128):
        if b <= prefer and t % b == 0:
            return b
    return t  # no aligned divisor: single block covering T (caller gates)


def mha_flash_attention(q, k, v, causal=False, block_q=None, block_k=None):
    """Multi-head wrapper: q/k/v are (B, H, T, D); collapses batch*heads,
    runs the Pallas kernel, restores the layout.  Default blocks tuned on
    v5e-class hardware: large K blocks amortize the scratch carry."""
    b, h, t, d = q.shape
    fold = lambda x: x.reshape(b * h, x.shape[2], d)
    out = flash_attention(fold(q), fold(k), fold(v), None, causal,
                          block_q, block_k)
    return out.reshape(b, h, t, d)


def supported(q_shape, dtype, kv_len=None):
    """Whether the Pallas path handles this problem: head dim a multiple of
    the VPU lane half-count (dense MXU tiles) and BOTH sequence lengths
    multiples of the smallest block so K blocks tile exactly."""
    d = q_shape[-1]
    t = q_shape[-2]
    kv_len = t if kv_len is None else kv_len
    return d % 64 == 0 and t % 128 == 0 and kv_len % 128 == 0 and \
        jnp.dtype(dtype).name in ("float32", "bfloat16")
