"""Flash attention as a Pallas TPU kernel.

TPU-native replacement for the reference's attention compute (the reference
has no fused attention at all — MXNet 1.x predates it; BERT-era GluonNLP
composed it from batch_dot + softmax, materializing the full T×T score
matrix).  This kernel computes attention blockwise with online softmax:
O(T) memory per core instead of O(T²), MXU-shaped (Bq×D)·(D×Bk) matmuls,
fp32 accumulation regardless of input dtype.

Layout: q/k/v are (BH, T, D) — batch*heads collapsed.  Grid is
(BH, T/Bq, T/Bk) with the K dimension innermost; VMEM scratch carries the
running (m, l, acc) statistics across K steps, and the output block is
written on the last K step (the standard sequential-grid accumulation
pattern).  The backward pass is two more Pallas kernels (dq and dk/dv),
using the saved logsumexp — the flash attention recompute trick.

Key-padding masks: `kv_valid` (BH,) int32 gives each row's number of valid
keys; key columns ≥ valid are masked to -inf and K blocks entirely beyond
valid are skipped (ragged batches pay only for their real length).  The
reference-era GluonNLP BERT consumed the same information as `valid_length`.

Attention-prob dropout runs INSIDE the kernel via the TPU PRNG
(`pltpu.prng_seed` / `prng_random_bits`), seeded per (seed, bh, qblk, kblk)
so the backward kernels regenerate bit-identical masks — no T×T mask is
ever materialized.  The softmax normalizer uses the un-dropped
probabilities (standard inverted dropout on the probs).  The TPU PRNG has
no CPU/interpret lowering, so dropout>0 requires a real TPU; callers gate
via `supported()`.

Falls back to interpret mode off-TPU so tests run anywhere.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention", "mha_flash_attention", "supported"]

NEG_INF = -1e30
# Largest (Bq × Bk) f32 score block we let the kernel materialize in VMEM:
# 512×1024×4B = 2 MiB, the tuned default product.  _pick_block's single-block
# fall-through for awkward T is allowed only under this bound (VERDICT r2
# weak#6: T with no power-of-two divisor silently ran block=T at any size).
MAX_BLOCK_ELEMS = 512 * 1024


def _cdiv(a, b):
    return (a + b - 1) // b


def _interpret():
    return jax.default_backend() != "tpu"


def _keep_mask(seed_ref, b, qi, ki, rate, block_q, block_k):
    """Regenerable dropout keep-mask for score block (qi, ki) of batch b.
    Seeding immediately before the draw makes the bits a pure function of
    (seed, b, qi, ki), so fwd / dq / dkv kernels all see the same mask.
    Mosaic on some TPUs caps prng_seed at two scalar values, so the tuple
    is folded injectively into two int32 lanes: (seed ⊕ b·φ, qi·2¹⁶+ki)
    with φ = 0x9E3779B9 (odd ⇒ b·φ bijective mod 2³²) — distinct
    (b, qi, ki) give distinct lanes for a fixed seed, needing qi < 2¹⁶
    AND ki < 2¹⁶ (both hold for any T the VMEM guard admits).  The
    multiply-XOR (rather than seed+b) keeps arithmetically related seeds
    across calls — counters, seed+layer schemes — from aligning whole
    rows' masks."""
    pltpu.prng_seed(seed_ref[0] ^ (b * -1640531527), qi * 65536 + ki)
    bits = pltpu.prng_random_bits((block_q, block_k))
    bits = pltpu.bitcast(bits, jnp.uint32)
    thresh = jnp.uint32(min(int(rate * (2 ** 32)), 2 ** 32 - 1))
    return bits >= thresh


def _score_mask(s, valid, causal, qi, ki, block_q, block_k):
    """Apply causal and/or key-padding masks to a score block."""
    kpos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    if causal:
        qpos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        s = jnp.where(qpos >= kpos, s, NEG_INF)
    if valid is not None:
        s = jnp.where(kpos < valid, s, NEG_INF)
    return s


def _run_cond(causal, valid, qi, ki, block_q, block_k):
    """Whether block (qi, ki) can contribute at all: on/below the causal
    diagonal AND not entirely beyond the valid key length."""
    cond = None
    if causal:
        cond = qi * block_q + block_q - 1 >= ki * block_k
    if valid is not None:
        c = ki * block_k < valid
        cond = c if cond is None else jnp.logical_and(cond, c)
    return True if cond is None else cond


# ----------------------------------------------------------------------------
# forward
# ----------------------------------------------------------------------------
def _fwd_kernel(*refs, scale, causal, masked, rate, biased, block_q,
                block_k):
    (q_ref, k_ref, v_ref), bias_ref, valid_ref, seed_ref, tail = \
        _split_refs(refs, 3, masked, rate, biased)
    o_ref, lse_ref, m_scr, l_scr, acc_scr = tail

    b = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    valid = valid_ref[jax.lax.rem(b, _VALID_BLOCK)] if masked else None

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        # dots run in the INPUT dtype with f32 accumulation: on the MXU a
        # dot with f32 operands is emulated in multiple bf16 passes, so
        # upcasting bf16 q/k/v before the dot tripled the matmul cost for
        # precision the softmax stats (kept f32 throughout) never needed
        q = q_ref[0]                                          # (Bq, D)
        k = k_ref[0]                                          # (Bk, D)
        v = v_ref[0]                                          # (Bk, D)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if biased:
            s = s + bias_ref[0].astype(jnp.float32)           # (Bq, Bk)
        s = _score_mask(s, valid, causal, qi, ki, block_q, block_k)
        m_prev = m_scr[:, 0]                                  # (Bq,)
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur[:, None])                       # (Bq, Bk)
        # normalizer uses the un-dropped probs; only the V-accumulation is
        # dropped (inverted dropout on softmax(s))
        l_cur = l_scr[:, 0] * alpha + jnp.sum(p, axis=1)
        if rate > 0.0:
            keep = _keep_mask(seed_ref, b, qi, ki, rate, block_q, block_k)
            p_acc = jnp.where(keep, p * (1.0 / (1.0 - rate)), 0.0)
        else:
            p_acc = p
        acc_scr[:] = acc_scr[:] * alpha[:, None] + jax.lax.dot_general(
            p_acc.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_cur[:, None], m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_cur[:, None], l_scr.shape)

    run = _run_cond(causal, valid, qi, ki, block_q, block_k)
    if run is True:
        _compute()
    else:
        pl.when(run)(_compute)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:, 0]
        l_safe = jnp.maximum(l, 1e-30)
        o_ref[0] = (acc_scr[:] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0] = (m_scr[:, 0] + jnp.log(l_safe))[:, None].astype(
            jnp.float32)


def _split_refs(refs, n_fixed, masked, rate, biased=False):
    """Unpack a kernel's ref list: (fixed input refs, bias_ref, valid_ref,
    seed_ref, outputs+scratch tail).  The optional bias VMEM block comes
    right after the fixed inputs; the optional SMEM scalars follow, in
    (valid, seed) order."""
    i = n_fixed
    bias_ref = None
    if biased:
        bias_ref = refs[i]
        i += 1
    valid_ref = None
    if masked:
        valid_ref = refs[i]
        i += 1
    seed_ref = None
    if rate > 0.0:
        seed_ref = refs[i]
        i += 1
    return refs[:n_fixed], bias_ref, valid_ref, seed_ref, refs[i:]


def _bias_spec(bias, bh, bq, bk, swap=False):
    """BlockSpec for the (BHB, T, Tk) bias: BHB may be BH (per-row), H
    (shared across batch; picked via b %% H) or 1 (fully shared).  With
    swap=True the grid is (b, kblk, qblk) — the dkv kernel's order."""
    bhb = bias.shape[0]
    if bhb == bh:
        row = lambda b: b
    elif bhb == 1:
        row = lambda b: 0
    else:  # per-head, shared over batch: fold index b = batch*H + h
        h = bhb
        row = lambda b: jax.lax.rem(b, h)
    if swap:
        return pl.BlockSpec((1, bq, bk), lambda b, j, i: (row(b), i, j),
                            memory_space=pltpu.VMEM)
    return pl.BlockSpec((1, bq, bk), lambda b, i, j: (row(b), i, j),
                        memory_space=pltpu.VMEM)


# SMEM block length for the per-batch valid-key vector.  Real Mosaic
# requires rank-1 blocks to be the whole array or a multiple of the
# 128-lane tiling (interpret mode accepts (1,) blocks, the r4 chip did
# not) — so the (BH,) vector is padded to a 128 multiple, streamed in
# (128,) blocks selected by b // 128, and indexed b % 128 in-kernel.
_VALID_BLOCK = 128


def _pad_valid(kv_valid):
    bh = kv_valid.shape[0]
    padded = _cdiv(bh, _VALID_BLOCK) * _VALID_BLOCK
    if padded != bh:
        kv_valid = jnp.pad(kv_valid, (0, padded - bh))
    return kv_valid


def _extra_specs_and_args(kv_valid, seed):
    """(in_specs tail, args tail) for the optional valid/seed SMEM scalars.
    Index maps ignore the grid position except the leading batch axis."""
    specs, args = [], []
    if kv_valid is not None:
        specs.append(pl.BlockSpec((_VALID_BLOCK,),
                                  lambda b, i, j: (b // _VALID_BLOCK,),
                                  memory_space=pltpu.SMEM))
        args.append(_pad_valid(kv_valid))
    if seed is not None:
        specs.append(pl.BlockSpec((1,), lambda b, i, j: (0,),
                                  memory_space=pltpu.SMEM))
        args.append(seed)
    return specs, args


def _fwd(q, k, v, kv_valid, seed, bias, scale, causal, rate, block_q,
         block_k):
    bh, t, d = q.shape
    tk = k.shape[1]
    block_q = min(block_q, t)
    block_k = min(block_k, tk)
    grid = (bh, _cdiv(t, block_q), _cdiv(tk, block_k))
    masked = kv_valid is not None
    biased = bias is not None
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               masked=masked, rate=rate, biased=biased,
                               block_q=block_q, block_k=block_k)
    bias_specs, bias_args = ([], [])
    if biased:
        bias_specs = [_bias_spec(bias, bh, block_q, block_k)]
        bias_args = [bias]
    extra_specs, extra_args = _extra_specs_and_args(
        kv_valid, seed if rate > 0.0 else None)
    extra_specs = bias_specs + extra_specs
    extra_args = bias_args + extra_args
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ] + extra_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            # lse rides as (BH, T, 1): TPU block rules need the last two
            # block dims divisible by (8, 128) or equal to the array dims
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # running max
            pltpu.VMEM((block_q, 128), jnp.float32),   # running denom
            pltpu.VMEM((block_q, d), jnp.float32),     # output accumulator
        ],
        interpret=_interpret(),
    )(q, k, v, *extra_args)
    return out, lse


# ----------------------------------------------------------------------------
# backward: dq kernel (grid k-innermost, accumulate dq over k blocks)
# ----------------------------------------------------------------------------
def _bwd_dq_kernel(*refs, scale, causal, masked, rate, biased, block_q,
                   block_k):
    (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref), bias_ref, \
        valid_ref, seed_ref, tail = _split_refs(refs, 6, masked, rate,
                                                biased)
    if biased:
        dq_ref, db_ref, dq_scr = tail
    else:
        dq_ref, dq_scr = tail
        db_ref = None

    b = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    valid = valid_ref[jax.lax.rem(b, _VALID_BLOCK)] if masked else None

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    if biased:
        # every (qi, ki) block of d_bias must be DEFINED even when the
        # compute is skipped (causal/padding): zero first, overwrite below
        db_ref[0] = jnp.zeros_like(db_ref[0])

    def _compute():
        # native-dtype dot operands, f32 stats/accumulators (see fwd)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, 0]                                 # (Bq,)
        delta = delta_ref[0][:, 0]                             # (Bq,)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if biased:
            s = s + bias_ref[0].astype(jnp.float32)
        s = _score_mask(s, valid, causal, qi, ki, block_q, block_k)
        p = jnp.exp(s - lse[:, None])                          # (Bq, Bk)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if rate > 0.0:
            # ds = p ∘ (z/(1-r)·dp̃ − δ): δ already equals Σ p̃·dp̃ because
            # it is computed from the dropped forward output
            keep = _keep_mask(seed_ref, b, qi, ki, rate, block_q, block_k)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - rate)), 0.0)
        ds_raw = p * (dp - delta[:, None])
        if biased:
            # bias enters AFTER the qk scale: d_bias = p ∘ (dp − δ)
            db_ref[0] = ds_raw.astype(db_ref.dtype)
        ds = ds_raw * scale
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    run = _run_cond(causal, valid, qi, ki, block_q, block_k)
    if run is True:
        _compute()
    else:
        pl.when(run)(_compute)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


# ----------------------------------------------------------------------------
# backward: dk/dv kernel (grid q-innermost, accumulate dk,dv over q blocks)
# ----------------------------------------------------------------------------
def _bwd_dkv_kernel(*refs, scale, causal, masked, rate, biased, block_q,
                    block_k):
    (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref), bias_ref, \
        valid_ref, seed_ref, tail = _split_refs(refs, 6, masked, rate,
                                                biased)
    dk_ref, dv_ref, dk_scr, dv_scr = tail

    b = pl.program_id(0)
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)
    valid = valid_ref[jax.lax.rem(b, _VALID_BLOCK)] if masked else None

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _compute():
        # native-dtype dot operands, f32 stats/accumulators (see fwd)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, 0]
        delta = delta_ref[0][:, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if biased:
            s = s + bias_ref[0].astype(jnp.float32)
        s = _score_mask(s, valid, causal, qi, ki, block_q, block_k)
        p = jnp.exp(s - lse[:, None])                          # (Bq, Bk)
        if rate > 0.0:
            # same (seed, b, qi, ki) triple as fwd/dq → identical bits
            keep = _keep_mask(seed_ref, b, qi, ki, rate, block_q, block_k)
            inv = 1.0 / (1.0 - rate)
            p_drop = jnp.where(keep, p * inv, 0.0)
        else:
            keep = None
            p_drop = p
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p_drop.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if keep is not None:
            dp = jnp.where(keep, dp * (1.0 / (1.0 - rate)), 0.0)
        ds = p * (dp - delta[:, None]) * scale
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    run = _run_cond(causal, valid, qi, ki, block_q, block_k)
    if run is True:
        _compute()
    else:
        pl.when(run)(_compute)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(scale, causal, rate, block_q, block_k, res, do):
    q, k, v, kv_valid, seed, bias, out, lse = res
    bh, t, d = q.shape
    tk = k.shape[1]
    bq = min(block_q, t)
    bk = min(block_k, tk)
    masked = kv_valid is not None
    biased = bias is not None
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)[..., None]                        # (BH, T, 1)
    extra_specs, extra_args = _extra_specs_and_args(
        kv_valid, seed if rate > 0.0 else None)
    bias_specs = [_bias_spec(bias, bh, bq, bk)] if biased else []
    bias_args = [bias] if biased else []

    qspec = pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM)
    kspec = pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM)
    rowq = pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0),
                        memory_space=pltpu.VMEM)
    # d_bias is emitted PER (b, qblk, kblk) at full (BH, T, Tk) and reduced
    # to the caller's broadcast shape afterwards — the gradient of a
    # materialized bias is inherently O(T²), same as the bias itself
    out_specs = qspec
    out_shape = jax.ShapeDtypeStruct(q.shape, q.dtype)
    if biased:
        dbspec = pl.BlockSpec((1, bq, bk), lambda b, i, j: (b, i, j),
                              memory_space=pltpu.VMEM)
        out_specs = [qspec, dbspec]
        out_shape = [out_shape,
                     jax.ShapeDtypeStruct((bh, t, tk), jnp.float32)]
    dq_out = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          masked=masked, rate=rate, biased=biased,
                          block_q=bq, block_k=bk),
        grid=(bh, _cdiv(t, bq), _cdiv(tk, bk)),
        in_specs=[qspec, kspec, kspec, qspec, rowq, rowq] + bias_specs
        + extra_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta, *bias_args, *extra_args)
    if biased:
        dq, db_full = dq_out
        bhb = bias.shape[0]
        if bhb == bh:
            db = db_full
        elif bhb == 1:
            db = jnp.sum(db_full, axis=0, keepdims=True)
        else:  # per-head bias shared over batch: sum the batch groups
            db = jnp.sum(db_full.reshape(bh // bhb, bhb, t, tk), axis=0)
        db = db.astype(bias.dtype)
    else:
        dq = dq_out
        db = None

    # dk/dv: swap grid so q is innermost; index maps take (b, kblk, qblk)
    qspec2 = pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0),
                          memory_space=pltpu.VMEM)
    kspec2 = pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0),
                          memory_space=pltpu.VMEM)
    rowq2 = pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0),
                         memory_space=pltpu.VMEM)
    bias_specs2 = [_bias_spec(bias, bh, bq, bk, swap=True)] if biased else []
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          masked=masked, rate=rate, biased=biased,
                          block_q=bq, block_k=bk),
        grid=(bh, _cdiv(tk, bk), _cdiv(t, bq)),
        # the SMEM scalar index maps only use the leading batch axis, so the
        # same specs serve both backward grids
        in_specs=[qspec2, kspec2, kspec2, qspec2, rowq2, rowq2]
        + bias_specs2 + extra_specs,
        out_specs=[kspec2, kspec2],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta, *bias_args, *extra_args)
    return dq, dk, dv, None, None, db


# ----------------------------------------------------------------------------
# public entry
# ----------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10))
def _flash_core(q, k, v, kv_valid, seed, bias, scale, causal, rate,
                block_q, block_k):
    out, _ = _fwd(q, k, v, kv_valid, seed, bias, scale, causal, rate,
                  block_q, block_k)
    return out


def _flash_fwd_rule(q, k, v, kv_valid, seed, bias, scale, causal, rate,
                    block_q, block_k):
    out, lse = _fwd(q, k, v, kv_valid, seed, bias, scale, causal, rate,
                    block_q, block_k)
    return out, (q, k, v, kv_valid, seed, bias, out, lse)


_flash_core.defvjp(_flash_fwd_rule, _bwd)


def flash_attention(q, k, v, scale=None, causal=False, kv_valid=None,
                    dropout_rate=0.0, dropout_seed=None, bias=None,
                    bias_groups=None, block_q=None, block_k=None):
    """softmax(q·kᵀ·scale [+causal/padding mask])·v, blockwise.
    q/k/v: (BH, T, D).  scale defaults to 1/sqrt(D); blocks default to the
    tuned sizes.  T (for both q and k/v) must tile exactly by the chosen
    blocks — partial K blocks would feed padded garbage into the softmax.

    kv_valid: optional (BH,) int32, number of valid keys per row (≥1); key
    columns beyond it are masked out and whole K blocks beyond it skipped.
    dropout_rate/dropout_seed: attention-prob dropout inside the kernel
    (TPU only — the TPU PRNG has no interpret lowering); seed is a (1,)
    int32 array, the mask is a pure function of it so fwd/bwd agree.
    bias: optional additive attention bias (ALiBi, relative position) of
    shape (BH, T, Tk), (1, T, Tk) fully shared, or (G, T, Tk) cycling
    with period G — G MUST then be passed as bias_groups (the mha wrapper
    passes H; a bare divisor would be ambiguous between per-head and
    per-batch).  Streamed block-by-block.  The backward materializes a
    (BH, T, Tk) f32 d_bias before reducing to the bias shape — the same
    footprint the DENSE path pays for its probability matrix in the
    forward (and keeps into backward), so the kernel path is never the
    worse choice; it is simply the inherent cost of a materialized
    O(T²) bias."""
    t, tk = q.shape[1], k.shape[1]
    scale = 1.0 / math.sqrt(q.shape[-1]) if scale is None else scale
    block_q = block_q or _pick_block(t, 512)
    block_k = block_k or _pick_block(tk, 1024)
    bq, bk = min(block_q, t), min(block_k, tk)
    if t % bq or tk % bk:
        raise ValueError(
            f"flash_attention: seq lens (q={t}, kv={tk}) must be divisible "
            f"by the block sizes ({block_q}, {block_k}); gate callers with "
            "kernels.flash_attention.supported()")
    if bq * bk > MAX_BLOCK_ELEMS:
        raise ValueError(
            f"flash_attention: block ({bq}×{bk}) exceeds the VMEM-sane "
            f"bound ({MAX_BLOCK_ELEMS} elems) — likely a seq len with no "
            "power-of-two divisor fell through to a single full-T block. "
            "Pass explicit block_q/block_k or gate with supported()")
    if dropout_rate < 0.0 or dropout_rate >= 1.0:
        raise ValueError(f"dropout_rate must be in [0, 1): {dropout_rate}")
    if dropout_rate > 0.0:
        if _interpret():
            raise ValueError(
                "flash_attention: in-kernel dropout needs the TPU PRNG, "
                "which has no interpret-mode lowering; use the dense path "
                "off-TPU (parallel.attention dispatches this automatically)")
        if dropout_seed is None:
            raise ValueError("dropout_rate > 0 requires dropout_seed")
        dropout_seed = jnp.asarray(dropout_seed, jnp.int32).reshape((1,))
    else:
        dropout_seed = None
    if kv_valid is not None:
        kv_valid = jnp.asarray(kv_valid, jnp.int32).reshape((q.shape[0],))
    if bias is not None:
        bh = q.shape[0]
        ok_lead = (bias.shape[0] in (bh, 1) or
                   (bias_groups is not None and
                    bias.shape[0] == bias_groups and bh % bias_groups == 0))
        if bias.ndim != 3 or bias.shape[1:] != (t, tk) or not ok_lead:
            raise ValueError(
                f"bias shape {bias.shape} must be (BH, {t}, {tk}), "
                f"(1, {t}, {tk}), or (G, {t}, {tk}) with G passed as "
                f"bias_groups and dividing BH={bh} — a bare divisor is "
                "ambiguous between per-head and per-batch")
    return _flash_core(q, k, v, kv_valid, dropout_seed, bias, scale,
                       causal, float(dropout_rate), block_q, block_k)



def _pick_block(t, prefer):
    """Largest power-of-two block ≤ prefer that divides t, so blocks tile T
    exactly — partial K blocks would feed garbage columns into the softmax.
    t ≤ the smallest candidate is returned as-is (single block); larger T
    with no aligned divisor also falls through to a single block, which
    flash_attention() rejects when it exceeds MAX_BLOCK_ELEMS."""
    if t <= 128:
        return t
    for b in (prefer, 1024, 512, 256, 128):
        if b <= prefer and t % b == 0:
            return b
    return t  # no aligned divisor: single block covering T (size-guarded)


def mha_flash_attention(q, k, v, causal=False, valid_length=None,
                        dropout_rate=0.0, dropout_seed=None, bias=None,
                        block_q=None, block_k=None):
    """Multi-head wrapper: q/k/v are (B, H, T, D); collapses batch*heads,
    runs the Pallas kernel, restores the layout.  valid_length is per-batch
    (B,) and is broadcast across heads.  Default blocks tuned on v5e-class
    hardware: large K blocks amortize the scratch carry."""
    b, h, t, d = q.shape
    fold = lambda x: x.reshape(b * h, x.shape[2], d)
    kv_valid = None
    if valid_length is not None:
        kv_valid = jnp.repeat(jnp.asarray(valid_length, jnp.int32), h)
    kbias = None
    bias_groups = None
    if bias is not None:
        # (B|1, H|1, Tq|1, Tk|1) -> kernel layout; singleton T dims are
        # broadcast up front (the kernel streams full (T, Tk) planes)
        tk = k.shape[2]
        bb, bhh = bias.shape[0], bias.shape[1]
        full_t = bias.shape[2:] == (t, tk)
        if bb == b and bhh == h and full_t:
            kbias = bias.reshape(b * h, t, tk)
        elif bb == 1 and bhh == h and full_t:
            kbias = bias.reshape(h, t, tk)
            bias_groups = h
        elif bb == 1 and bhh == 1 and full_t:
            kbias = bias.reshape(1, t, tk)
        else:
            # singleton T/Tk dims or per-batch shared-head layouts:
            # materialize the full fold (differentiable broadcast)
            kbias = jnp.broadcast_to(bias, (b, h, t, tk)).reshape(
                b * h, t, tk)
    out = flash_attention(fold(q), fold(k), fold(v), None, causal,
                          kv_valid, dropout_rate, dropout_seed, kbias,
                          bias_groups, block_q, block_k)
    return out.reshape(b, h, t, d)


def supported(q_shape, dtype, kv_len=None, dropout_rate=0.0):
    """Whether the Pallas path handles this problem: head dim a multiple of
    the VPU lane half-count (dense MXU tiles), BOTH sequence lengths
    multiples of the smallest block so K blocks tile exactly, and — when
    attention dropout is active — a real TPU backend (the kernel PRNG has
    no interpret lowering)."""
    d = q_shape[-1]
    t = q_shape[-2]
    kv_len = t if kv_len is None else kv_len
    if dropout_rate > 0.0 and _interpret():
        return False
    return d % 64 == 0 and t % 128 == 0 and kv_len % 128 == 0 and \
        jnp.dtype(dtype).name in ("float32", "bfloat16")
