"""Paged-attention decode as a Pallas TPU kernel.

The serving runtime's decode step is one new-token query per sequence
against a **paged** KV cache: each sequence's keys/values live scattered
across fixed-size blocks of one shared pool, addressed by a per-sequence
block table (tpu_mx/serving/kv_cache.py).  Until this kernel, decode
resolved those tables on the HOST — a padded dense `(B, Lmax, H, D)`
gather per step per layer, O(total context) of memcpy with the pool
living in host memory (docs/DIVERGENCES.md #27).

This module is the native path: the flash kernel's online-softmax loop
over KV blocks (tpu_mx/kernels/flash_attention.py), re-gridded so each
program walks ONE sequence's block table with the pool resident in HBM.
The block table and the true lengths ride as **scalar-prefetch** operands
(`pltpu.PrefetchScalarGridSpec`): they are available before the kernel
body runs, so the K/V BlockSpec index maps dereference `table[b, i]`
directly and the DMA engine fetches exactly the blocks each sequence
owns — per-token decode cost becomes O(blocks-visited), and the cache
never round-trips through the host.

Shape contract (decode-specific, deliberately different from flash's
`(BH, T, D)` training layout):

- `q`: `(B, H, D)`, `(B, 1, H, D)` or `(B, Tq, H, D)` — each sequence's
  new-token queries.  `Tq == 1` is classic one-token decode; a small
  `Tq > 1` is the speculative **draft window** (ISSUE 16): the queries
  are the last `Tq` positions of the sequence (query `t` sits at
  absolute position `lengths[b] - Tq + t`) and the causal mask is
  applied per row, so one batched `(B, Tq, H, D)` call verifies a whole
  drafted token window against the same paged pool.
- `k_pool`/`v_pool`: `(num_blocks, block_size, H, D)` — ONE layer's
  shared block pool.  The last two dims are full-dim blocks, so Mosaic's
  (sublane, lane) tiling sees `(H, D)` exactly.
- `block_tables`: `(B, NB)` int32.  Row `b`'s first
  `ceil(lengths[b]/block_size)` entries are the sequence's block ids in
  position order; every entry PAST that must still be a valid pool index
  (the cache pads with block 0) — the padded fetches are finite garbage
  the length mask excludes exactly, never an out-of-bounds DMA.
- `lengths`: `(B,)` int32 true context lengths (>= 1), the new token's
  slot included.

Two arms share the math:

- :func:`paged_attention` — the Pallas kernel.  Grid `(B, NB)`, KV-block
  index innermost; VMEM scratch carries the running `(m, l, acc)` f32
  statistics across a sequence's blocks (flash's sequential-grid
  accumulation), blocks entirely past `lengths[b]` are skipped via
  `pl.when`, and the output row is written on the last block step.
  Falls back to interpret mode off-TPU — the CPU tier-1 suite exercises
  the real code path (the flash kernel's established pattern).
- :func:`paged_attention_reference` — the same block-table algorithm as
  ONE jitted XLA program (gather-by-table + masked softmax fused by the
  compiler).  Off-TPU this is the production paged arm: it keeps the
  pool device-resident and beats the per-step host dense-gather at long
  context (bench `decode_attention` micro-arm, ROUND8_NOTES.md), while
  the interpret-mode kernel stays a correctness-only tool.

No backward pass: decode is inference — there is nothing to
differentiate, and keeping the kernel forward-only is what lets the
grid stay `(B, NB)` with no logsumexp output.
"""
from __future__ import annotations

import functools
import math

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["paged_attention", "paged_attention_reference", "window_walk",
           "supported", "DEFAULT_BLOCK_SIZE"]

NEG_INF = -1e30

# Serving KV block size (tokens per pool block).  Swept on the bench
# harness (tools/paged_sweep.py -> PAGED_SWEEP_r08.json, receipts in
# ROUND8_NOTES.md): 8 loses ~20-25% on the paged arm (double the block
# walk's iteration count for the same bytes); 16/32/64 land within ~10%
# of each other, with 16 best at short context and carrying the least
# padded-tail waste and free-list fragmentation — so 16 stands.
DEFAULT_BLOCK_SIZE = 16


def _interpret():
    return jax.default_backend() != "tpu"


def _kernel(tab_ref, len_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr,
            acc_scr, *, scale, block_size, tq):
    """One (sequence, kv-block) grid step: flash's online-softmax update
    with the K dimension walking the sequence's block table.

    In-kernel layout is row-major `(Tq*H, block_size)` scores — row
    `r = t*H + h` is query-window position `t`, head `h` — so the
    running stats mirror flash's `(rows, 128)` scratch pattern with
    rows = window × heads (`Tq == 1` reduces to the original head-major
    layout exactly).  Each row carries its own causal limit: query `t`
    sits at absolute position `length - Tq + t`, so row `r` admits key
    positions `< length - (Tq - 1 - t)`.  All score/stat math is f32
    regardless of pool dtype; the dots are elementwise-mul + reduce on
    the VPU — decode attention is memory-bound (few-row queries), the
    MXU has nothing to chew on."""
    b = pl.program_id(0)
    i = pl.program_id(1)
    nb = pl.num_programs(1)
    length = len_ref[b]

    @pl.when(i == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # the LAST window row's limit is `length` itself, so the block-skip
    # guard is unchanged from the Tq=1 kernel
    @pl.when(i * block_size < length)
    def _compute():
        q = q_ref[0].astype(jnp.float32)                   # (Tq, H, D)
        k = k_ref[0].astype(jnp.float32)                   # (BS, H, D)
        v = v_ref[0].astype(jnp.float32)                   # (BS, H, D)
        h = q.shape[1]
        # s[t, h, s'] = q[t, h, :] . k[s', h, :] — head-batched window dots
        s = jnp.sum(q[:, None, :, :] * k[None, :, :, :], axis=-1)
        s = s.transpose(0, 2, 1).reshape(tq * h, -1) * scale  # (Tq*H, BS)
        kpos = i * block_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        # per-row causal limit: row r = t*H + h_ admits kpos < length -
        # (Tq - 1 - t); at Tq=1 this is exactly `kpos < length`
        row_t = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) // h
        s = jnp.where(kpos < length - (tq - 1) + row_t, s, NEG_INF)
        m_prev = m_scr[:, 0]                               # (Tq*H,)
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur[:, None])                    # (Tq*H, BS)
        l_scr[:] = jnp.broadcast_to(
            (l_scr[:, 0] * alpha + jnp.sum(p, axis=1))[:, None],
            l_scr.shape)
        # acc[t*H + h_, d] += sum_s' p[t*H + h_, s'] * v[s', h_, d]
        p3 = p.reshape(tq, h, -1).transpose(2, 0, 1)       # (BS, Tq, H)
        acc_scr[:] = acc_scr[:] * alpha[:, None] + jnp.sum(
            p3[:, :, :, None] * v[:, None, :, :], axis=0).reshape(
            tq * h, -1)
        m_scr[:] = jnp.broadcast_to(m_cur[:, None], m_scr.shape)

    @pl.when(i == nb - 1)
    def _finalize():
        l_safe = jnp.maximum(l_scr[:, 0], 1e-30)
        o_ref[0] = (acc_scr[:] / l_safe[:, None]).reshape(
            o_ref.shape[1:]).astype(o_ref.dtype)


def _normalize_q(q):
    """Accept (B, H, D) or (B, Tq, H, D); return (B, Tq, H, D) + had_t
    flag (was the caller's q 4-d already).  Shape-only: no host->device
    conversion happens here — operands flow into the jitted/pallas call
    as-is, so a numpy caller pays one C++-fast-path commit per call
    instead of an eager convert op per operand (~73us each on this
    host, measured — it dominated the per-step decode cost at short
    context).  A 3-d reshape is a view on both numpy and jax arrays."""
    if not hasattr(q, "ndim"):
        q = np.asarray(q)
    if q.ndim == 4:
        return q, True
    if q.ndim != 3:
        raise ValueError(f"paged_attention: q must be (B, H, D) or "
                         f"(B, Tq, H, D), got shape {q.shape}")
    return q.reshape(q.shape[0], 1, *q.shape[1:]), False


def _check_operands(q, k_pool, v_pool, block_tables, lengths):
    b, tq, h, d = q.shape
    if k_pool.ndim != 4 or k_pool.shape != v_pool.shape:
        raise ValueError(
            f"paged_attention: pools must be matching (num_blocks, "
            f"block_size, H, D); got {k_pool.shape} / {v_pool.shape}")
    if k_pool.shape[2:] != (h, d):
        raise ValueError(
            f"paged_attention: pool heads/dim {k_pool.shape[2:]} != query "
            f"({h}, {d})")
    if block_tables.ndim != 2 or block_tables.shape[0] != b:
        raise ValueError(
            f"paged_attention: block_tables must be (B={b}, NB); got "
            f"{block_tables.shape}")
    if lengths.shape != (b,):
        raise ValueError(
            f"paged_attention: lengths must be (B={b},); got "
            f"{lengths.shape}")


@functools.lru_cache(maxsize=128)
def _kernel_call(b, nb, block_size, tq, h, d, out_dtype, scale, interpret):
    """Build (once per static geometry) the jitted pallas_call for one
    decode shape.  The decode hot path calls this kernel once per layer
    per token — an uncached eager pallas_call would re-trace (and on a
    TPU backend re-lower through Mosaic) every single call, which would
    dwarf the O(blocks-visited) work the kernel exists to deliver.  The
    jit wrapper carries the compilation cache; the lru key is exactly
    the set of values baked into the trace (the draft-window width `tq`
    included — each window width is its own grid geometry)."""
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,           # (block_tables, lengths)
        grid=(b, nb),
        in_specs=[
            pl.BlockSpec((1, tq, h, d),
                         lambda sb, i, tab, lens: (sb, 0, 0, 0)),
            pl.BlockSpec((1, block_size, h, d),
                         lambda sb, i, tab, lens: (tab[sb, i], 0, 0, 0)),
            pl.BlockSpec((1, block_size, h, d),
                         lambda sb, i, tab, lens: (tab[sb, i], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, tq, h, d),
                               lambda sb, i, tab, lens: (sb, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((tq * h, 128), jnp.float32),   # running max
            pltpu.VMEM((tq * h, 128), jnp.float32),   # running denom
            pltpu.VMEM((tq * h, d), jnp.float32),     # output accumulator
        ],
    )
    return jax.jit(pl.pallas_call(
        functools.partial(_kernel, scale=scale, block_size=block_size,
                          tq=tq),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, tq, h, d), jnp.dtype(out_dtype)),
        interpret=interpret,
    ))


def paged_attention(q, k_pool, v_pool, block_tables, lengths, scale=None):
    """Decode attention over a paged KV pool (see module docstring).

    Returns `(B, H, D)` (or `(B, Tq, H, D)` matching a 4-d `q`) in
    `q.dtype`.  `block_tables` entries beyond each row's real blocks
    must be valid pool indices (0-padding per the cache contract);
    `lengths` masks them out exactly."""
    q, had_t = _normalize_q(q)
    block_tables = _as_i32(block_tables)
    lengths = _as_i32(lengths)
    _check_operands(q, k_pool, v_pool, block_tables, lengths)
    b, tq, h, d = q.shape
    block_size = k_pool.shape[1]
    nb = block_tables.shape[1]
    scale = 1.0 / math.sqrt(d) if scale is None else scale
    fn = _kernel_call(b, nb, block_size, tq, h, d, jnp.dtype(q.dtype).name,
                      float(scale), _interpret())
    out = fn(block_tables, lengths, q, k_pool, v_pool)
    return out if had_t else out[:, 0]


def _as_i32(x):
    """int32 view without an eager device op: numpy stays numpy (the jit
    boundary commits it on the C++ fast path), jax arrays only convert
    when the dtype is actually wrong."""
    if isinstance(x, np.ndarray) or not hasattr(x, "devices"):
        return np.asarray(x, np.int32)
    return x if x.dtype == jnp.int32 else x.astype(jnp.int32)


def window_walk(q, k_pool, v_pool, block_tables, lengths, scale):
    """The kernel's block walk as lax.scan + per-block dynamic indexing,
    vmapped over the batch — plain traceable jax, so the fused decode
    step (serving/jax_model.py) can inline it into ITS jitted program
    against the donated pool without a nested dispatch boundary.

    `q` is the canonical `(B, Tq, H, D)` window; returns the same
    shape.  NOT a gather-then-softmax: materializing the padded
    `(B, Lmax, H, D)` batch in-program and re-reading it through the
    einsum/softmax passes measured ~3x slower at bench contexts on the
    CPU backend — the online-softmax walk reads each pool byte once,
    exactly like the Pallas grid does."""
    b, tq, h, d = q.shape
    bs = k_pool.shape[1]
    qf = q.astype(jnp.float32)

    def one_row(tab, length, qr):
        # query t sits at absolute position length - Tq + t -> admits
        # key positions strictly below length - (Tq - 1 - t)
        limit = length - (tq - 1) + jnp.arange(tq, dtype=jnp.int32)

        def step(carry, bid):
            m, l, acc, i = carry
            k = jax.lax.dynamic_index_in_dim(k_pool, bid, 0,
                                             keepdims=False)
            v = jax.lax.dynamic_index_in_dim(v_pool, bid, 0,
                                             keepdims=False)
            s = jnp.einsum("thd,shd->ths", qr,
                           k.astype(jnp.float32)) * scale
            kpos = i * bs + jnp.arange(bs, dtype=jnp.int32)
            s = jnp.where(kpos[None, None, :] < limit[:, None, None],
                          s, NEG_INF)
            m_cur = jnp.maximum(m, jnp.max(s, axis=2))
            alpha = jnp.exp(m - m_cur)
            p = jnp.exp(s - m_cur[:, :, None])
            l = l * alpha + jnp.sum(p, axis=2)
            acc = acc * alpha[:, :, None] + jnp.einsum(
                "ths,shd->thd", p, v.astype(jnp.float32))
            return (m_cur, l, acc, i + 1), None

        init = (jnp.full((tq, h), NEG_INF, jnp.float32),
                jnp.zeros((tq, h), jnp.float32),
                jnp.zeros((tq, h, d), jnp.float32), jnp.int32(0))
        (_, l, acc, _), _ = jax.lax.scan(step, init, tab)
        return acc / jnp.maximum(l, 1e-30)[:, :, None]

    # output cast happens in-trace (free at dispatch time): the decode
    # contract is out.dtype == q.dtype on every arm
    return jax.vmap(one_row)(block_tables, lengths, qf).astype(q.dtype)


_reference_impl = functools.partial(jax.jit, static_argnames=("scale",))(
    window_walk)


def paged_attention_reference(q, k_pool, v_pool, block_tables, lengths,
                              scale=None):
    """The kernel's algorithm as one jitted XLA program — same operands,
    same masking contract, same online-softmax-over-blocks walk in f32.
    The off-TPU production paged arm (and the kernel's parity oracle):
    the table walk happens inside the compiled program against the
    resident pool, so a decode step costs one dispatch — no O(context)
    host memcpy pass, no materialized padded batch."""
    q, had_t = _normalize_q(q)
    block_tables = _as_i32(block_tables)
    lengths = _as_i32(lengths)
    _check_operands(q, k_pool, v_pool, block_tables, lengths)
    scale = 1.0 / math.sqrt(q.shape[-1]) if scale is None else float(scale)
    out = _reference_impl(q, k_pool, v_pool, block_tables, lengths, scale)
    return out if had_t else out[:, 0]


def supported(head_dim, dtype, block_size=DEFAULT_BLOCK_SIZE):
    """Whether the real-Mosaic kernel should take this decode on a TPU
    backend: head_dim a multiple of the dense-tile lane count and a
    native MXU dtype (the flash kernel's gate), block_size sublane-
    aligned.  Interpret mode (off-TPU) accepts anything — it is
    correctness-only and callers route production decode through
    :func:`paged_attention_reference` there."""
    if _interpret():
        return True
    return (head_dim % 64 == 0 and block_size % 8 == 0 and
            jnp.dtype(dtype).name in ("float32", "bfloat16"))
