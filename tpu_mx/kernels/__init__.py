"""Pallas TPU kernels: the hand-tuned hot ops of the framework
(the analog of the reference's cuDNN/hand-CUDA kernels under
REF:src/operator/ — here written against the MXU/VMEM model)."""
from . import flash_attention
from . import paged_attention
from .flash_attention import flash_attention as flash_attention_fn
from .flash_attention import mha_flash_attention
from .paged_attention import paged_attention as paged_attention_fn
from .paged_attention import paged_attention_reference
