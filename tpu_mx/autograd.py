"""Imperative autograd: `record() / pause() / backward()` over a Python tape.

TPU-native analog of the reference's autograd (REF:src/imperative/imperative.cc
``Imperative::RecordOp/Backward``, REF:python/mxnet/autograd.py).  The reference
records an NNVM tape of FGradient closures; here every recorded op stores the
``jax.vjp`` pullback of its pure function.  ``backward()`` walks the tape in
reverse creation order accumulating cotangents — the same semantics
(grad_req write/add, head gradients, retain_graph) without a graph IR, because
XLA is the graph layer underneath.
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "record", "pause", "train_mode", "predict_mode",
    "is_recording", "is_training", "mark_variables", "backward", "grad",
    "Function", "get_symbol",
]


class _State(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False
        self.tape = []


_STATE = _State()


class _TapeNode:
    """One recorded op: pullback + input/output bookkeeping.

    Outputs are held by strong reference: the cotangent accumulator keys on
    id(), so an output collected mid-graph would let CPython reuse its id and
    misroute cotangents — keeping outputs alive until the tape is dropped
    makes id() keys sound (the reference ties graph lifetime to NDArray
    refcounts the same way)."""

    __slots__ = ("vjp_fn", "inputs", "outputs", "out_meta", "out_ids", "name")

    def __init__(self, vjp_fn, inputs, outputs, name=""):
        self.vjp_fn = vjp_fn
        self.inputs = inputs                       # list[NDArray] (strong refs keep leaves alive)
        self.outputs = list(outputs)
        self.out_meta = [(o.shape, o.dtype) for o in outputs]
        self.out_ids = [id(o) for o in outputs]
        self.name = name


# ----------------------------------------------------------------------------
# recording scopes
# ----------------------------------------------------------------------------
class _RecordingScope:
    def __init__(self, recording, training):
        self._rec, self._train = recording, training

    def __enter__(self):
        self._old = (_STATE.recording, _STATE.training)
        if self._rec is not None and self._rec != _STATE.recording:
            # tape boundary: a pending fused op segment must flush under
            # the recording state its ops were issued in, so fusion never
            # tapes (or skips taping) ops across a record()/pause() edge
            from . import fusion
            fusion.flush("tape_boundary")
        if self._rec and not _STATE.recording:
            # entering the outermost record scope starts a fresh graph; a
            # prior recorded-but-never-backwarded forward (e.g. an aborted
            # step) is dropped here, bounding tape memory
            _STATE.tape = []
        if self._rec is not None:
            _STATE.recording = self._rec
        if self._train is not None:
            _STATE.training = self._train
        return self

    def __exit__(self, *exc):
        if _STATE.recording != self._old[0]:
            from . import fusion
            fusion.flush("tape_boundary")
        _STATE.recording, _STATE.training = self._old
        return False


def record(train_mode=True):
    """``with autograd.record():`` — start taping ops (and set train mode)."""
    return _RecordingScope(True, train_mode)


def pause(train_mode=False):
    return _RecordingScope(False, train_mode)


def train_mode():
    return _RecordingScope(None, True)


def predict_mode():
    return _RecordingScope(None, False)


def is_recording():
    return _STATE.recording


def is_training():
    return _STATE.training


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers to arrays (reference: MXAutogradMarkVariables)."""
    if not isinstance(variables, (list, tuple)):
        variables, gradients = [variables], [gradients]
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad = g
        v._grad_req = req


# ----------------------------------------------------------------------------
# tape write path (called from ndarray._imperative_invoke)
# ----------------------------------------------------------------------------
def _needs_tape(arrays):
    return _STATE.recording and any(
        getattr(a, "_grad", None) is not None or getattr(a, "_tape_node", None) is not None
        for a in arrays
    )


def _record_op(vjp_fn, inputs, outputs, name=""):
    node = _TapeNode(vjp_fn, inputs, outputs, name)
    for o in outputs:
        o._tape_node = node
    _STATE.tape.append(node)
    return node


# ----------------------------------------------------------------------------
# backward
# ----------------------------------------------------------------------------
def _zero_ct(shape, dtype):
    """Zero cotangent for an unused output.  Integer outputs (frexp's
    exponent, argmax-style companions) have JAX cotangent type float0."""
    import numpy as _np
    if not jnp.issubdtype(dtype, jnp.inexact):
        from jax.dtypes import float0
        return _np.zeros(shape, dtype=float0)
    return jnp.zeros(shape, dtype)


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Reverse-accumulate gradients from ``heads`` into every leaf with an
    attached grad buffer.  Matches reference semantics: default head gradient
    is ones; ``grad_req='add'`` accumulates across backward calls."""
    from .ndarray import NDArray  # late import (cycle)
    from . import fusion
    fusion.flush("backward")  # heads/tape must be realized before the walk

    if not isinstance(heads, (list, tuple)):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]

    # cotangent accumulator keyed by output NDArray identity
    cot = {}
    for h, hg in zip(heads, head_grads):
        if hg is None:
            g = jnp.ones(h.shape, h.dtype)
        else:
            g = hg._data if isinstance(hg, NDArray) else jnp.asarray(hg)
        cot[id(h)] = cot[id(h)] + g if id(h) in cot else g

    tape = _STATE.tape
    leaf_grads = {}  # id(leaf NDArray) -> (leaf, accumulated grad)

    for node in reversed(tape):
        outs_ct = [cot.get(oid) for oid in node.out_ids]
        if all(c is None for c in outs_ct):
            continue
        full_ct = tuple(
            c if c is not None else _zero_ct(shape, dtype)
            for c, (shape, dtype) in zip(outs_ct, node.out_meta)
        )
        in_cts = node.vjp_fn(full_ct if len(full_ct) > 1 else full_ct[0])
        for inp, ict in zip(node.inputs, in_cts):
            if ict is None:
                continue
            if getattr(inp, "_grad", None) is not None:
                key = id(inp)
                if key in leaf_grads:
                    leaf_grads[key] = (inp, leaf_grads[key][1] + ict)
                else:
                    leaf_grads[key] = (inp, ict)
            if getattr(inp, "_tape_node", None) is not None:
                key = id(inp)
                cot[key] = cot[key] + ict if key in cot else ict

    for leaf, g in leaf_grads.values():
        g = g.astype(leaf.dtype)
        if leaf._grad_req == "add":
            leaf._grad._data = leaf._grad._data + g
        elif leaf._grad_req != "null":
            leaf._grad._data = g

    if not retain_graph:
        for node in tape:
            node.vjp_fn = None
        _STATE.tape = []


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False,
         train_mode=True):
    """Functional-style gradient (reference: mx.autograd.grad): returns grads of
    ``heads`` w.r.t. ``variables`` without touching attached .grad buffers."""
    from .ndarray import NDArray

    single = not isinstance(variables, (list, tuple))
    if single:
        variables = [variables]
    saved = [(v, getattr(v, "_grad", None), getattr(v, "_grad_req", "write")) for v in variables]
    tape_backup = list(_STATE.tape)
    try:
        for v in variables:
            v._grad = NDArray(jnp.zeros(v.shape, v.dtype))
            v._grad_req = "write"
        backward(heads, head_grads, retain_graph=True, train_mode=train_mode)
        results = [v._grad for v in variables]
    finally:
        for (v, g, req) in saved:
            v._grad, v._grad_req = g, req
        if retain_graph:
            _STATE.tape = tape_backup
        else:
            _STATE.tape = []
    return results[0] if single else results


def get_symbol(x):  # reference API parity: symbolic extraction is not applicable
    raise NotImplementedError(
        "get_symbol: the TPU-native stack has no NNVM symbol; use HybridBlock.export()"
    )


# ----------------------------------------------------------------------------
# custom differentiable functions (reference: mx.autograd.Function)
# ----------------------------------------------------------------------------
class Function:
    """User-defined op with custom forward/backward, reference-compatible:

        class Sigmoid(Function):
            def forward(self, x): ...  (NDArray math, saves with self.save_for_backward)
            def backward(self, dy): ... (returns grads for each forward input)
    """

    def __init__(self):
        self._saved = ()

    def save_for_backward(self, *arrays):
        self._saved = arrays

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray import NDArray

        with pause():
            outputs = self.forward(*inputs)
        single_out = not isinstance(outputs, (list, tuple))
        outs = [outputs] if single_out else list(outputs)

        if _needs_tape(inputs):
            fn = self

            def vjp_fn(out_ct):
                cts = (out_ct,) if single_out else tuple(out_ct)
                with pause():
                    in_grads = fn.backward(*[NDArray(c) for c in cts])
                if not isinstance(in_grads, (list, tuple)):
                    in_grads = [in_grads]
                return tuple(
                    (g._data if isinstance(g, NDArray) else g) if g is not None else None
                    for g in in_grads
                )

            _record_op(vjp_fn, list(inputs), outs, name=type(self).__name__)
        return outs[0] if single_out else outs
