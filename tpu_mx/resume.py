"""Deterministic resume: versioned training-state capsules.

Durable checkpoints (tpu_mx/checkpoint.py) and the self-healing supervisor
(tpu_mx/supervisor.py) made recovery *survivable*; this module makes it
*reproducible*.  A restart that restores only weights silently resets the
JAX global PRNG key, numpy's host RNG and every ``DataIter``'s shuffle/
cursor state, so the recovered run re-feeds or skips batches and diverges
from the run that crashed.  A **capsule** snapshots the rest of the
training state — RNG streams, data position, loop cursor — so a recovered
run replays the exact run that died, batch for batch, bit for bit
(tests/test_supervisor.py's bit-identical-resume proof; the ``soak`` CI
tier gates on it).

Two capsule kinds, one JSON format (:data:`CAPSULE_FORMAT`):

- **Epoch capsule** — ``prefix-NNNN.capsule.json``, written with each
  epoch's durable checkpoint and listed in its manifest (so it is
  size+sha256 *verified* like every other checkpoint file).  Restoring it
  resumes at the epoch boundary with the exact RNG stream and the exact
  next-epoch shuffle.
- **Step capsule** — a rolling ``prefix-step.capsule.json`` written every
  ``interval`` committed steps, plus a ``.state`` sidecar holding the
  mid-epoch train state (weights/optimizer — any object with
  ``state_dict()/load_state_dict()``: a ``parallel.CompiledTrainStep``,
  or :class:`ModuleState` over a Module).  The sidecar is written FIRST
  and its size+sha256 ride the capsule (the commit point), so a crash
  between the two is detected and falls back to the epoch boundary.
  Restoring it resumes at the exact batch.

What a capsule captures: ``mx.random`` state (global JAX key + numpy host
state), every registered iterator's ``state_dict()`` (epoch permutation,
cursor, private RNG), and the supervisor's loop cursor + the numeric
sentinel's skip ledger.  What it deliberately does NOT capture: weights
(epoch checkpoints / the step sidecar own those), compression
error-feedback (per-device, excluded from checkpoints — DIVERGENCES #13),
the native C++ image pipeline's internal cursors (use ``use_native=False``
for deterministic resume), and profiler/telemetry state.

Versioning: this build WRITES ``format: tpu_mx-capsule-v2`` and READS v1
and v2.  v2 (ISSUE 17, elastic fleets) records the data-stream position
in GLOBAL sample space — the sharded ``NDArrayIter``'s global cursor +
permutation plus a ``world`` map (num_workers/rank/fleet generation) —
so an N-world capsule restores into an M-world run exactly: iterators
re-partition from the global cursor (``io.NDArrayIter.set_shard``), and
the batch sequence the M-world run consumes is identical to the one the
N-world run would have consumed next.  v1 capsules (whole-stream or
per-worker LOCAL cursors — indistinguishable from the file alone) still
restore on the same-world unsharded path; restoring one across a
world-size change is refused with the gap surfaced via
``resume.resume_step_gap``, never guessed.  A reader that sees an
unknown format (or a torn sidecar, or a stale step capsule superseded by
a newer epoch) logs why and falls back to the next-coarser recovery
point — epoch capsule, then plain weights-only resume.

Telemetry: ``resume.capsules_written{kind}``, ``resume.capsule_restore_seconds``
and the ``resume.resume_step_gap`` gauge (batches whose consumption cannot
be replayed exactly — 0 whenever a capsule restored; the soak tier fails
if it is ever nonzero).
"""
from __future__ import annotations

import base64
import json
import logging
import os
import pickle
import time

import numpy as np

from .base import MXNetError
from . import checkpoint as _ckpt
from . import random as _random
from . import telemetry as _telemetry
from . import tracing as _tracing

__all__ = ["CAPSULE_FORMAT", "CAPSULE_FORMAT_V1", "CAPSULE_FORMATS",
           "CapsuleManager", "ModuleState",
           "encode_state", "decode_state", "capsule_path",
           "step_capsule_path", "step_state_path", "read_capsule"]

log = logging.getLogger(__name__)

CAPSULE_FORMAT_V1 = "tpu_mx-capsule-v1"
#: the format this build WRITES (v2: global-cursor data positions + world map)
CAPSULE_FORMAT = "tpu_mx-capsule-v2"
#: the formats this build READS (v1 restores on the same-world path only)
CAPSULE_FORMATS = (CAPSULE_FORMAT_V1, CAPSULE_FORMAT)


# ---------------------------------------------------------------------------
# JSON-safe state encoding
# ---------------------------------------------------------------------------
def encode_state(obj):
    """Deep-encode a state tree into JSON-safe values.  ndarrays become
    ``{"__ndarray__": {dtype, shape, data}}`` with a base64 payload of the
    raw bytes — exact representation, not repr: bit-exactness is the
    entire point of a capsule."""
    if isinstance(obj, np.ndarray):
        return {"__ndarray__": {
            "dtype": str(obj.dtype), "shape": list(obj.shape),
            "data": base64.b64encode(
                np.ascontiguousarray(obj).tobytes()).decode("ascii")}}
    if isinstance(obj, np.generic):
        return obj.item()
    if isinstance(obj, (list, tuple)):
        return [encode_state(x) for x in obj]
    if isinstance(obj, dict):
        return {str(k): encode_state(v) for k, v in obj.items()}
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if hasattr(obj, "__array__"):  # jax arrays / NDArray-likes
        return encode_state(np.asarray(obj))
    raise MXNetError(
        f"capsule cannot encode a {type(obj).__name__} — state_dict trees "
        "must contain only arrays, scalars, strings, lists and dicts")


def decode_state(obj):
    """Inverse of :func:`encode_state` (tuples come back as lists — the
    consumers here normalize where tuple-ness matters)."""
    if isinstance(obj, dict):
        nd = obj.get("__ndarray__")
        if nd is not None and set(obj) == {"__ndarray__"}:
            arr = np.frombuffer(base64.b64decode(nd["data"]),
                                dtype=np.dtype(nd["dtype"]))
            return arr.reshape(nd["shape"]).copy()
        return {k: decode_state(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [decode_state(v) for v in obj]
    return obj


def _np_tree(obj):
    """Device/NDArray leaves → host numpy, preserving tree structure
    (incl. namedtuple optimizer states) — the step sidecar must never
    pickle live device buffers."""
    if isinstance(obj, dict):
        return {k: _np_tree(v) for k, v in obj.items()}
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):  # namedtuple
        return type(obj)(*(_np_tree(v) for v in obj))
    if isinstance(obj, tuple):
        return tuple(_np_tree(v) for v in obj)
    if isinstance(obj, list):
        return [_np_tree(v) for v in obj]
    if hasattr(obj, "asnumpy"):
        return obj.asnumpy()
    if hasattr(obj, "__array__") and not isinstance(obj, np.ndarray):
        return np.asarray(obj)
    return obj


def _jax_tree(obj):
    """numpy leaves → jax arrays (restore side of :func:`_np_tree`)."""
    import jax.numpy as jnp
    if isinstance(obj, dict):
        return {k: _jax_tree(v) for k, v in obj.items()}
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):
        return type(obj)(*(_jax_tree(v) for v in obj))
    if isinstance(obj, tuple):
        return tuple(_jax_tree(v) for v in obj)
    if isinstance(obj, list):
        return [_jax_tree(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return jnp.asarray(obj)
    return obj


# ---------------------------------------------------------------------------
# paths
# ---------------------------------------------------------------------------
def capsule_path(prefix, epoch):
    return f"{prefix}-{int(epoch):04d}.capsule.json"


def step_capsule_path(prefix):
    return f"{prefix}-step.capsule.json"


def step_state_path(prefix):
    return f"{prefix}-step.capsule.state"


def read_capsule(path):
    """Parse a capsule file; returns the dict or None (missing/unreadable/
    unknown format — logged, never raised: a bad capsule degrades to the
    next-coarser recovery point, it must not kill the resume)."""
    try:
        with open(path, encoding="utf-8") as f:
            cap = json.load(f)
    except (OSError, ValueError) as e:
        if os.path.exists(path):
            log.warning("capsule %s unreadable (%s) — ignoring", path, e)
        return None
    if not isinstance(cap, dict) or cap.get("format") not in CAPSULE_FORMATS:
        log.warning("capsule %s has unknown format %r (this build reads "
                    "%s) — ignoring", path,
                    cap.get("format") if isinstance(cap, dict) else None,
                    "/".join(CAPSULE_FORMATS))
        return None
    return cap


# ---------------------------------------------------------------------------
# the manager
# ---------------------------------------------------------------------------
class CapsuleManager:
    """Snapshots and restores the non-weight training state.

    ``prefix`` — the checkpoint prefix capsules live next to (the epoch
    capsule rides that prefix's per-epoch manifest).
    ``iters`` — DataIters implementing ``state_dict``/``load_state_dict``
    whose position the capsule carries.
    ``state`` — optional object with ``state_dict()``/``load_state_dict()``
    (a ``parallel.CompiledTrainStep``, or :class:`ModuleState`) captured
    into the step capsule's sidecar so mid-epoch resume has mid-epoch
    weights; without it, step capsules are not usable for mid-epoch
    resume and recovery falls back to the epoch boundary.
    ``interval`` — committed steps between step capsules (0 = epoch
    capsules only).
    ``fleet`` — optional :class:`tpu_mx.parallel.fleet.Fleet`; when set,
    the capsule's ``world`` map records this worker's (rank, num_workers)
    and the fleet generation it was captured under (otherwise the map is
    derived from the registered iterators' shard placement).

    Wire it to a supervisor with ``Supervisor(capsule=mgr)`` /
    ``sup.attach_capsule(mgr)`` (or ``module.fit(supervised=Supervise(
    prefix=..., capsule=True, capsule_interval=N))``); the supervisor
    calls :meth:`on_step` / :meth:`on_epoch` / :meth:`restore` at the
    right points."""

    def __init__(self, prefix, iters=(), state=None, interval=0, fleet=None):
        if not prefix:
            raise MXNetError("CapsuleManager needs a checkpoint prefix")
        self.prefix = prefix
        self.iters = list(iters)
        self.state = state
        self.fleet = fleet
        self.interval = int(interval)
        self.supervisor = None     # back-ref set by Supervisor.attach_capsule
        self._written_epoch = None
        for it in self.iters:
            # fail fast, BEFORE any training: an iterator that cannot
            # snapshot (e.g. the native image pipeline) would otherwise
            # surface as a fatal NotImplementedError only at the first
            # epoch's capsule write, after a full epoch of work — with no
            # checkpoint committed for it
            try:
                it.state_dict()
            except NotImplementedError as e:
                raise MXNetError(
                    f"CapsuleManager: {type(it).__name__} cannot snapshot "
                    f"({e}) — deterministic resume needs state_dict "
                    "support on every registered iterator") from e

    # -- capture ------------------------------------------------------------
    def _world(self):
        """The (rank, num_workers, generation) this capsule is captured
        under — from the fleet when attached, else from the registered
        iterators' shard placement (unsharded pipelines record the static
        1-worker world)."""
        if self.fleet is not None:
            rank = 0
            try:
                rank = self.fleet.shard()[0]
            except MXNetError:
                pass  # controller-only handles have no member slot
            return {"num_workers": max(1, self.fleet.acked_world_size),
                    "rank": int(rank),
                    "generation": int(self.fleet.acked_generation)}
        n = max([int(getattr(it, "num_workers", 1))
                 for it in self.iters] or [1])
        ranks = [int(getattr(it, "rank", 0)) for it in self.iters
                 if int(getattr(it, "num_workers", 1)) == n]
        return {"num_workers": n, "rank": ranks[0] if ranks else 0,
                "generation": 0}

    def _sharded(self):
        return self._world()["num_workers"] > 1

    def _body(self, epoch, step, sup=None):
        sup = sup if sup is not None else self.supervisor
        body = {"format": CAPSULE_FORMAT,
                "epoch": int(epoch), "step": int(step),
                "wall_time": time.time(),
                "world": self._world(),
                "rng": encode_state(_random.get_state()),
                "iters": [encode_state(it.state_dict())
                          for it in self.iters]}
        if sup is not None:
            body["supervisor"] = encode_state({
                "steps": int(sup.steps),
                "batches_skipped": int(sup.batches_skipped),
                "sentinel": sup.sentinel.state_dict()})
            # the fingerprint history rides the capsule: after a
            # corruption rollback the survivors resume knowing which
            # step was last cross-replica VERIFIED, not merely saved
            if getattr(sup, "integrity", None) is not None:
                body["integrity"] = encode_state(
                    sup.integrity.state_dict())
        return body

    def write_epoch_file(self, epoch, sup=None):
        """Write the epoch capsule and return its path.  Cooperative
        callers (``elastic.save_checkpoint(capsule=)``, ``for_module``'s
        save_fn) call this BEFORE the manifest commit and list the path in
        the manifest, so the capsule is verified with the checkpoint."""
        path = capsule_path(self.prefix, epoch)
        sup = sup if sup is not None else self.supervisor
        step = sup.step_in_epoch if sup is not None else 0
        body = self._body(epoch, step, sup)
        with _ckpt.atomic_write(path, "w") as f:
            f.write(json.dumps(body, sort_keys=True))
        self._written_epoch = int(epoch)
        _telemetry.counter("resume.capsules_written", kind="epoch").inc()
        _tracing.emit("resume.capsule_write", kind="epoch",
                      epoch=int(epoch), step=int(step))
        return path

    def on_epoch(self, epoch, sup=None):
        """Post-save hook (the supervisor calls it after ``save_fn``):
        write the epoch capsule if the saver didn't (folding it into the
        epoch's manifest), then retire the now-superseded step capsule."""
        if self._written_epoch != int(epoch):
            path = self.write_epoch_file(epoch, sup)
            _ckpt.update_manifest(self.prefix, epoch, [path])
        self._discard_step_capsule()

    def on_step(self, sup):
        """Per-committed-step hook: write the rolling step capsule every
        ``interval`` steps."""
        if self.interval and sup.step_in_epoch % self.interval == 0:
            self.write_step(sup)

    def write_step(self, sup=None):
        """Write the rolling step capsule (+ train-state sidecar when a
        ``state`` object is attached).  Sidecar first; its size+sha256
        ride the capsule, making the capsule the commit point of the
        pair."""
        sup = sup if sup is not None else self.supervisor
        epoch = sup._epoch if sup is not None else 0
        step = sup.step_in_epoch if sup is not None else 0
        body = self._body(epoch or 0, step, sup)
        if self.state is not None:
            spath = step_state_path(self.prefix)
            payload = pickle.dumps(_np_tree(self.state.state_dict()),
                                   protocol=pickle.HIGHEST_PROTOCOL)
            with _ckpt.atomic_write(spath) as f:
                f.write(payload)
            body["state_file"] = {"name": os.path.basename(spath),
                                  **_ckpt._file_entry(spath)}
        with _ckpt.atomic_write(step_capsule_path(self.prefix), "w") as f:
            f.write(json.dumps(body, sort_keys=True))
        _telemetry.counter("resume.capsules_written", kind="step").inc()
        _tracing.emit("resume.capsule_write", kind="step",
                      epoch=int(epoch or 0), step=int(step))

    def _discard_step_capsule(self):
        for p in (step_capsule_path(self.prefix),
                  step_state_path(self.prefix)):
            try:
                os.remove(p)
            except OSError:
                pass

    # -- restore ------------------------------------------------------------
    def _format_usable(self, cap):
        """Why-not string for a capsule's FORMAT, or None when usable.

        v1 capsules recorded data positions without a world map — a v1
        file from an old N-world run holds per-worker LOCAL cursors that
        cannot be re-partitioned, and the file alone cannot prove it was
        whole-stream.  So v1 restores only on the same-world unsharded
        path (where its fields mean exactly what they always meant);
        under a sharded pipeline it is refused and the caller surfaces
        the gap — never guesses."""
        if cap.get("format") != CAPSULE_FORMAT_V1:
            return None
        if self._sharded():
            return ("capsule v1 predates the global-cursor format — its "
                    "cursors cannot be re-partitioned across a world-size "
                    "change; resuming without it, gap surfaced")
        return None

    def _step_usable(self, cap, resume_from):
        """Why-not string, or None when the step capsule can resume the
        exact batch (readable format for this world, epoch not
        superseded, sidecar present and hash-verified)."""
        why = self._format_usable(cap)
        if why is not None:
            return why
        if self.state is None or cap.get("state_file") is None:
            return ("no train-state sidecar — mid-epoch weights "
                    "unavailable, resuming at the epoch boundary")
        if int(cap.get("epoch", -1)) < int(resume_from):
            return "stale (a newer epoch checkpoint supersedes it)"
        sf = cap["state_file"]
        spath = step_state_path(self.prefix)
        if not os.path.exists(spath):
            return "train-state sidecar missing"
        if os.path.getsize(spath) != int(sf.get("size", -1)) or \
                _ckpt.sha256_file(spath) != sf.get("sha256"):
            return "train-state sidecar torn/corrupt (size/sha mismatch)"
        return None

    def _apply(self, cap, sup):
        _random.set_state(decode_state(cap["rng"]))
        states = [decode_state(s) for s in cap.get("iters", [])]
        if len(states) != len(self.iters):
            raise MXNetError(
                f"capsule carries {len(states)} iterator state(s) but the "
                f"manager registers {len(self.iters)} — resume must "
                "reconstruct the same data pipeline")
        for it, s in zip(self.iters, states):
            it.load_state_dict(s)
        if sup is not None and "supervisor" in cap:
            s = decode_state(cap["supervisor"])
            sup.sentinel.load_state_dict(s.get("sentinel", {}))
            sup.batches_skipped = max(sup.batches_skipped,
                                      int(s.get("batches_skipped", 0)))
            sup.steps = max(sup.steps, int(s.get("steps", 0)))
        if sup is not None and "integrity" in cap \
                and getattr(sup, "integrity", None) is not None:
            sup.integrity.load_state_dict(decode_state(cap["integrity"]))

    def restore(self, sup=None, resume_from=0, use_step=True):
        """Called after the weights restore (``restore_fn`` /
        ``elastic.auto_resume``) landed on the newest verified epoch;
        returns the epoch to resume FROM.

        Preference order: usable step capsule (exact batch — restores RNG,
        iterators, sentinel ledger AND the mid-epoch train state from the
        sidecar, arming the supervisor's mid-epoch position) → epoch
        capsule (epoch boundary, exact RNG/shuffle; any mid-epoch progress
        is *replayed* deterministically, not lost) → nothing (weights-only
        resume; the ``resume.resume_step_gap`` gauge records the batches
        that can no longer be replayed exactly).

        ``use_step=False`` is the numeric-rollback path: the step capsule
        is *discarded* (it holds the state that produced the divergence)
        and the epoch capsule is deliberately NOT applied either — rewinding
        the RNG/shuffle would make the retry a bit-identical replay that
        provably re-diverges at the same step until the rollback budget
        degrades; leaving the live streams running re-randomizes the
        retried epoch (a fresh permutation still covers every sample),
        which is the only retry that can actually escape a deterministic
        divergence."""
        sup = sup if sup is not None else self.supervisor
        t0 = time.perf_counter()
        gap = 0
        out = int(resume_from)
        used = "none"
        resumed_step = 0
        try:
            if not use_step:
                used = "discarded"
                log.warning(
                    "numeric rollback: discarding the step capsule (it "
                    "holds the diverged trajectory) and keeping the live "
                    "RNG/shuffle streams — an exact replay would diverge "
                    "again at the same step")
                self._discard_step_capsule()
                return out
            step_cap = read_capsule(step_capsule_path(self.prefix))
            why = self._step_usable(step_cap, resume_from) \
                if step_cap is not None else None
            if step_cap is not None and why is None:
                self._apply(step_cap, sup)
                self.state.load_state_dict(
                    _load_sidecar(step_state_path(self.prefix)))
                out = int(step_cap["epoch"])
                used = "step"
                resumed_step = int(step_cap["step"])
                if sup is not None:
                    sup._pending_resume = (out, int(step_cap["step"]))
                log.info("capsule: resuming mid-epoch at epoch %d, step %d "
                         "(exact batch, exact RNG stream)",
                         out, int(step_cap["step"]))
            else:
                if step_cap is not None:
                    log.warning("step capsule unusable: %s", why)
                epoch_cap = read_capsule(
                    capsule_path(self.prefix, resume_from - 1)) \
                    if resume_from > 0 else None
                if epoch_cap is not None:
                    ewhy = self._format_usable(epoch_cap)
                    if ewhy is not None:
                        log.warning("epoch capsule unusable: %s", ewhy)
                        epoch_cap = None
                if epoch_cap is not None:
                    self._apply(epoch_cap, sup)
                    used = "epoch"
                    log.info("capsule: resuming at the epoch %d boundary "
                             "with the exact RNG stream", resume_from)
                elif step_cap is not None:
                    # no deterministic recovery point at all: the batches
                    # the dead run consumed past the last checkpoint are
                    # genuinely unreplayable — surface the gap
                    gap = int(step_cap.get("step", 0))
        finally:
            _telemetry.gauge("resume.resume_step_gap").set(gap)
            _telemetry.histogram("resume.capsule_restore_seconds").observe(
                time.perf_counter() - t0)
            _tracing.emit("resume.capsule_restore", used=used,
                          epoch=int(out), step=resumed_step, gap=int(gap))
        return out


def _load_sidecar(path):
    with open(path, "rb") as f:
        return _jax_tree(pickle.load(f))


# ---------------------------------------------------------------------------
# Module adapter
# ---------------------------------------------------------------------------
class ModuleState:
    """``state_dict``/``load_state_dict`` adapter over a bound Module so
    the step capsule's sidecar can carry mid-epoch weights + optimizer
    state through the ``module.fit(supervised=)`` path (CompiledTrainStep
    implements the protocol natively)."""

    def __init__(self, module):
        self.module = module

    def _updater_holder(self):
        m = self.module
        if hasattr(m, "_updater_states"):
            return m
        return getattr(m, "_curr_module", None)  # BucketingModule

    def state_dict(self):
        arg, aux = self.module.get_params()
        sd = {"arg": {k: v.asnumpy() for k, v in arg.items()},
              "aux": {k: v.asnumpy() for k, v in aux.items()}}
        holder = self._updater_holder()
        if holder is not None and getattr(holder, "_updater_states", None):
            sd["updater_states"] = _np_tree(holder._updater_states)
        return sd

    def load_state_dict(self, sd):
        self.module.set_params(sd.get("arg") or None, sd.get("aux") or None,
                               force_init=True)
        upd = sd.get("updater_states")
        holder = self._updater_holder()
        if upd is not None and holder is not None:
            holder._updater_states = _jax_tree(upd)
