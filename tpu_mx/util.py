"""mx.util (REF:python/mxnet/util.py): numpy-semantics toggles and the
small decorator helpers reference code imports from here."""
from __future__ import annotations

import functools

from . import npx as _npx

__all__ = ["is_np_array", "is_np_shape", "set_np", "reset_np", "use_np",
           "use_np_array", "use_np_shape", "np_array", "np_shape",
           "getenv", "setenv"]

is_np_array = _npx.is_np_array
is_np_shape = _npx.is_np_shape
set_np = _npx.set_np
reset_np = _npx.reset_np


class _NpScope:
    """Context manager/decorator flipping the np flags (REF util.py
    np_shape/np_array): the unified NDArray already carries numpy
    semantics (DIVERGENCES #6), so this records intent and restores."""

    def __init__(self, active=True):
        self._active = active

    def __enter__(self):
        self._prev = _npx.is_np_array()
        _npx.set_np(array=self._active)
        return self

    def __exit__(self, *exc):
        _npx.set_np(array=self._prev)
        return False

    def __call__(self, fn):
        import inspect
        if inspect.isclass(fn):
            # the reference's canonical usage is @use_np on a Block CLASS:
            # keep it a class (subclassable, isinstance-able) and wrap the
            # methods that execute user math
            for meth in ("__init__", "forward", "hybrid_forward",
                         "__call__"):
                if meth in vars(fn):
                    setattr(fn, meth, type(self)(self._active)(
                        vars(fn)[meth]))
            return fn

        @functools.wraps(fn)
        def wrapped(*a, **kw):
            with type(self)(self._active):
                return fn(*a, **kw)
        return wrapped


np_array = _NpScope
np_shape = _NpScope


def use_np_array(fn):
    return _NpScope(True)(fn)


def use_np_shape(fn):
    return _NpScope(True)(fn)


def use_np(fn):
    """Decorator: run fn under numpy semantics (REF util.py:use_np)."""
    return _NpScope(True)(fn)


def getenv(name):
    import os
    v = os.environ.get(name)
    return int(v) if v is not None and v.isdigit() else v


def setenv(name, value):
    import os
    os.environ[name] = str(value)
