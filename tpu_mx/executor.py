"""mx.executor (REF:python/mxnet/executor.py): re-export of the Executor
that `Symbol.bind`/`simple_bind` return — kept as its own module for
reference import-path parity (`from mxnet.executor import Executor`)."""
from .symbol.symbol import Executor

__all__ = ["Executor"]
