"""Lazy pointwise-fusion engine for the imperative NDArray path.

The reference's dependency engine bulked imperative ops into segments
(MXNET_ENGINE_BULK_SIZE; [ver>=1.6] pointwise fusion in
REF:src/imperative/imperative_utils.h CreateEngineOp).  Here that becomes
real for the TPU-native stack: inside an ``engine.bulk()`` scope (or with
``TPUMX_FUSION=1`` always-on), ``ops._apply`` on *fusible* ops
(elementwise / broadcast / cast / reduce tails) appends a node to this
thread's pending :class:`FusionSegment` instead of dispatching, and
returns an NDArray whose buffer is a lazy thunk.  Any barrier flushes the
segment as ONE jitted callable:

  - a read of the buffer (``wait_to_read`` / ``asnumpy`` / ``asscalar`` /
    any ``_data`` access — the property on NDArray routes every read path
    here),
  - a non-fusible consumer (its ``_raw`` unwrap reads ``_data``),
  - an autograd tape boundary (entering/leaving ``record()``/``pause()``,
    or ``backward()``),
  - the segment reaching the engine bulk size,
  - ``engine.bulk()`` scope exit or ``waitall()``.

The jitted callable is memoized in a process-lifetime cache keyed by the
op-chain signature (op keys + dataflow wiring + baked-in scalar params +
which nodes are live outputs); jax.jit's own cache supplies the
shape/dtype/device specialization layer underneath, so one chain key
serves every input geometry.

Autograd composes by recording the flushed segment as a SINGLE tape node:
the pullback is ``jax.vjp`` over the fused function (jitted, recomputing
the forward — the classic rematerializing fused backward), so gradients
flow through fused segments with the same chain rule the eager tape
applies per op.

Numerics contract (documented in docs/performance.md): a fused segment
executes the *same primitive sequence* as the eager ops, compiled as one
XLA program — identical semantics to what ``hybridize()``/``jit`` already
gives the compiled path.  XLA may contract a multiply feeding an add into
an FMA inside a fused loop (excess precision, <=1 ulp per contraction
site, the fused result being the more accurate one); chains with no such
adjacency are bit-identical to eager, and ``TPUMX_FUSION=0`` restores
eager dispatch exactly.

Deferred-error divergence: an invalid op (e.g. a broadcast shape
mismatch) raises at the flush barrier, not at the op call site; the error
message names the ops in the segment.
"""
from __future__ import annotations

import os
import threading
import weakref

import jax
import jax.numpy as jnp
import numpy as _np

from . import telemetry as _telemetry
from . import tracing as _tracing

__all__ = ["enabled", "flush", "stats", "reset_stats", "pending_ops",
           "cache_stats", "FusionSegment"]


class _TLS(threading.local):
    def __init__(self):
        self.pending = None      # FusionSegment being built, or None
        self.scope_depth = 0     # engine.bulk() nesting depth
        self.suppress_depth = 0  # bulk(size<=1) anti-fusion nesting


_TLS_ = _TLS()

# process-lifetime jit caches: chain key -> jitted callable
_FWD_CACHE = {}
_BWD_CACHE = {}

stats = {
    "ops_fused": 0,          # ops appended to segments
    "segments_flushed": 0,   # segments executed
    "segments_dead": 0,      # segments whose every output died unread
    "cache_hits": 0,
    "cache_misses": 0,
    "flush_reasons": {},     # reason -> count
}


def reset_stats():
    for k in ("ops_fused", "segments_flushed", "segments_dead",
              "cache_hits", "cache_misses"):
        stats[k] = 0
    stats["flush_reasons"] = {}


def clear_cache():
    """Drop the memoized jitted segment programs (test hook)."""
    _FWD_CACHE.clear()
    _BWD_CACHE.clear()


def cache_stats():
    """Public jit-cache accessor: compiled-program counts plus hit/miss
    totals, backed by the telemetry registry counters
    (``fusion.cache_hits`` / ``fusion.cache_misses`` /
    ``fusion.flushes``).  bench.py's fusion leg persists this dict into
    its JSON record, so cache behavior rides every benchmark receipt."""
    def val(name):
        m = _telemetry.get(name)
        return int(m.value) if m is not None else 0

    return {"programs": len(_FWD_CACHE),
            "bwd_programs": len(_BWD_CACHE),
            "hits": val("fusion.cache_hits"),
            "misses": val("fusion.cache_misses"),
            "segments_flushed": val("fusion.flushes")}


# os.environ.get costs ~3us per call (str->bytes encode in os.py) — far
# too much for a per-op-dispatch check.  On POSIX CPython the live
# mapping is os.environ._data with BYTES keys; read that directly,
# falling back to the portable path (Windows _data is str-keyed and
# upper-cased, so the bytes lookup would silently miss there).
# putenv/monkeypatch.setenv both go through os.environ, so _data stays
# current.
_ENV_DATA = getattr(os.environ, "_data", None) if os.name == "posix" \
    else None
if isinstance(_ENV_DATA, dict):
    def _fusion_env():
        v = _ENV_DATA.get(b"TPUMX_FUSION")
        return v.decode() if v is not None else None
else:  # pragma: no cover — non-CPython os.environ layout
    def _fusion_env():
        return os.environ.get("TPUMX_FUSION")


def enabled():
    """Is fusion dispatch active on this thread right now?

    TPUMX_FUSION=1 forces always-on, TPUMX_FUSION=0 forces off (restoring
    plain eager dispatch exactly, even inside ``engine.bulk()``); unset,
    fusion is active inside ``engine.bulk()`` scopes.  A ``bulk(size<=1)``
    scope SUPPRESSES fusion even under TPUMX_FUSION=1 — the reference's
    bulk-size-0/1 escape hatch must keep meaning "op-by-op here" (e.g. to
    localize a deferred error to its call site)."""
    if _TLS_.suppress_depth > 0:
        return False
    env = _fusion_env()
    if env == "1":
        return True
    if env == "0":
        return False
    return _TLS_.scope_depth > 0


def enter_scope():
    _TLS_.scope_depth += 1


def exit_scope():
    _TLS_.scope_depth -= 1
    flush("scope_exit")


def enter_suppress():
    flush("suppress_scope")  # ops before the scope must not see barriers move
    _TLS_.suppress_depth += 1


def exit_suppress():
    _TLS_.suppress_depth -= 1


def pending_ops():
    """Number of ops in this thread's pending segment (introspection)."""
    seg = _TLS_.pending
    return len(seg.fns) if seg is not None else 0


class _Lazy:
    """Marker a lazy NDArray holds in ``_lazy``: (segment, node index)."""

    __slots__ = ("segment", "index")

    def __init__(self, segment, index):
        self.segment = segment
        self.index = index


class FusionSegment:
    """A pending bulked op sequence: straight-line dataflow IR.

    Node inputs are specs: ``("e", i)`` external input i, ``("n", i)``
    output of node i.  Python scalars become weakly-typed 0-d external
    inputs — runtime arguments, exactly what eager dispatch passes to its
    per-primitive program.  Baking them as trace constants would (a) let
    XLA's algebraic simplifier fold them (e.g. divide-by-constant becomes
    multiply-by-reciprocal, a 1-ulp divergence from eager) and (b) key
    the cache on the value, so an lr schedule would recompile per step."""

    __slots__ = ("fns", "keys", "specs", "names", "nondiffs", "ext",
                 "ext_handles", "ext_ids", "handles", "avals", "bulk_size")

    def __init__(self, bulk_size):
        self.fns = []           # per node: the pure raw-array fn
        self.keys = []          # per node: hashable op key (incl. params)
        self.specs = []         # per node: tuple of input specs
        self.names = []         # per node: display name for errors
        self.nondiffs = []      # per node: eager-path nondiff flag
        self.ext = []           # external raw arrays, in first-use order
        self.ext_handles = []   # the NDArray handle per ext (None if raw)
        self.ext_ids = {}       # dedup key -> ext index
        self.handles = []       # per node: weakref to the result NDArray
        self.avals = []         # per node: lazily computed output aval
        self.bulk_size = bulk_size

    def _ext_index(self, raw, handle):
        # dedup by HANDLE identity for NDArray inputs: two distinct
        # handles can share one jax.Array (detach(), NDArray(nd)), and
        # collapsing them would route both cotangents into whichever
        # handle registered first, starving the other's .grad
        key = id(handle) if handle is not None else id(raw)
        idx = self.ext_ids.get(key)
        if idx is None:
            idx = len(self.ext)
            self.ext_ids[key] = idx
            self.ext.append(raw)
            self.ext_handles.append(handle)
        return idx

    def node_aval(self, i):
        """Output aval of node i without executing (jax abstract eval)."""
        if self.avals[i] is None:
            ins = []
            for kind, v in self.specs[i]:
                if kind == "e":
                    x = self.ext[v]
                    ins.append(jax.ShapeDtypeStruct(tuple(x.shape), x.dtype))
                elif kind == "n":
                    ins.append(self.node_aval(v))
                else:
                    ins.append(v)
            self.avals[i] = jax.eval_shape(self.fns[i], *ins)
        return self.avals[i]


def aval_of(lazy):
    return lazy.segment.node_aval(lazy.index)


_NDARRAY = None


def _ndarray_cls():
    global _NDARRAY
    if _NDARRAY is None:
        from .ndarray.ndarray import NDArray
        _NDARRAY = NDArray
    return _NDARRAY


def _lazy_ndarray(NDArray, segment, index):
    out = NDArray.__new__(NDArray)
    out._buf = None
    out._lazy = _Lazy(segment, index)
    out._grad = None
    out._grad_req = "write"
    out._tape_node = None
    out._version = 0
    return out


def append(fn, args, name, key, nondiff):
    """Append one fusible op to this thread's pending segment.

    Returns the lazy result NDArray, or None if an argument kind is not
    representable in the segment IR (caller falls back to eager)."""
    NDArray = _ndarray_cls()
    seg = _TLS_.pending
    if seg is None:
        from . import engine
        seg = FusionSegment(max(2, engine._bulk_size))
        _TLS_.pending = seg

    specs = []
    for a in args:
        if isinstance(a, NDArray):
            lz = a._lazy
            if lz is not None and lz.segment is seg:
                specs.append(("n", lz.index))
            else:
                # a lazy handle from another segment cannot normally
                # exist (one pending segment per thread; flush realizes
                # all) — ._data realizes through the property if it does
                specs.append(("e", seg._ext_index(a._data, a)))
        elif isinstance(a, (bool, int, float)):
            specs.append(("e", seg._ext_index(_scalar_ext(a), None)))
        elif isinstance(a, (jax.Array, _np.ndarray)):
            specs.append(("e", seg._ext_index(a, None)))
        else:
            # np.generic scalars, tracers, anything else: promotion or
            # identity semantics are not scalar-bakeable — let the caller
            # dispatch eagerly (a flush barrier via _raw)
            _telemetry.counter("fusion.eager_fallbacks").inc()
            return None

    idx = len(seg.fns)
    seg.fns.append(fn)
    seg.keys.append(key)
    seg.specs.append(tuple(specs))
    seg.names.append(name)
    seg.nondiffs.append(bool(nondiff))
    seg.avals.append(None)
    out = _lazy_ndarray(NDArray, seg, idx)
    seg.handles.append(weakref.ref(out))
    stats["ops_fused"] += 1
    if idx + 1 >= seg.bulk_size:
        flush("bulk_size")
    return out


_SCALAR_MEMO = {}


def _scalar_ext(v):
    """Python scalar -> weakly-typed 0-d jax array (memoized: the same
    literal recurs every chain iteration).  Weak typing preserves eager
    promotion semantics through the jit boundary."""
    key = (type(v), v)
    arr = _SCALAR_MEMO.get(key)
    if arr is None:
        arr = _SCALAR_MEMO[key] = jnp.asarray(v)
        if len(_SCALAR_MEMO) > 4096:  # unbounded-literal guard
            _SCALAR_MEMO.clear()
            _SCALAR_MEMO[key] = arr
    return arr


def realize(handle):
    """Barrier from NDArray._data: flush the segment backing `handle`."""
    lz = handle._lazy
    if lz is None:
        return
    if lz.segment is _TLS_.pending:
        flush("read_barrier")
    else:  # pragma: no cover — defensive: a detached segment still owed
        _execute(lz.segment, "read_barrier")
    if handle._lazy is not None:  # pragma: no cover — defensive
        raise RuntimeError("fusion flush failed to realize a lazy NDArray")


def flush(reason="barrier"):
    """Flush this thread's pending segment (no-op when none)."""
    seg = _TLS_.pending
    if seg is None:
        return
    _TLS_.pending = None
    _execute(seg, reason)


def _make_replay(fns, specs, nondiffs, out_idxs):
    """The fused program: replay the node chain over raw ext arrays.

    Nondiff node outputs are wrapped in ``lax.stop_gradient`` — identity
    in the forward (XLA erases it), and in the segment's single vjp it
    reproduces eager semantics exactly: an unrecorded op's output is a
    constant the tape never differentiates through."""
    from jax import lax
    single = len(out_idxs) == 1

    def fused(*ext):
        vals = []
        for fn, sp, nd_ in zip(fns, specs, nondiffs):
            ins = [ext[v] if kind == "e" else
                   (vals[v] if kind == "n" else v)
                   for kind, v in sp]
            out = fn(*ins)
            vals.append(lax.stop_gradient(out) if nd_ else out)
        if single:
            return vals[out_idxs[0]]
        return tuple(vals[i] for i in out_idxs)

    return fused


def _execute(seg, reason):
    from . import autograd

    stats["flush_reasons"][reason] = \
        stats["flush_reasons"].get(reason, 0) + 1
    if not seg.fns:
        return
    _telemetry.counter("fusion.flush_cause", cause=reason).inc()
    _telemetry.histogram("fusion.segment_ops",
                         buckets=_telemetry.SEGMENT_OPS_BUCKETS,
                         unit="ops").observe(len(seg.fns))

    # Live outputs: node results whose handle is still reachable and still
    # lazy on THIS segment.  Dead intermediates stay internal to the fused
    # program (never materialized) — the fusion win the eager path can't
    # have.  The live set rides the cache key: CPython's deterministic
    # refcounting makes it stable for a given call pattern.
    live = []      # (node index, handle)
    for i, ref in enumerate(seg.handles):
        h = ref()
        if h is not None and h._lazy is not None \
                and h._lazy.segment is seg:
            live.append((i, h))
    if not live:
        stats["segments_dead"] += 1
        _telemetry.counter("fusion.segments_dead").inc()
        return

    out_idxs = tuple(i for i, _ in live)
    chain_key = (tuple(seg.keys), tuple(seg.specs),
                 tuple(seg.nondiffs), len(seg.ext), out_idxs)

    fwd = _FWD_CACHE.get(chain_key)
    if fwd is None:
        stats["cache_misses"] += 1
        _telemetry.counter("fusion.cache_misses").inc()
        fwd = jax.jit(_make_replay(seg.fns, seg.specs, seg.nondiffs,
                                   out_idxs))
        _FWD_CACHE[chain_key] = fwd
    else:
        stats["cache_hits"] += 1
        _telemetry.counter("fusion.cache_hits").inc()

    try:
        results = fwd(*seg.ext)
    except Exception as e:
        raise type(e)(
            f"{e}\n(raised while flushing a fused op segment "
            f"[{' -> '.join(seg.names)}]; with fusion enabled, op errors "
            f"surface at the flush barrier, not the op call site)") from e
    if len(out_idxs) == 1:
        results = (results,)

    for (i, h), r in zip(live, results):
        h._buf = r
        h._lazy = None
    stats["segments_flushed"] += 1
    # telemetry scope differs from the legacy stats dict by design:
    # stats["ops_fused"] counts appends (incl. segments that later die
    # unread), fusion.ops_fused counts only ops that EXECUTED fused —
    # the number that tells an operator what the engine actually won
    _telemetry.counter("fusion.flushes").inc()
    _telemetry.counter("fusion.ops_fused").inc(len(seg.fns))
    # flight-recorder event at flush granularity (never per-op): the
    # black box can attribute a flush storm to the step that caused it
    _tracing.emit("fusion.flush", cause=reason, ops=len(seg.fns))

    # ---- autograd: the whole segment becomes ONE tape node -------------
    # Only inexact outputs of DIFF nodes join the tape: integer outputs
    # fall through unrecorded like eager (also keeps float0 cotangents
    # out of the jitted pullback), and a nondiff node's output is an
    # unrecorded constant eagerly — taping it would let a backward pass
    # overwrite leaf grads with zeros that eager never touches.
    rec = [(i, h) for i, h in live
           if not seg.nondiffs[i]
           and jnp.issubdtype(h._buf.dtype, jnp.inexact)]
    if not rec:
        return
    rec_idxs = tuple(i for i, _ in rec)
    # Differentiate only ext inputs with a tape-CONNECTED path to a
    # recorded output — a path through a nondiff node doesn't count
    # (eager never records that branch, so its leaves must receive NO
    # cotangent; the segment vjp would hand them stop_gradient zeros and
    # backward would overwrite real grads with them).  Per-node ext
    # reachability as bitmasks, nondiff nodes propagating nothing.
    ext_bit = {i: 1 << i for i, h in enumerate(seg.ext_handles)
               if h is not None
               and jnp.issubdtype(seg.ext[i].dtype, jnp.inexact)}
    masks = []
    for ni in range(len(seg.fns)):
        if seg.nondiffs[ni]:
            masks.append(0)
            continue
        m = 0
        for kind, v in seg.specs[ni]:
            if kind == "e":
                m |= ext_bit.get(v, 0)
            elif kind == "n":
                m |= masks[v]
        masks.append(m)
    needed = 0
    for i in rec_idxs:
        needed |= masks[i]
    diff_idx = tuple(i for i in sorted(ext_bit) if needed & ext_bit[i])
    if not diff_idx:
        return
    diff_handles = [seg.ext_handles[i] for i in diff_idx]
    if not autograd._needs_tape(diff_handles):
        return
    bwd_key = (chain_key, rec_idxs, diff_idx)
    ext = list(seg.ext)               # captured values: eager read-at-call
    fns, specs = list(seg.fns), list(seg.specs)
    nondiffs = list(seg.nondiffs)

    def vjp_call(cts):
        bwd = _BWD_CACHE.get(bwd_key)
        if bwd is None:
            replay = _make_replay(fns, specs, nondiffs, rec_idxs)

            def pullback(ext_ins, cts_):
                def diff_only(*dd):
                    full = list(ext_ins)
                    for i, d in zip(diff_idx, dd):
                        full[i] = d
                    return replay(*full)

                _, vjp_fn = jax.vjp(
                    diff_only, *[ext_ins[i] for i in diff_idx])
                return vjp_fn(cts_)

            bwd = jax.jit(pullback)
            _BWD_CACHE[bwd_key] = bwd
        return bwd(ext, cts)

    autograd._record_op(vjp_call, diff_handles, [h for _, h in rec],
                        name="fused_segment")
