"""Engine control API (REF:python/mxnet/engine.py, REF:include/mxnet/engine.h).

The reference's dependency engine schedules every NDArray mutation on
per-device thread pools; Python exposes ``bulk`` (op bulking) and engine
type inspection.  TPU-natively the "engine" is JAX's async dispatch plus
XLA program order: ops issue immediately and execute in stream order, and
``jit`` regions are the bulked segments.

``bulk()`` is REAL op bulking here (since the fusion engine landed —
previously a documented no-op): inside a ``bulk(size)`` scope, fusible
imperative ops (elementwise / broadcast / cast / reduce tails) are
deferred onto a pending segment and flushed as ONE jitted XLA program at
any barrier (a buffer read, a non-fusible consumer, an autograd tape
boundary, the segment reaching ``size`` ops, or scope exit).  The jitted
program is memoized across scopes keyed by the op-chain signature, so
steady-state bulked dispatch costs one cache hit + one XLA call instead
of N eager dispatches with N-1 materialized intermediates.  See
``tpu_mx/fusion.py`` for the segment IR and the numerics contract
(hybridize-grade XLA semantics; ``TPUMX_FUSION=0`` restores plain eager
dispatch exactly, ``TPUMX_FUSION=1`` turns fusion on outside ``bulk``
scopes too).  ``bulk_stats()`` exposes the engine counters.

The wait functions map to ``block_until_ready`` over live buffers, with a
pending-segment flush first — a real full-engine barrier.
"""
from __future__ import annotations

import contextlib
import os

from . import fusion as _fusion

__all__ = ["bulk", "set_bulk_size", "wait_for_all", "engine_type",
           "push_async", "push_sync", "bulk_stats", "reset_bulk_stats"]

try:
    _bulk_size = int(os.environ.get("MXNET_ENGINE_BULK_SIZE", "15"))
except ValueError:
    _bulk_size = 15


def engine_type():
    """Name of the active scheduler.  The reference returns one of
    NaiveEngine/ThreadedEngine/ThreadedEnginePerDevice; here scheduling is
    JAX's asynchronous dispatch plus the lazy fusion segments."""
    return "JaxAsyncDispatch"


def set_bulk_size(size):
    """Set the max ops per fused segment; returns the previous value
    (REF:src/imperative/cached_op.cc bulking).  Takes effect for segments
    started after the call; a size <= 1 means no bulking."""
    global _bulk_size
    prev, _bulk_size = _bulk_size, int(size)
    return prev


@contextlib.contextmanager
def bulk(size):
    """Scope within which fusible imperative ops are bulked into lazily
    flushed jitted segments of up to ``size`` ops (the reference's engine
    bulking, realized through tpu_mx/fusion.py).  Scope exit is a flush
    barrier.  ``size <= 1`` disables bulking for the scope — including
    under ``TPUMX_FUSION=1`` — matching the reference's
    MXNET_ENGINE_BULK_SIZE=0/1 escape hatch (op-by-op execution, e.g. to
    localize a deferred error to its call site)."""
    prev = set_bulk_size(size)
    fusing = int(size) > 1
    if fusing:
        _fusion.enter_scope()
    else:
        _fusion.enter_suppress()
    try:
        yield
    finally:
        if fusing:
            _fusion.exit_scope()  # flushes the pending segment
        else:
            _fusion.exit_suppress()
        set_bulk_size(prev)


def bulk_stats():
    """Engine bulking counters: ops_fused, segments_flushed, cache hits /
    misses, flush reasons.  Cumulative per process; reset with
    ``reset_bulk_stats()``."""
    out = dict(_fusion.stats)
    out["flush_reasons"] = dict(_fusion.stats["flush_reasons"])
    return out


def reset_bulk_stats():
    _fusion.reset_stats()


def wait_for_all():
    """Block until all issued computation has finished
    (Engine::WaitForAll).  Flushes any pending fused segment first."""
    from .ndarray import waitall
    waitall()


def push_async(fn, read_arrays=(), write_arrays=(), name="external_op"):
    """External-op injection point (REF:include/mxnet/c_api.h
    MXEnginePushAsync/MXEnginePushSync — the hook Horovod used to insert
    allreduce ops with engine-tracked dependencies).

    TPU-natively there is no dependency engine to register with: values ARE
    the dependencies (functional arrays), and XLA program order serializes
    conflicting work.  So the contract reduces to: wait for the reads to be
    real, run `fn(read_arrays, write_arrays)`, and let it rebind outputs
    (`NDArray._rebind`).  fn runs on the host thread — collectives that
    should overlap compute belong INSIDE the compiled step
    (parallel.CompiledTrainStep), not here; this hook exists for
    extensibility parity (external optimizers, logging, custom comm)."""
    for a in read_arrays:
        wait = getattr(a, "wait_to_read", None)
        if wait is not None:
            wait()
    return fn(list(read_arrays), list(write_arrays))


push_sync = push_async  # dispatch is synchronous from Python's view
