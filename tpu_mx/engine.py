"""Engine control API (REF:python/mxnet/engine.py, REF:include/mxnet/engine.h).

The reference's dependency engine schedules every NDArray mutation on
per-device thread pools; Python exposes ``bulk`` (op bulking) and engine
type inspection.  TPU-natively the "engine" is JAX's async dispatch plus
XLA program order: ops issue immediately and execute in stream order, and
``jit`` regions are the bulked segments.  This module keeps the control
surface: ``bulk`` is honored as a hint (ops inside are already batched by
dispatch), and the wait functions map to ``block_until_ready``.

DIVERGENCE — read before benchmarking dispatch overhead: ``set_bulk_size``
and ``bulk()`` are **semantic no-ops** here.  They record the value and
restore it, but do not change how ops execute; XLA fusion under
``hybridize()``/``jit`` is the real bulking mechanism.  Numbers measured
inside ``bulk()`` scopes reflect plain eager dispatch.
"""
from __future__ import annotations

import contextlib
import os

__all__ = ["bulk", "set_bulk_size", "wait_for_all", "engine_type",
           "push_async", "push_sync"]

try:
    _bulk_size = int(os.environ.get("MXNET_ENGINE_BULK_SIZE", "15"))
except ValueError:
    _bulk_size = 15


def engine_type():
    """Name of the active scheduler.  The reference returns one of
    NaiveEngine/ThreadedEngine/ThreadedEnginePerDevice; here scheduling is
    JAX's asynchronous dispatch."""
    return "JaxAsyncDispatch"


def set_bulk_size(size):
    """Set the bulking hint; returns the previous value.  Kept for API
    compatibility — XLA fusion under ``jit`` supersedes engine-level
    bulking (REF:src/imperative/cached_op.cc bulking)."""
    global _bulk_size
    prev, _bulk_size = _bulk_size, int(size)
    return prev


@contextlib.contextmanager
def bulk(size):
    """Scope within which ops may be bulked (no-op semantically: JAX's
    dispatch already pipelines; use ``hybridize()``/``jit`` for true
    single-program execution)."""
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)


def wait_for_all():
    """Block until all issued computation has finished
    (Engine::WaitForAll)."""
    from .ndarray import waitall
    waitall()


def push_async(fn, read_arrays=(), write_arrays=(), name="external_op"):
    """External-op injection point (REF:include/mxnet/c_api.h
    MXEnginePushAsync/MXEnginePushSync — the hook Horovod used to insert
    allreduce ops with engine-tracked dependencies).

    TPU-natively there is no dependency engine to register with: values ARE
    the dependencies (functional arrays), and XLA program order serializes
    conflicting work.  So the contract reduces to: wait for the reads to be
    real, run `fn(read_arrays, write_arrays)`, and let it rebind outputs
    (`NDArray._rebind`).  fn runs on the host thread — collectives that
    should overlap compute belong INSIDE the compiled step
    (parallel.CompiledTrainStep), not here; this hook exists for
    extensibility parity (external optimizers, logging, custom comm)."""
    for a in read_arrays:
        wait = getattr(a, "wait_to_read", None)
        if wait is not None:
            wait()
    return fn(list(read_arrays), list(write_arrays))


push_sync = push_async  # dispatch is synchronous from Python's view
