"""RecordIO — the reference's packed dataset format, bit-compatible.

Mirrors the capability of REF:python/mxnet/recordio.py +
REF:3rdparty/dmlc-core/include/dmlc/recordio.h: a seekable stream of
length-prefixed records with a magic word per record, plus an indexed variant
for random access, plus the image-record header (``IRHeader``) used by
``im2rec``/``ImageRecordIter``.

Format (little-endian), identical to dmlc recordio so .rec files made by the
reference's tools remain readable and vice versa:

    [uint32 kMagic=0xced7230a][uint32 lrec][data][0-3 pad bytes]

``lrec``: upper 3 bits = continuation flag (0 whole, 1 begin / 2 middle /
3 end of a split record), lower 29 bits = payload length.  Records whose
payload contains the magic word are split by the writer in the C++ impl; we
write whole records (payloads < 2**29) and *read* both forms.
"""
from __future__ import annotations

import collections
import os
import struct

import numpy as np

from .base import MXNetError, check

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "RecordIO", "IndexedRecordIO",
           "IRHeader", "pack", "unpack", "pack_img", "unpack_img"]

_kMagic = 0xCED7230A
_MAGIC_BYTES = struct.pack("<I", _kMagic)
_LEN_MASK = (1 << 29) - 1


class MXRecordIO:
    """Sequential RecordIO reader/writer (REF:python/mxnet/recordio.py
    MXRecordIO; format from dmlc/recordio.h)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        check(flag in ("r", "w"), f"invalid flag {flag!r}; use 'r' or 'w'")
        self.open()

    def open(self):
        # tpumx-lint: disable=durability -- streaming dataset writer, not
        # recovery state: records append incrementally over a whole pack
        # run (atomic_write cannot wrap an open-ended stream), and im2rec
        # reruns rebuild a torn pack from source
        self.record = open(self.uri, "rb" if self.flag == "r" else "wb")
        self.is_open = True

    def close(self):
        if getattr(self, "is_open", False):
            self.record.close()
            self.is_open = False

    def reset(self):
        self.close()
        self.open()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def tell(self):
        return self.record.tell()

    def write(self, buf):
        check(self.flag == "w", "not opened for writing")
        check(len(buf) <= _LEN_MASK, "record too large (>512MB)")
        data = bytes(buf)
        # The C++ writer splits payloads containing the magic word so a
        # corrupted stream can resync on magic boundaries. We keep payloads
        # whole (flag 0) — valid per format, simpler, and both readers accept
        # it — but must still write the header and 4-byte alignment exactly.
        self.record.write(_MAGIC_BYTES)
        self.record.write(struct.pack("<I", len(data)))
        self.record.write(data)
        pad = (4 - len(data) % 4) % 4
        if pad:
            self.record.write(b"\x00" * pad)

    def read(self):
        """Next record's payload as bytes, or None at EOF."""
        check(self.flag == "r", "not opened for reading")
        parts = []
        while True:
            head = self.record.read(8)
            if len(head) < 8:
                if parts:
                    raise MXNetError("truncated record at EOF")
                return None
            magic, lrec = struct.unpack("<II", head)
            if magic != _kMagic:
                raise MXNetError(
                    f"invalid record magic {magic:#x} at "
                    f"{self.record.tell() - 8}")
            cflag, length = lrec >> 29, lrec & _LEN_MASK
            data = self.record.read(length)
            if len(data) != length:
                raise MXNetError("truncated record payload")
            pad = (4 - length % 4) % 4
            if pad:
                self.record.read(pad)
            if cflag == 0:
                check(not parts, "unexpected whole record inside split")
                return data
            parts.append(data)
            if cflag == 3:  # end of split record: joined by magic bytes
                return _MAGIC_BYTES.join(parts)


class MXIndexedRecordIO(MXRecordIO):
    """RecordIO with a sidecar ``.idx`` text file (``key\\toffset`` lines)
    for random access (REF:python/mxnet/recordio.py MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if self.flag == "r" and os.path.isfile(self.idx_path):
            with open(self.idx_path) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) < 2:
                        continue
                    key = self.key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)
        if self.flag == "w":
            # tpumx-lint: disable=durability -- index lines stream out in
            # lockstep with the record pack above (same rebuild-on-rerun
            # contract); see MXRecordIO.open
            self.fidx = open(self.idx_path, "w")

    def close(self):
        if getattr(self, "is_open", False) and self.flag == "w":
            self.fidx.close()
        super().close()

    def seek(self, idx):
        check(self.flag == "r", "not opened for reading")
        self.record.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write(f"{key}\t{pos}\n")
        self.idx[key] = pos
        self.keys.append(key)


# -- image record header ------------------------------------------------------
# struct IRHeader {uint32 flag; float label; uint64 id, id2;} — 'IfQQ'.
# flag > 0 means `flag` extra float32 labels follow the header (multi-label /
# detection records, REF:src/io/image_recordio.h).
IRHeader = collections.namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack ``IRHeader`` + byte payload into one record payload."""
    header = IRHeader(*header)
    label = header.label
    if isinstance(label, (np.ndarray, list, tuple)):
        label = np.asarray(label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0.0)
        s = label.tobytes() + s
    return struct.pack(_IR_FORMAT, *header) + s


def unpack(s):
    """Inverse of :func:`pack` → (IRHeader, payload bytes)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[:header.flag * 4], dtype=np.float32).copy()
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Encode ``img`` (HWC uint8 ndarray) and pack it with ``header``."""
    import cv2
    check(img_fmt.lower() in (".jpg", ".jpeg", ".png"),
          f"unsupported image format {img_fmt}")
    if img_fmt.lower() in (".jpg", ".jpeg"):
        params = [cv2.IMWRITE_JPEG_QUALITY, quality]
    else:
        params = [cv2.IMWRITE_PNG_COMPRESSION, quality // 10]
    ok, buf = cv2.imencode(img_fmt, img, params)
    check(ok, "cv2.imencode failed")
    return pack(header, buf.tobytes())


# Short aliases used by gluon.data (RecordFileDataset/ImageRecordDataset).
RecordIO = MXRecordIO
IndexedRecordIO = MXIndexedRecordIO


def unpack_img(s, iscolor=1):
    """Inverse of :func:`pack_img` → (IRHeader, decoded HWC ndarray)."""
    import cv2
    header, img_bytes = unpack(s)
    img = cv2.imdecode(np.frombuffer(img_bytes, dtype=np.uint8), iscolor)
    return header, img
