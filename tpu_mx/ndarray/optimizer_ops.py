"""Raw optimizer update ops — the reference's fused-kernel surface
(REF:src/operator/optimizer_op.cc, REF:src/operator/contrib/adamw.cc).

Upstream exposes each optimizer's update math as a standalone `mx.nd.*` op
(`sgd_mom_update`, `adam_update`, `rmsprop_update`, …) with
`FMutateInputs` on the state tensors: callers pass `out=weight` and the
op rewrites states in place.  The Python `mx.optimizer` classes are thin
drivers over these kernels.  Here the relationship is inverted — the
`tpu_mx.optimizer` classes own the (jit-fused) math — but the raw op
surface is preserved for reference-habit users and kvstore
server-side-update parity:

- state arguments (`mom`, `mean`, `var`, `n`, `z`, …) are NDArrays and
  are REBOUND in place (the engine-var version bump, reference style);
- the updated weight goes to `out` (returned; pass `out=weight` for the
  upstream in-place idiom);
- all ops are non-differentiable (optimizer steps are not part of any
  autograd tape, matching the reference's kernels).

Formulas follow upstream 1.x exactly — notably `adam_update` does NOT
bias-correct (the upstream Python Adam pre-scales the learning rate;
`tpu_mx.optimizer.Adam` folds correction into the fused core instead,
which is the documented internal divergence).

Inside a functional trace (hybridize / CompiledTrainStep) the ops return
raw `(new_weight, *new_states)` tuples — in-place rebinding has no
meaning on tracers.
"""
from __future__ import annotations

import jax.numpy as jnp

from .ndarray import NDArray
from .ops import _apply

__all__ = [
    "sgd_mom_update", "mp_sgd_update", "mp_sgd_mom_update", "adam_update",
    "nag_mom_update", "mp_nag_mom_update", "rmsprop_update",
    "rmspropalex_update", "ftrl_update", "ftml_update", "signsgd_update",
    "signum_update", "lamb_update_phase1", "lamb_update_phase2",
    "adamw_update", "mp_adamw_update",
]


def _prep(grad, rescale_grad, clip_gradient):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g


def _cg(clip_gradient):
    return clip_gradient if clip_gradient and clip_gradient > 0 else None


def _finish(res, states, out):
    """res = (new_weight, *new_states).  Rebind states in place, deliver
    the weight to `out` (or a fresh NDArray).  Functional traces get the
    raw tuple back."""
    if not isinstance(res, (list, tuple)):
        return res
    if not isinstance(res[0], NDArray):
        return tuple(res)  # functional trace: raw arrays
    new_w, new_states = res[0], res[1:]
    for s, ns in zip(states, new_states):
        s._rebind(ns._data.astype(s.dtype))
    if out is not None:
        out._rebind(new_w._data.astype(out.dtype))
        return out
    return new_w


# ---------------------------------------------------------------------------
# SGD family
# ---------------------------------------------------------------------------
def sgd_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1, lazy_update=True,
                   out=None, **kw):
    """mom = momentum·mom − lr·(g + wd·w);  w += mom
    (REF optimizer_op-inl.h SGDMomKernel)."""
    cg = _cg(clip_gradient)

    def core(w, g, m):
        gp = _prep(g, rescale_grad, cg)
        new_m = momentum * m - lr * (gp + wd * w)
        return w + new_m, new_m

    return _finish(_apply(core, [weight, grad, mom], "sgd_mom_update",
                          nondiff=True), [mom], out)


def mp_sgd_update(weight, grad, weight32, lr, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1, lazy_update=True, out=None, **kw):
    """Mixed-precision SGD: the f32 master weight is updated, the
    low-precision weight output is a cast of it."""
    cg = _cg(clip_gradient)

    def core(w, g, w32):
        gp = _prep(g.astype(jnp.float32), rescale_grad, cg)
        new_w32 = w32 - lr * (gp + wd * w32)
        return new_w32.astype(w.dtype), new_w32

    return _finish(_apply(core, [weight, grad, weight32], "mp_sgd_update",
                          nondiff=True), [weight32], out)


def mp_sgd_mom_update(weight, grad, mom, weight32, lr, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1,
                      lazy_update=True, out=None, **kw):
    cg = _cg(clip_gradient)

    def core(w, g, m, w32):
        gp = _prep(g.astype(jnp.float32), rescale_grad, cg)
        new_m = momentum * m - lr * (gp + wd * w32)
        new_w32 = w32 + new_m
        return new_w32.astype(w.dtype), new_m, new_w32

    return _finish(_apply(core, [weight, grad, mom, weight32],
                          "mp_sgd_mom_update", nondiff=True),
                   [mom, weight32], out)


def nag_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1, out=None, **kw):
    """Nesterov momentum: mom = momentum·mom + (g + wd·w);
    w −= lr·(g + wd·w + momentum·mom)  (REF NAGMomKernel)."""
    cg = _cg(clip_gradient)

    def core(w, g, m):
        gp = _prep(g, rescale_grad, cg) + wd * w
        new_m = momentum * m + gp
        return w - lr * (gp + momentum * new_m), new_m

    return _finish(_apply(core, [weight, grad, mom], "nag_mom_update",
                          nondiff=True), [mom], out)


def mp_nag_mom_update(weight, grad, mom, weight32, lr, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1,
                      out=None, **kw):
    cg = _cg(clip_gradient)

    def core(w, g, m, w32):
        gp = _prep(g.astype(jnp.float32), rescale_grad, cg) + wd * w32
        new_m = momentum * m + gp
        new_w32 = w32 - lr * (gp + momentum * new_m)
        return new_w32.astype(w.dtype), new_m, new_w32

    return _finish(_apply(core, [weight, grad, mom, weight32],
                          "mp_nag_mom_update", nondiff=True),
                   [mom, weight32], out)


# ---------------------------------------------------------------------------
# Adam / AdamW / LAMB
# ---------------------------------------------------------------------------
def adam_update(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1,
                lazy_update=True, out=None, **kw):
    """Upstream adam_update: NO bias correction (the reference's Python
    Adam pre-scales lr by √(1−β2ᵗ)/(1−β1ᵗ) before calling the kernel)."""
    cg = _cg(clip_gradient)

    def core(w, g, m, v):
        gp = _prep(g, rescale_grad, cg) + wd * w
        new_m = beta1 * m + (1 - beta1) * gp
        new_v = beta2 * v + (1 - beta2) * jnp.square(gp)
        return (w - lr * new_m / (jnp.sqrt(new_v) + epsilon),
                new_m, new_v)

    return _finish(_apply(core, [weight, grad, mean, var], "adam_update",
                          nondiff=True), [mean, var], out)


def adamw_update(weight, grad, mean, var, rescale_grad, lr, beta1=0.9,
                 beta2=0.999, epsilon=1e-8, wd=0.0, eta=1.0,
                 clip_gradient=-1, out=None, **kw):
    """AdamW with decoupled weight decay (REF:src/operator/contrib/
    adamw.cc): w −= eta·(lr·m/(√v+ε) + wd·w).  Like the upstream kernel
    — and like adam_update above — there is NO in-kernel bias correction;
    the Python optimizer driver pre-scales lr.  `rescale_grad` is a
    tensor argument upstream (the AMP loss-scale rides in it) — scalar or
    NDArray accepted."""
    cg = _cg(clip_gradient)

    def core(w, g, m, v, rs):
        gp = g * rs
        if cg is not None:
            gp = jnp.clip(gp, -cg, cg)
        new_m = beta1 * m + (1 - beta1) * gp
        new_v = beta2 * v + (1 - beta2) * jnp.square(gp)
        new_w = w - eta * (lr * new_m / (jnp.sqrt(new_v) + epsilon)
                           + wd * w)
        return new_w, new_m, new_v

    return _finish(_apply(core, [weight, grad, mean, var, rescale_grad],
                          "adamw_update", nondiff=True), [mean, var], out)


def mp_adamw_update(weight, grad, mean, var, weight32, rescale_grad, lr,
                    beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0, eta=1.0,
                    clip_gradient=-1, out=None, **kw):
    cg = _cg(clip_gradient)

    def core(w, g, m, v, w32, rs):
        gp = g.astype(jnp.float32) * rs
        if cg is not None:
            gp = jnp.clip(gp, -cg, cg)
        new_m = beta1 * m + (1 - beta1) * gp
        new_v = beta2 * v + (1 - beta2) * jnp.square(gp)
        new_w32 = w32 - eta * (lr * new_m / (jnp.sqrt(new_v) + epsilon)
                               + wd * w32)
        return new_w32.astype(w.dtype), new_m, new_v, new_w32

    return _finish(_apply(core, [weight, grad, mean, var, weight32,
                                 rescale_grad],
                          "mp_adamw_update", nondiff=True),
                   [mean, var, weight32], out)


def lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1, **kw):
    """LAMB phase 1 (REF optimizer_op.cc lamb_update_phase1): returns the
    raw update direction g' = m̂/(√v̂+ε) + wd·w; mean/var rebound in
    place."""
    cg = _cg(clip_gradient)

    def core(w, g, m, v):
        gp = _prep(g, rescale_grad, cg)
        new_m = beta1 * m + (1 - beta1) * gp
        new_v = beta2 * v + (1 - beta2) * jnp.square(gp)
        mhat, vhat = new_m, new_v
        if bias_correction:
            mhat = new_m / (1 - beta1 ** t)
            vhat = new_v / (1 - beta2 ** t)
        return mhat / (jnp.sqrt(vhat) + epsilon) + wd * w, new_m, new_v

    res = _apply(core, [weight, grad, mean, var], "lamb_update_phase1",
                 nondiff=True)
    if isinstance(res, (list, tuple)) and isinstance(res[0], NDArray):
        mean._rebind(res[1]._data)
        var._rebind(res[2]._data)
        return res[0]
    return res


def lamb_update_phase2(weight, g, r1, r2, lr, lower_bound=-1.0,
                       upper_bound=-1.0, out=None, **kw):
    """LAMB phase 2: w −= lr·(r1/r2)·g with the trust ratio r1/r2 from
    the norms computed between phases (r1=‖w‖, r2=‖g‖), optionally
    clipping r1 into [lower_bound, upper_bound]."""

    def core(w, gg, r1v, r2v):
        r1c = r1v
        if lower_bound > 0:
            r1c = jnp.maximum(r1c, lower_bound)
        if upper_bound > 0:
            r1c = jnp.minimum(r1c, upper_bound)
        ratio = jnp.where((r1c > 0) & (r2v > 0), r1c / r2v, 1.0)
        return w - lr * ratio * gg

    res = _apply(core, [weight, g, r1, r2], "lamb_update_phase2",
                 nondiff=True)
    if isinstance(res, NDArray) and out is not None:
        out._rebind(res._data.astype(out.dtype))
        return out
    return res


# ---------------------------------------------------------------------------
# RMSProp / Ftrl / FTML
# ---------------------------------------------------------------------------
def rmsprop_update(weight, grad, n, lr, gamma1=0.95, epsilon=1e-8, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1, clip_weights=-1,
                   out=None, **kw):
    """Tieleman & Hinton RMSProp (non-centered)."""
    cg = _cg(clip_gradient)

    def core(w, g, nn):
        gp = _prep(g, rescale_grad, cg) + wd * w
        new_n = gamma1 * nn + (1 - gamma1) * jnp.square(gp)
        new_w = w - lr * gp / (jnp.sqrt(new_n) + epsilon)
        if clip_weights and clip_weights > 0:
            new_w = jnp.clip(new_w, -clip_weights, clip_weights)
        return new_w, new_n

    return _finish(_apply(core, [weight, grad, n], "rmsprop_update",
                          nondiff=True), [n], out)


def rmspropalex_update(weight, grad, n, g, delta, lr, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1, clip_weights=-1, out=None, **kw):
    """Graves' centered RMSProp (REF RMSPropAlexKernel): tracks the
    gradient mean too; update via momentum buffer delta."""
    cg = _cg(clip_gradient)

    def core(w, gr, nn, gm, d):
        gp = _prep(gr, rescale_grad, cg) + wd * w
        new_n = gamma1 * nn + (1 - gamma1) * jnp.square(gp)
        new_g = gamma1 * gm + (1 - gamma1) * gp
        new_d = gamma2 * d - lr * gp / jnp.sqrt(
            new_n - jnp.square(new_g) + epsilon)
        new_w = w + new_d
        if clip_weights and clip_weights > 0:
            new_w = jnp.clip(new_w, -clip_weights, clip_weights)
        return new_w, new_n, new_g, new_d

    return _finish(_apply(core, [weight, grad, n, g, delta],
                          "rmspropalex_update", nondiff=True),
                   [n, g, delta], out)


def ftrl_update(weight, grad, z, n, lr, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1, out=None, **kw):
    """FTRL-proximal (REF FtrlKernel / McMahan et al.)."""
    cg = _cg(clip_gradient)

    def core(w, g, zz, nn):
        gp = _prep(g, rescale_grad, cg)
        new_z = zz + gp - (jnp.sqrt(nn + jnp.square(gp)) - jnp.sqrt(nn)) \
            / lr * w
        new_n = nn + jnp.square(gp)
        new_w = jnp.where(
            jnp.abs(new_z) > lamda1,
            (jnp.sign(new_z) * lamda1 - new_z) /
            ((beta + jnp.sqrt(new_n)) / lr + wd),
            0.0)
        return new_w, new_z, new_n

    return _finish(_apply(core, [weight, grad, z, n], "ftrl_update",
                          nondiff=True), [z, n], out)


def ftml_update(weight, grad, d, v, z, lr, t, beta1=0.6, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                clip_grad=-1, out=None, **kw):
    """FTML (Zheng & Kwok 2017; REF FTMLKernel)."""
    cg = _cg(clip_grad)

    def core(w, g, dd, vv, zz):
        gp = _prep(g, rescale_grad, cg) + wd * w
        new_v = beta2 * vv + (1 - beta2) * jnp.square(gp)
        d_t = (1 - beta1 ** t) / lr * (
            jnp.sqrt(new_v / (1 - beta2 ** t)) + epsilon)
        sigma = d_t - beta1 * dd
        new_z = beta1 * zz + (1 - beta1) * gp - sigma * w
        return -new_z / d_t, d_t, new_v, new_z

    return _finish(_apply(core, [weight, grad, d, v, z], "ftml_update",
                          nondiff=True), [d, v, z], out)


# ---------------------------------------------------------------------------
# sign-based
# ---------------------------------------------------------------------------
def signsgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1, out=None, **kw):
    """signSGD (Bernstein et al.): w = (1−lr·wd)·w − lr·sign(g)."""
    cg = _cg(clip_gradient)

    def core(w, g):
        gp = _prep(g, rescale_grad, cg)
        return (1 - lr * wd) * w - lr * jnp.sign(gp)

    res = _apply(core, [weight, grad], "signsgd_update", nondiff=True)
    if isinstance(res, NDArray) and out is not None:
        out._rebind(res._data.astype(out.dtype))
        return out
    return res


def signum_update(weight, grad, mom, lr, momentum=0.9, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1, wd_lh=0.0, out=None,
                  **kw):
    """Signum: sign of the momentum buffer; wd folded into the gradient,
    wd_lh applied decoupled (REF SignumKernel)."""
    cg = _cg(clip_gradient)

    def core(w, g, m):
        gp = _prep(g, rescale_grad, cg)
        new_m = momentum * m - (1 - momentum) * (gp + wd * w)
        return (1 - lr * wd_lh) * w + lr * jnp.sign(new_m), new_m

    return _finish(_apply(core, [weight, grad, mom], "signum_update",
                          nondiff=True), [mom], out)


# ---------------------------------------------------------------------------
# fused multi-tensor updates (REF:src/operator/optimizer_op.cc multi_sgd_*,
# preloaded_multi_sgd_*).  Upstream fuses many small parameter updates into
# one kernel launch; here one _apply traces ALL updates into a single XLA
# program (which fuses them) — the same amortization, compiler-scheduled.
# data is the reference's interleaved varargs layout.
# ---------------------------------------------------------------------------
def _check_out(out, n, name):
    """`out` must be None or a length-n sequence (a bare NDArray is only
    unambiguous for n==1) — validated BEFORE any state is rebound, so a
    bad call can never leave optimizer state partially mutated."""
    if out is None:
        return None
    if isinstance(out, NDArray):
        if n != 1:
            raise ValueError(f"{name}: out must be a sequence of "
                             f"{n} NDArrays (got a single NDArray)")
        return [out]
    out = list(out)
    if len(out) != n:
        raise ValueError(f"{name}: out has {len(out)} entries for "
                         f"{n} weights")
    return out


def _deliver(res, tensors, group, n, state_slots, out):
    """Shared result epilogue for the multi drivers: functional traces
    get the raw tuple; otherwise states are rebound in place and weights
    delivered to `out` (validated) or returned fresh."""
    if not isinstance(res, (list, tuple)) or not res or \
            not isinstance(res[0], NDArray):
        return res  # functional trace: raw tuple
    per = 1 + len(state_slots)
    results = []
    for i in range(n):
        new_w = res[i * per]
        new_states = res[i * per + 1:(i + 1) * per]
        for slot, ns in zip(state_slots, new_states):
            s = tensors[i * group + slot]
            s._rebind(ns._data.astype(s.dtype))
        if out is not None:
            out[i]._rebind(new_w._data.astype(out[i].dtype))
            results.append(out[i])
        else:
            results.append(new_w)
    return results


def _multi_update(data, group, per_weight, name, num_weights, out,
                  state_slots):
    """Shared driver: `data` = flat interleaved tensors, `group` elems per
    weight, `per_weight(i, *slice)` returns (new_w, *new_states) in slice
    order for the state_slots indices.  States rebound in place; weights
    delivered to `out` (length-n sequence) or fresh."""
    n = num_weights
    if len(data) != n * group:
        raise ValueError(f"{name}: expected {n * group} tensors "
                         f"({group} per weight), got {len(data)}")
    out = _check_out(out, n, name)

    def fn(*raw):
        outs = []
        for i in range(n):
            outs.extend(per_weight(i, *raw[i * group:(i + 1) * group]))
        return tuple(outs)

    res = _apply(fn, list(data), name, nondiff=True)
    return _deliver(res, data, group, n, state_slots, out)


def _lrs_wds(kw, n):
    lrs = kw.get("lrs", kw.get("lr"))
    wds = kw.get("wds", kw.get("wd", 0.0))
    if lrs is None:
        raise ValueError("multi update ops need lrs=(...)")
    lrs = [float(lrs)] * n if not isinstance(lrs, (list, tuple)) else \
        [float(v) for v in lrs]
    wds = [float(wds)] * n if not isinstance(wds, (list, tuple)) else \
        [float(v) for v in wds]
    if len(lrs) != n or len(wds) != n:
        raise ValueError(f"lrs/wds must have one entry per weight "
                         f"({n}): got {len(lrs)}/{len(wds)}")
    return lrs, wds


def multi_sgd_update(*data, num_weights=None, rescale_grad=1.0,
                     clip_gradient=-1, out=None, **kw):
    """Interleaved [w0, g0, w1, g1, …] fused SGD."""
    n = num_weights or len(data) // 2
    lrs, wds = _lrs_wds(kw, n)
    cg = _cg(clip_gradient)

    def per_weight(i, w, g):
        gp = _prep(g, rescale_grad, cg)
        return (w - lrs[i] * (gp + wds[i] * w),)

    return _multi_update(data, 2, per_weight, "multi_sgd_update", n, out,
                         state_slots=())


def multi_sgd_mom_update(*data, num_weights=None, momentum=0.0,
                         rescale_grad=1.0, clip_gradient=-1, out=None,
                         **kw):
    """Interleaved [w0, g0, mom0, …] fused momentum SGD; moms rebound."""
    n = num_weights or len(data) // 3
    lrs, wds = _lrs_wds(kw, n)
    cg = _cg(clip_gradient)

    def per_weight(i, w, g, m):
        gp = _prep(g, rescale_grad, cg)
        new_m = momentum * m - lrs[i] * (gp + wds[i] * w)
        return (w + new_m, new_m)

    return _multi_update(data, 3, per_weight, "multi_sgd_mom_update", n,
                         out, state_slots=(2,))


def multi_mp_sgd_update(*data, num_weights=None, rescale_grad=1.0,
                        clip_gradient=-1, out=None, **kw):
    """Interleaved [w0, g0, w32_0, …] fused mixed-precision SGD."""
    n = num_weights or len(data) // 3
    lrs, wds = _lrs_wds(kw, n)
    cg = _cg(clip_gradient)

    def per_weight(i, w, g, w32):
        gp = _prep(g.astype(jnp.float32), rescale_grad, cg)
        new_w32 = w32 - lrs[i] * (gp + wds[i] * w32)
        return (new_w32.astype(w.dtype), new_w32)

    return _multi_update(data, 3, per_weight, "multi_mp_sgd_update", n,
                         out, state_slots=(2,))


def multi_mp_sgd_mom_update(*data, num_weights=None, momentum=0.0,
                            rescale_grad=1.0, clip_gradient=-1, out=None,
                            **kw):
    """Interleaved [w0, g0, mom0, w32_0, …] fused mp momentum SGD."""
    n = num_weights or len(data) // 4
    lrs, wds = _lrs_wds(kw, n)
    cg = _cg(clip_gradient)

    def per_weight(i, w, g, m, w32):
        gp = _prep(g.astype(jnp.float32), rescale_grad, cg)
        new_m = momentum * m - lrs[i] * (gp + wds[i] * w32)
        new_w32 = w32 + new_m
        return (new_w32.astype(w.dtype), new_m, new_w32)

    return _multi_update(data, 4, per_weight, "multi_mp_sgd_mom_update",
                         n, out, state_slots=(2, 3))


def _preloaded(data, group, num_weights, name, body, out, state_slots):
    """preloaded_* variants: per-weight lrs/wds ride as the LAST TWO
    tensor args instead of python tuples (the reference preloads them to
    the device once and reuses across steps)."""
    n = num_weights or (len(data) - 2) // group
    if len(data) != n * group + 2:
        raise ValueError(f"{name}: expected {n * group} tensors + lrs + "
                         f"wds, got {len(data)}")
    tensors, lrs, wds = data[:-2], data[-2], data[-1]
    out = _check_out(out, n, name)

    def fn(*raw):
        *groups_flat, raw_lrs, raw_wds = raw
        outs = []
        for i in range(n):
            outs.extend(body(i, raw_lrs[i], raw_wds[i],
                             *groups_flat[i * group:(i + 1) * group]))
        return tuple(outs)

    res = _apply(fn, list(tensors) + [lrs, wds], name, nondiff=True)
    return _deliver(res, tensors, group, n, state_slots, out)


def preloaded_multi_sgd_update(*data, num_weights=None, rescale_grad=1.0,
                               clip_gradient=-1, out=None, **kw):
    cg = _cg(clip_gradient)

    def body(i, lr, wd, w, g):
        gp = _prep(g, rescale_grad, cg)
        return (w - lr * (gp + wd * w),)

    return _preloaded(data, 2, num_weights, "preloaded_multi_sgd_update",
                      body, out, state_slots=())


def preloaded_multi_sgd_mom_update(*data, num_weights=None, momentum=0.0,
                                   rescale_grad=1.0, clip_gradient=-1,
                                   out=None, **kw):
    cg = _cg(clip_gradient)

    def body(i, lr, wd, w, g, m):
        gp = _prep(g, rescale_grad, cg)
        new_m = momentum * m - lr * (gp + wd * w)
        return (w + new_m, new_m)

    return _preloaded(data, 3, num_weights,
                      "preloaded_multi_sgd_mom_update", body, out,
                      state_slots=(2,))


def preloaded_multi_mp_sgd_update(*data, num_weights=None,
                                  rescale_grad=1.0, clip_gradient=-1,
                                  out=None, **kw):
    cg = _cg(clip_gradient)

    def body(i, lr, wd, w, g, w32):
        gp = _prep(g.astype(jnp.float32), rescale_grad, cg)
        new_w32 = w32 - lr * (gp + wd * w32)
        return (new_w32.astype(w.dtype), new_w32)

    return _preloaded(data, 3, num_weights,
                      "preloaded_multi_mp_sgd_update", body, out,
                      state_slots=(2,))


def preloaded_multi_mp_sgd_mom_update(*data, num_weights=None,
                                      momentum=0.0, rescale_grad=1.0,
                                      clip_gradient=-1, out=None, **kw):
    cg = _cg(clip_gradient)

    def body(i, lr, wd, w, g, m, w32):
        gp = _prep(g.astype(jnp.float32), rescale_grad, cg)
        new_m = momentum * m - lr * (gp + wd * w32)
        new_w32 = w32 + new_m
        return (new_w32.astype(w.dtype), new_m, new_w32)

    return _preloaded(data, 4, num_weights,
                      "preloaded_multi_mp_sgd_mom_update", body, out,
                      state_slots=(2, 3))


__all__ += [
    "multi_sgd_update", "multi_sgd_mom_update", "multi_mp_sgd_update",
    "multi_mp_sgd_mom_update", "preloaded_multi_sgd_update",
    "preloaded_multi_sgd_mom_update", "preloaded_multi_mp_sgd_update",
    "preloaded_multi_mp_sgd_mom_update",
]
