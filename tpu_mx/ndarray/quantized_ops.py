"""Int8 quantized compute ops (REF:src/operator/quantization/*: quantize_v2,
dequantize, requantize, quantized_fully_connected, quantized_conv — the
MKLDNN/cuDNN int8 kernels).

TPU-native design: int8 storage with `lax.dot_general`/`conv_general_dilated`
`preferred_element_type=int32` — the actual int8 matmul path XLA lowers onto
the MXU's int8 mode — followed by the float32 scale composition the
reference carries in its (min, max) calibration ranges.  Ranges ride along
as explicit (min, max) scalars exactly like the reference's three-output
convention: every quantized op returns (data, min, max).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .ops import _apply

__all__ = ["quantize_v2", "dequantize", "requantize",
           "quantized_fully_connected", "quantized_conv",
           "quantized_flatten", "quantized_pooling"]

_INT8_RANGE = 127.0


def quantize_v2(data, min_calib_range=None, max_calib_range=None,
                out_type="int8", **kw):
    """f32 -> int8 with symmetric scale from calibrated (or observed) range;
    returns (q, min, max) (REF:quantization/quantize_v2-inl.h)."""

    def f(x):
        if min_calib_range is not None:
            mn = jnp.asarray(min_calib_range, jnp.float32)
            mx = jnp.asarray(max_calib_range, jnp.float32)
        else:
            mn = x.min().astype(jnp.float32)
            mx = x.max().astype(jnp.float32)
        amax = jnp.maximum(jnp.abs(mn), jnp.abs(mx))
        scale = _INT8_RANGE / jnp.maximum(amax, 1e-12)
        q = jnp.clip(jnp.round(x * scale), -127, 127).astype(jnp.int8)
        return q, -amax, amax

    return _apply(f, [data], "quantize_v2", nondiff=True)


def dequantize(data, min_range, max_range, out_type="float32", **kw):
    """int8 -> f32 (REF:quantization/dequantize-inl.h)."""

    def f(q, mn, mx):
        amax = jnp.maximum(jnp.abs(mn), jnp.abs(mx))
        return q.astype(jnp.float32) * (amax / _INT8_RANGE)

    return _apply(f, [data, min_range, max_range], "dequantize", nondiff=True)


def requantize(data, min_range, max_range, min_calib_range=None,
               max_calib_range=None, **kw):
    """int32 accumulator -> int8 with a new range
    (REF:quantization/requantize-inl.h)."""

    def f(q32, mn, mx):
        # incoming int32 represents values q32 * (amax_in / (127*127))
        amax_in = jnp.maximum(jnp.abs(mn), jnp.abs(mx))
        real = q32.astype(jnp.float32) * (amax_in / (_INT8_RANGE ** 2))
        if min_calib_range is not None:
            amax_out = jnp.maximum(abs(float(min_calib_range)),
                                   abs(float(max_calib_range)))
        else:
            amax_out = jnp.maximum(jnp.abs(real).max(), 1e-12)
        q8 = jnp.clip(jnp.round(real * (_INT8_RANGE / amax_out)),
                      -127, 127).astype(jnp.int8)
        return q8, -amax_out, amax_out

    return _apply(f, [data, min_range, max_range], "requantize", nondiff=True)


def quantized_fully_connected(data, weight, bias, min_data, max_data,
                              min_weight, max_weight, min_bias=None,
                              max_bias=None, num_hidden=None, no_bias=False,
                              **kw):
    """int8 x int8 -> int32 dense (REF:quantization/quantized_fully_connected.cc).
    Returns (y_int32, min_out, max_out) where y represents
    y * (amax_d * amax_w / 127^2)."""

    def f(x, w, *rest):
        if no_bias:
            mnd, mxd, mnw, mxw = rest[:4]
            b = None
        else:
            b, mnd, mxd, mnw, mxw = rest[0], rest[1], rest[2], rest[3], rest[4]
        y = lax.dot_general(
            x.astype(jnp.int8), w.astype(jnp.int8),
            dimension_numbers=(((x.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)
        amax_d = jnp.maximum(jnp.abs(mnd), jnp.abs(mxd))
        amax_w = jnp.maximum(jnp.abs(mnw), jnp.abs(mxw))
        out_scale = amax_d * amax_w  # value = q * out_scale / 127^2
        if b is not None:
            # bias arrives int8 with its own range; rescale into the
            # accumulator's grid
            mnb, mxb = rest[5], rest[6]
            amax_b = jnp.maximum(jnp.abs(mnb), jnp.abs(mxb))
            b32 = jnp.round(
                b.astype(jnp.float32) * (amax_b / _INT8_RANGE)
                * (_INT8_RANGE ** 2) / jnp.maximum(out_scale, 1e-12)
            ).astype(jnp.int32)
            y = y + b32
        return y, -out_scale, out_scale

    args = [data, weight] + ([] if no_bias else [bias]) + \
        [min_data, max_data, min_weight, max_weight] + \
        ([] if no_bias or min_bias is None else [min_bias, max_bias])
    return _apply(f, args, "quantized_fully_connected", nondiff=True)


def quantized_conv(data, weight, bias, min_data, max_data, min_weight,
                   max_weight, kernel=None, stride=None, pad=None,
                   dilate=None, num_filter=None, num_group=1, no_bias=True,
                   layout="NCHW", **kw):
    """int8 conv with int32 accumulation
    (REF:quantization/quantized_conv.cc).  Same (out, min, max) contract as
    quantized_fully_connected."""
    nd_ = len(kernel)
    strides = stride or (1,) * nd_
    padding = [(p_, p_) for p_ in (pad or (0,) * nd_)]
    dilation = dilate or (1,) * nd_
    spatial = "DHW"[-nd_:]
    if layout is None:
        layout = "NC" + spatial
    channels_last = layout.endswith("C")
    wspec = ("O" + spatial + "I") if channels_last else ("OI" + spatial)
    dn = (layout, wspec, layout)

    def f(x, w, *rest):
        mnd, mxd, mnw, mxw = rest[:4]
        y = lax.conv_general_dilated(
            x.astype(jnp.int8), w.astype(jnp.int8), window_strides=strides,
            padding=padding, rhs_dilation=dilation,
            feature_group_count=num_group, dimension_numbers=dn,
            preferred_element_type=jnp.int32)
        amax_d = jnp.maximum(jnp.abs(mnd), jnp.abs(mxd))
        amax_w = jnp.maximum(jnp.abs(mnw), jnp.abs(mxw))
        out_scale = amax_d * amax_w
        return y, -out_scale, out_scale

    args = [data, weight] + [min_data, max_data, min_weight, max_weight]
    return _apply(f, args, "quantized_conv", nondiff=True)


def quantized_pooling(data, min_data, max_data, kernel=None,
                      pool_type="max", stride=None, pad=None,
                      global_pool=False, layout="NCHW", **kw):
    """int8 pooling with range passthrough
    (REF:quantization/quantized_pooling.cc).  max pools directly on int8;
    avg accumulates in int32 and rounds back (the reference's MKLDNN
    contract).  Returns (out, min, max) — ranges are unchanged because
    both pool types are order/scale-preserving."""
    channels_last = layout.endswith("C")
    nd_ = len(layout) - 2

    def f(x, mn, mx):
        if global_pool:
            axes = tuple(range(1, 1 + nd_)) if channels_last else \
                tuple(range(2, 2 + nd_))
            if pool_type == "max":
                y = x.max(axis=axes, keepdims=True)
            else:
                y = jnp.round(
                    x.astype(jnp.int32).mean(axis=axes, keepdims=True))
                y = jnp.clip(y, -127, 127).astype(jnp.int8)
            return y, mn, mx
        strides = stride or (1,) * nd_
        pads = pad or (0,) * nd_
        if channels_last:
            window = (1,) + tuple(kernel) + (1,)
            wstride = (1,) + tuple(strides) + (1,)
            padding = [(0, 0)] + [(p_, p_) for p_ in pads] + [(0, 0)]
        else:
            window = (1, 1) + tuple(kernel)
            wstride = (1, 1) + tuple(strides)
            padding = [(0, 0), (0, 0)] + [(p_, p_) for p_ in pads]
        if pool_type == "max":
            y = lax.reduce_window(x, jnp.int8(-128), lax.max, window,
                                  wstride, padding)
        else:
            s = lax.reduce_window(x.astype(jnp.int32), jnp.int32(0),
                                  lax.add, window, wstride, padding)
            cnt = 1
            for k_ in kernel:
                cnt *= k_
            y = jnp.clip(jnp.round(s / cnt), -127, 127).astype(jnp.int8)
        return y, mn, mx

    return _apply(f, [data, min_data, max_data], "quantized_pooling",
                  nondiff=True)


def quantized_flatten(data, min_data, max_data, **kw):
    def f(x, mn, mx):
        return x.reshape(x.shape[0], -1), mn, mx

    return _apply(f, [data, min_data, max_data], "quantized_flatten",
                  nondiff=True)
