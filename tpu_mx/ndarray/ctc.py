"""CTC loss (REF:src/operator/contrib/ctc_loss-inl.h — warp-ctc/cuDNN CTC
kernels; REF:python/mxnet/gluon/loss.py CTCLoss).

TPU-native design: the classic alpha (forward) recursion in log space,
expressed as a `lax.scan` over time with the extended label sequence
(blank-interleaved) as a static-width lane dimension — one fused XLA loop,
batch vmapped.  The backward pass is jax autodiff through the scan (the
reference hand-writes the beta recursion; vjp-of-scan computes exactly
that), so CTCLoss composes with every other op and with jit.

Layout conventions match the reference: data (T, N, C+1) activations
(softmax applied internally), label (N, L) with padding, blank index 0 or
C (`blank_label` 'first'/'last').
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .ops import _apply

__all__ = ["CTCLoss", "ctc_loss"]

_NEG = -1e30


def _logsumexp2(a, b):
    m = jnp.maximum(a, b)
    m_safe = jnp.maximum(m, _NEG)
    return m_safe + jnp.log(jnp.exp(a - m_safe) + jnp.exp(b - m_safe))


def _logsumexp3(a, b, c):
    return _logsumexp2(_logsumexp2(a, b), c)


def _ctc_single(logp, labels, input_len, label_len, blank):
    """Negative log likelihood for one sequence.
    logp: (T, C) log-probs; labels: (L,) int; lens: scalars."""
    T, C = logp.shape
    L = labels.shape[0]
    S = 2 * L + 1
    # extended sequence: blank, l1, blank, l2, ..., blank
    ext = jnp.full((S,), blank, jnp.int32)
    ext = ext.at[1::2].set(labels.astype(jnp.int32))
    pos = jnp.arange(S)
    valid_s = pos < 2 * label_len + 1
    # transitions: from s (stay), s-1, and s-2 when ext[s] != blank and
    # ext[s] != ext[s-2] (the CTC skip rule)
    ext_m2 = jnp.concatenate([jnp.full((2,), -1, jnp.int32), ext[:-2]])
    can_skip = (ext != blank) & (ext != ext_m2)

    alpha0 = jnp.full((S,), _NEG)
    alpha0 = alpha0.at[0].set(logp[0, blank])
    alpha0 = alpha0.at[1].set(jnp.where(label_len > 0, logp[0, ext[1]], _NEG))

    def step(alpha, logp_t):
        a_prev = jnp.concatenate([jnp.full((1,), _NEG), alpha[:-1]])
        a_prev2 = jnp.concatenate([jnp.full((2,), _NEG), alpha[:-2]])
        a = _logsumexp3(alpha, a_prev,
                        jnp.where(can_skip, a_prev2, _NEG))
        a = a + logp_t[ext]
        a = jnp.where(valid_s, a, _NEG)
        return a, a

    _, alphas = lax.scan(step, alpha0, logp[1:])
    alphas = jnp.concatenate([alpha0[None], alphas], 0)     # (T, S)
    # likelihood at t = input_len - 1, states 2*label_len and 2*label_len - 1
    t_last = jnp.clip(input_len - 1, 0, T - 1)
    a_T = alphas[t_last]
    end1 = a_T[jnp.clip(2 * label_len, 0, S - 1)]
    end2 = jnp.where(label_len > 0,
                     a_T[jnp.clip(2 * label_len - 1, 0, S - 1)], _NEG)
    return -_logsumexp2(end1, end2)


def ctc_loss(data, label, data_lengths=None, label_lengths=None,
             use_data_lengths=False, use_label_lengths=False,
             blank_label="first", **kw):
    """(T, N, C) activations + (N, L) labels -> (N,) loss.  Without explicit
    lengths, label padding follows the reference: `-1` padding always ends a
    label; with blank_label='first', `0` padding also ends it (labels are
    then 1-based with 0 reserved for blank)."""

    def f(acts, lab, *lens):
        T, N, C = acts.shape
        logp = jax.nn.log_softmax(acts.astype(jnp.float32), axis=-1)
        logp = jnp.transpose(logp, (1, 0, 2))                # (N, T, C)
        lab = lab.astype(jnp.int32)
        if use_data_lengths:
            in_lens = lens[0].astype(jnp.int32)
        else:
            in_lens = jnp.full((N,), T, jnp.int32)
        pad_end = (lab < 0) | ((lab == 0) if blank_label == "first" else
                               jnp.zeros_like(lab, bool))
        if use_label_lengths:
            lab_lens = lens[-1].astype(jnp.int32)
        else:
            # first padding position (or L)
            lab_lens = jnp.argmax(
                jnp.concatenate(
                    [pad_end, jnp.ones((N, 1), bool)], 1), axis=1
            ).astype(jnp.int32)
        # labels are direct class indices in both conventions: 1..C-1 when
        # blank is channel 0 ('first'), 0..C-2 when blank is the last channel
        blank = 0 if blank_label == "first" else C - 1
        lab_eff = jnp.clip(lab, 0, C - 1)
        return jax.vmap(_ctc_single, in_axes=(0, 0, 0, 0, None))(
            logp, lab_eff, in_lens, lab_lens, blank)

    args = [data, label]
    if use_data_lengths:
        args.append(data_lengths)
    if use_label_lengths:
        args.append(label_lengths)
    return _apply(f, args, "ctc_loss")


CTCLoss = ctc_loss
