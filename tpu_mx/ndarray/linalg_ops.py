"""mx.nd.linalg_* — the la_op family (REF:src/operator/tensor/la_op.cc,
la_op.h: LAPACK/cuSOLVER kernels registered per-op).

TPU-native design: every op is a thin pure wrapper over
`jax.scipy.linalg`/`jnp.linalg`, which XLA lowers to its native
triangular-solve / cholesky / eigh HLOs (tiled onto the MXU where possible)
— no LAPACK workspace management, and batching comes from the leading
dimensions instead of hand-written batch loops.  All ops operate on the
last two axes and broadcast over the rest, matching the reference's
"tensor of matrices" convention.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .ops import _apply

__all__ = ["linalg_trsm", "linalg_trmm", "linalg_det", "linalg_slogdet",
           "linalg_inverse", "linalg_potri", "linalg_makediag",
           "linalg_extractdiag", "linalg_maketrian", "linalg_extracttrian",
           "linalg_gelqf", "linalg_syevd", "linalg_sumlogdiag"]


def linalg_trsm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0,
                **kw):
    """Triangular solve: op(A) X = alpha*B (or X op(A) = alpha*B when
    `rightside`).  REF:la_op trsm."""

    def f(a, b):
        if rightside:
            # X op(A) = alpha B  <=>  op(A)^T X^T = alpha B^T
            x = jax.scipy.linalg.solve_triangular(
                a, jnp.swapaxes(alpha * b, -1, -2),
                trans=0 if transpose else 1, lower=lower)
            return jnp.swapaxes(x, -1, -2)
        return jax.scipy.linalg.solve_triangular(
            a, alpha * b, trans=1 if transpose else 0, lower=lower)

    return _apply(f, [A, B], "linalg_trsm")


def linalg_trmm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0,
                **kw):
    """Triangular matrix multiply: alpha * op(tri(A)) @ B (B @ op(tri(A))
    when `rightside`).  REF:la_op trmm."""

    def f(a, b):
        tri = jnp.tril(a) if lower else jnp.triu(a)
        if transpose:
            tri = jnp.swapaxes(tri, -1, -2)
        return alpha * (jnp.matmul(b, tri) if rightside else jnp.matmul(tri, b))

    return _apply(f, [A, B], "linalg_trmm")


def linalg_det(A, **kw):
    """Matrix determinant (REF:la_op det)."""
    return _apply(jnp.linalg.det, [A], "linalg_det")


def linalg_slogdet(A, **kw):
    """(sign, log|det|) pair (REF:la_op slogdet)."""

    def f(a):
        sign, logabs = jnp.linalg.slogdet(a)
        return sign, logabs

    return _apply(f, [A], "linalg_slogdet")


def linalg_inverse(A, **kw):
    """Matrix inverse (REF:la_op inverse)."""
    return _apply(jnp.linalg.inv, [A], "linalg_inverse")


def linalg_potri(A, lower=True, **kw):
    """Inverse of the SPD matrix whose Cholesky factor is A:
    out = (A Aᵀ)⁻¹ for lower A (REF:la_op potri, LAPACK dpotri)."""

    def f(a):
        eye = jnp.broadcast_to(jnp.eye(a.shape[-1], dtype=a.dtype), a.shape)
        inv_a = jax.scipy.linalg.solve_triangular(a, eye, lower=lower)
        return (jnp.matmul(jnp.swapaxes(inv_a, -1, -2), inv_a) if lower
                else jnp.matmul(inv_a, jnp.swapaxes(inv_a, -1, -2)))

    return _apply(f, [A], "linalg_potri")


def linalg_makediag(A, offset=0, **kw):
    """Vector(s) -> diagonal matrix (REF:la_op makediag)."""

    def f(a):
        n = a.shape[-1] + abs(offset)
        base = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        idx = jnp.arange(a.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        return base.at[..., r, c].set(a)

    return _apply(f, [A], "linalg_makediag")


def linalg_extractdiag(A, offset=0, **kw):
    """Matrix diagonal(s) -> vector (REF:la_op extractdiag)."""
    return _apply(lambda a: jnp.diagonal(a, offset=offset, axis1=-2, axis2=-1),
                  [A], "linalg_extractdiag")


def linalg_maketrian(A, offset=0, lower=True, **kw):
    """Packed triangle vector -> triangular matrix (REF:la_op maketrian)."""

    def f(a):
        k = a.shape[-1]
        # n(n+1)/2 = k  ->  n
        n = int((-1 + (1 + 8 * k) ** 0.5) / 2) + abs(offset)
        m = n  # square output
        if lower:
            r, c = jnp.tril_indices(m, k=-abs(offset) if offset else 0)
            if offset:
                mask = r - c >= abs(offset)
                r, c = r[mask][:k], c[mask][:k]
        else:
            r, c = jnp.triu_indices(m, k=abs(offset) if offset else 0)
            if offset:
                mask = c - r >= abs(offset)
                r, c = r[mask][:k], c[mask][:k]
        out = jnp.zeros(a.shape[:-1] + (m, m), a.dtype)
        return out.at[..., r, c].set(a)

    return _apply(f, [A], "linalg_maketrian")


def linalg_extracttrian(A, offset=0, lower=True, **kw):
    """Triangular part -> packed vector (REF:la_op extracttrian)."""

    def f(a):
        m = a.shape[-1]
        if lower:
            r, c = jnp.tril_indices(m, k=-offset if offset else 0)
        else:
            r, c = jnp.triu_indices(m, k=offset if offset else 0)
        return a[..., r, c]

    return _apply(f, [A], "linalg_extracttrian")


def linalg_gelqf(A, **kw):
    """LQ factorization A = L Q with Q orthonormal rows (REF:la_op gelqf,
    LAPACK dgelqf).  Computed as the transposed QR of Aᵀ."""

    def f(a):
        q, r = jnp.linalg.qr(jnp.swapaxes(a, -1, -2), mode="reduced")
        l = jnp.swapaxes(r, -1, -2)
        qt = jnp.swapaxes(q, -1, -2)
        # sign-normalize so L has a non-negative diagonal (LAPACK convention
        # is sign-ambiguous; fix for determinism)
        d = jnp.sign(jnp.diagonal(l, axis1=-2, axis2=-1))
        d = jnp.where(d == 0, 1.0, d).astype(a.dtype)
        return l * d[..., None, :], qt * d[..., :, None]

    return _apply(f, [A], "linalg_gelqf")


def linalg_syevd(A, **kw):
    """Symmetric eigendecomposition: returns (U, lambda) with
    A = Uᵀ diag(lambda) U (rows of U are eigenvectors — the reference's
    convention, REF:la_op syevd)."""

    def f(a):
        w, v = jnp.linalg.eigh(a)
        return jnp.swapaxes(v, -1, -2), w

    return _apply(f, [A], "linalg_syevd")


def linalg_sumlogdiag(A, **kw):
    """sum(log(diag(A))) per matrix (REF:la_op sumlogdiag)."""
    return _apply(
        lambda a: jnp.sum(jnp.log(jnp.diagonal(a, axis1=-2, axis2=-1)),
                          axis=-1),
        [A], "linalg_sumlogdiag")
