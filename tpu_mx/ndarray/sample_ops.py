"""Per-element samplers — mx.nd.sample_* (REF:src/operator/random/
sample_op.cc, multisample_op.cc): distribution parameters are TENSORS and
each element draws with its own parameters, appending `shape` extra draw
dims (the reference's "multisample" family).

TPU-native: each op splits one key from the global stream and vmaps the
jax.random sampler over the parameter tensors — a single fused XLA program,
in contrast to the reference's per-element curand loops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from .ops import _apply

__all__ = ["sample_uniform", "sample_normal", "sample_gamma",
           "sample_exponential", "sample_poisson",
           "sample_negative_binomial", "sample_generalized_negative_binomial",
           "random_negative_binomial",
           "random_generalized_negative_binomial"]


def _extra_shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


def _sampled(name, draw, params, shape, dtype):
    """draw(key, broadcast_params, extra_shape) -> array of
    extra_shape + param_shape."""
    from .. import random as _random
    key = _random.take_key()
    extra = _extra_shape(shape)

    def f(*ps):
        ps = jnp.broadcast_arrays(*ps) if len(ps) > 1 else list(ps)
        out = draw(key, ps, extra + ps[0].shape)
        # reference layout: param_shape + extra (draws are trailing axes)
        if extra:
            out = jnp.moveaxis(out, tuple(range(len(extra))),
                               tuple(range(-len(extra), 0)))
        return out.astype(jnp.dtype(dtype))

    return _apply(f, list(params), name, nondiff=True)


def sample_uniform(low, high, shape=None, dtype="float32", **kw):
    return _sampled(
        "sample_uniform",
        lambda k, ps, s: jax.random.uniform(k, s) * (ps[1] - ps[0]) + ps[0],
        (low, high), shape, dtype)


def sample_normal(mu, sigma, shape=None, dtype="float32", **kw):
    return _sampled(
        "sample_normal",
        lambda k, ps, s: ps[0] + ps[1] * jax.random.normal(k, s),
        (mu, sigma), shape, dtype)


def sample_gamma(alpha, beta, shape=None, dtype="float32", **kw):
    return _sampled(
        "sample_gamma",
        lambda k, ps, s: jax.random.gamma(k, ps[0], s) * ps[1],
        (alpha, beta), shape, dtype)


def sample_exponential(lam, shape=None, dtype="float32", **kw):
    return _sampled(
        "sample_exponential",
        lambda k, ps, s: jax.random.exponential(k, s) / ps[0],
        (lam,), shape, dtype)


def sample_poisson(lam, shape=None, dtype="float32", **kw):
    return _sampled(
        "sample_poisson",
        lambda k, ps, s: jax.random.poisson(k, ps[0], s).astype(jnp.float32),
        (lam,), shape, dtype)


def _negbin_draw(key, k_param, p, shape):
    """NB(k successes, prob p) via the Gamma-Poisson mixture the reference
    uses: lambda ~ Gamma(k, (1-p)/p), X ~ Poisson(lambda)."""
    kg, kp = jax.random.split(key)
    lam = jax.random.gamma(kg, k_param, shape) * (1.0 - p) / p
    return jax.random.poisson(kp, lam, shape).astype(jnp.float32)


def sample_negative_binomial(k, p, shape=None, dtype="float32", **kw):
    return _sampled(
        "sample_negative_binomial",
        lambda key, ps, s: _negbin_draw(key, ps[0], ps[1], s),
        (k, p), shape, dtype)


def sample_generalized_negative_binomial(mu, alpha, shape=None,
                                         dtype="float32", **kw):
    """Mean/dispersion parameterization (REF:sample_op.cc
    GeneralizedNegativeBinomial): lambda ~ Gamma(1/alpha, alpha*mu)."""

    def draw(key, ps, s):
        m, a = ps
        kg, kp = jax.random.split(key)
        lam = jax.random.gamma(kg, 1.0 / a, s) * (a * m)
        return jax.random.poisson(kp, lam, s).astype(jnp.float32)

    return _sampled("sample_generalized_negative_binomial", draw,
                    (mu, alpha), shape, dtype)


def random_negative_binomial(k=1, p=1.0, shape=(1,), dtype="float32",
                             ctx=None, **kw):
    from .. import random as _random
    from .ops import _place
    key = _random.take_key()
    data = _negbin_draw(key, float(k), float(p),
                        tuple(shape) if shape else ())
    return _place(data.astype(jnp.dtype(dtype)), ctx)


def random_generalized_negative_binomial(mu=1.0, alpha=1.0, shape=(1,),
                                         dtype="float32", ctx=None, **kw):
    from .. import random as _random
    from .ops import _place
    key = _random.take_key()
    kg, kp = jax.random.split(key)
    s = tuple(shape) if shape else ()
    lam = jax.random.gamma(kg, 1.0 / alpha, s) * (alpha * mu)
    data = jax.random.poisson(kp, lam, s).astype(jnp.dtype(dtype))
    return _place(data, ctx)
