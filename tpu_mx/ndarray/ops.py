"""Operator library: `mx.nd.*` over jax.numpy / lax, with tape recording.

TPU-native analog of the reference operator library (REF:src/operator/** —
mshadow/cuDNN/MKLDNN kernels registered via NNVM).  Design (SURVEY §7.1):
every op has a *pure functional core* on raw `jax.Array`s, compiled by XLA
(which supplies the fusion/memory-planning the reference got from NNVM passes
and hand-written kernels).  The `_apply` wrapper gives the imperative face:
it unwraps NDArray handles, records a `jax.vjp` pullback on the autograd tape
when needed (the FGradient analog), and re-wraps outputs.  Called with raw
arrays (inside a `hybridize()` trace) it is a zero-overhead passthrough, so
one namespace serves both `F=mx.nd` and the traced path — the reference got
the same duality from its nd/sym twin stubs.
"""
from __future__ import annotations

import builtins
import functools
import math as _math

import jax
import jax.numpy as jnp
import numpy as _np
from jax import lax

from .. import autograd
from .. import _functional
from .. import fusion as _fusion
from .. import layout as _layout_mod
from .ndarray import NDArray, array, concatenate, load, save, waitall
from ..context import current_context

_abs = builtins.abs
_sum = builtins.sum
_max = builtins.max
_min = builtins.min


# ----------------------------------------------------------------------------
# imperative invoke (analog of REF:src/imperative/imperative.cc Imperative::Invoke)
# ----------------------------------------------------------------------------
def _is_traced(x):
    return isinstance(x, jax.core.Tracer)


def _raw(a):
    if isinstance(a, NDArray):
        return a._data
    if isinstance(a, (jax.Array, _np.ndarray)) or _is_traced(a):
        return a
    return a  # python scalar — kept as-is so jnp broadcasting rules apply


def _scalar_key(*vals):
    """Type-tagged scalars for fuse keys.  1 == 1.0 == True in Python, so
    bare values would collide across spellings — but each bakes a
    DIFFERENT trace constant into the op's closure (int vs weak-float
    promotion), and a key collision replays the wrong cached program with
    the wrong output dtype vs eager."""
    return tuple((type(v).__name__, v) for v in vals)


def _apply(fn, args, name="op", nondiff=False, fuse=None):
    """Dispatch one op: args = tensor positionals (NDArray | array | scalar).

    `fuse` marks the op fusible for engine bulking: a hashable key naming
    the op AND every static parameter its `fn` closes over (the fusion
    cache replays a previously traced chain on key match, so anything that
    changes the math must be in the key).  None = non-fusible; reading the
    args below is then the flush barrier for any lazy inputs."""
    if _functional.active() or not any(isinstance(a, NDArray) for a in args):
        # functional mode: inside a hybridize/apply trace (even if an NDArray
        # leaked in via a creation op), or a pure-array call — no wrapping,
        # no tape
        return fn(*[_raw(a) for a in args])
    if fuse is not None and _fusion.enabled():
        res = _fusion.append(fn, args, name, fuse, nondiff)
        if res is not None:
            return res
    datas = [_raw(a) for a in args]

    diff_idx = [
        i for i, a in enumerate(args)
        # inexact = floats AND complex: fft chains produce complex64
        # intermediates whose cotangents must keep flowing
        if isinstance(a, NDArray) and jnp.issubdtype(a.dtype, jnp.inexact)
    ]
    diff_inputs = [args[i] for i in diff_idx]

    if not nondiff and diff_idx and autograd._needs_tape(diff_inputs):
        def closed(*diff_datas):
            full = list(datas)
            for i, d in zip(diff_idx, diff_datas):
                full[i] = d
            out = fn(*full)
            # normalize list outputs (jnp.split family) to tuples so the
            # pullback's expected cotangent pytree matches what backward
            # builds (a tuple)
            return tuple(out) if isinstance(out, list) else out

        out_data, vjp_fn = jax.vjp(closed, *[datas[i] for i in diff_idx])
        multi = isinstance(out_data, (tuple, list))
        outs_raw = list(out_data) if multi else [out_data]
        if any(jnp.issubdtype(o.dtype, jnp.inexact) for o in outs_raw):
            # record even MIXED-dtype outputs (frexp's mantissa/exponent):
            # backward supplies float0 cotangents for the integer ones —
            # dropping the whole op would silently zero real gradients
            outs = [NDArray(o) for o in outs_raw]
            autograd._record_op(vjp_fn, diff_inputs, outs, name=name)
            return outs if multi else outs[0]
        # all-integer output: fall through unrecorded
        out_data = tuple(outs_raw) if multi else outs_raw[0]
    else:
        out_data = fn(*datas)

    if isinstance(out_data, (tuple, list)):
        return [NDArray(o) for o in out_data]
    return NDArray(out_data)


def _index(a, key):
    return _apply(lambda x: x[key], [a], name="index")


# ----------------------------------------------------------------------------
# creation ops
# ----------------------------------------------------------------------------
def _place(data, ctx):
    if _functional.active():
        return data  # raw inside a functional trace
    return NDArray(data, ctx=ctx or current_context())


def zeros(shape, ctx=None, dtype="float32", **kw):
    return _place(jnp.zeros(shape, dtype=dtype), ctx)


def ones(shape, ctx=None, dtype="float32", **kw):
    return _place(jnp.ones(shape, dtype=dtype), ctx)


def full(shape, val, ctx=None, dtype="float32", **kw):
    return _place(jnp.full(shape, val, dtype=dtype), ctx)


def empty(shape, ctx=None, dtype="float32"):
    return zeros(shape, ctx=ctx, dtype=dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32"):
    a = jnp.arange(start, stop, step, dtype=dtype)
    if repeat != 1:
        a = jnp.repeat(a, repeat)
    return _place(a, ctx)


def linspace(start, stop, num, endpoint=True, ctx=None, dtype="float32"):
    return _place(jnp.linspace(start, stop, num, endpoint=endpoint, dtype=dtype), ctx)


def eye(N, M=0, k=0, ctx=None, dtype="float32"):
    return _place(jnp.eye(N, M if M else N, k=k, dtype=dtype), ctx)


def zeros_like(a, **kw):
    return _apply(jnp.zeros_like, [a], "zeros_like", nondiff=True,
                  fuse="zeros_like")


def ones_like(a, **kw):
    return _apply(jnp.ones_like, [a], "ones_like", nondiff=True,
                  fuse="ones_like")


def full_like(a, fill_value, **kw):
    fuse = ("full_like",) + _scalar_key(fill_value) \
        if isinstance(fill_value, (int, float)) else None
    return _apply(lambda x: jnp.full_like(x, fill_value), [a], "full_like",
                  nondiff=True, fuse=fuse)


# ----------------------------------------------------------------------------
# unary elementwise
# ----------------------------------------------------------------------------
def _unary(jfn, name):
    def op(data, out=None, **kw):
        # fusible: jfn is a module-level pure function, the name alone is
        # a complete chain-cache key.  The out= path realizes immediately
        # (res._data is a flush barrier) — in-place targets keep strict
        # eager rebind semantics.
        res = _apply(jfn, [data], name, fuse=name)
        if out is not None:
            out._rebind(res._data if isinstance(res, NDArray) else res)
            return out
        return res
    op.__name__ = name
    return op


abs = _unary(jnp.abs, "abs")
sign = _unary(jnp.sign, "sign")
ceil = _unary(jnp.ceil, "ceil")
floor = _unary(jnp.floor, "floor")
trunc = _unary(jnp.trunc, "trunc")
round = _unary(jnp.round, "round")
rint = _unary(jnp.rint, "rint")
fix = _unary(jnp.trunc, "fix")
exp = _unary(jnp.exp, "exp")
expm1 = _unary(jnp.expm1, "expm1")
log = _unary(jnp.log, "log")
log2 = _unary(jnp.log2, "log2")
log10 = _unary(jnp.log10, "log10")
log1p = _unary(jnp.log1p, "log1p")
sqrt = _unary(jnp.sqrt, "sqrt")
rsqrt = _unary(lambda x: lax.rsqrt(x), "rsqrt")
cbrt = _unary(jnp.cbrt, "cbrt")
rcbrt = _unary(lambda x: 1.0 / jnp.cbrt(x), "rcbrt")
square = _unary(jnp.square, "square")
reciprocal = _unary(lambda x: 1.0 / x, "reciprocal")
negative = _unary(jnp.negative, "negative")
sin = _unary(jnp.sin, "sin")
cos = _unary(jnp.cos, "cos")
tan = _unary(jnp.tan, "tan")
arcsin = _unary(jnp.arcsin, "arcsin")
arccos = _unary(jnp.arccos, "arccos")
arctan = _unary(jnp.arctan, "arctan")
sinh = _unary(jnp.sinh, "sinh")
cosh = _unary(jnp.cosh, "cosh")
tanh = _unary(jnp.tanh, "tanh")
arcsinh = _unary(jnp.arcsinh, "arcsinh")
arccosh = _unary(jnp.arccosh, "arccosh")
arctanh = _unary(jnp.arctanh, "arctanh")
degrees = _unary(jnp.degrees, "degrees")
radians = _unary(jnp.radians, "radians")
sigmoid = _unary(jax.nn.sigmoid, "sigmoid")
softsign = _unary(jax.nn.soft_sign, "softsign")
relu = _unary(jax.nn.relu, "relu")
erf = _unary(jax.scipy.special.erf, "erf")
erfinv = _unary(jax.scipy.special.erfinv, "erfinv")
gammaln = _unary(jax.scipy.special.gammaln, "gammaln")
gamma = _unary(lambda x: jnp.exp(jax.scipy.special.gammaln(x)), "gamma")
logical_not = _unary(lambda x: (x == 0).astype(x.dtype), "logical_not")
isnan = _unary(jnp.isnan, "isnan")
isinf = _unary(jnp.isinf, "isinf")
isfinite = _unary(jnp.isfinite, "isfinite")


def cast(data, dtype, **kw):
    return _apply(lambda x: x.astype(dtype), [data], "cast",
                  fuse=("cast", jnp.dtype(dtype).name))


Cast = cast


def amp_cast(data, dtype):
    """AMP cast op (reference [ver>=1.5] REF:src/operator/tensor/amp_cast.cc)."""
    return cast(data, dtype)


def amp_multicast(*data, num_outputs=None):
    widest = jnp.result_type(*[d.dtype for d in data])
    return [cast(d, widest) for d in data]


def BlockGrad(data, **kw):
    # fusible: lax.stop_gradient inside the composite blocks the
    # cotangent in the segment's single vjp exactly as not-recording
    # blocks it eagerly
    return _apply(lax.stop_gradient, [data], "BlockGrad", nondiff=True,
                  fuse="BlockGrad")


stop_gradient = BlockGrad


def identity(data, **kw):
    return _apply(lambda x: x, [data], "identity", fuse="identity")


def shape_array(data):
    return _apply(lambda x: jnp.array(x.shape, dtype=jnp.int64), [data], "shape_array",
                  nondiff=True)


def size_array(data):
    return _apply(lambda x: jnp.array([x.size], dtype=jnp.int64), [data], "size_array",
                  nondiff=True)


# ----------------------------------------------------------------------------
# binary elementwise (+ broadcast_* aliases for reference API parity)
# ----------------------------------------------------------------------------
def _binary(jfn, name):
    def op(lhs, rhs, out=None, **kw):
        res = _apply(jfn, [lhs, rhs], name, fuse=name)
        if out is not None:
            out._rebind(res._data)
            return out
        return res
    op.__name__ = name
    return op


add = _binary(jnp.add, "add")
subtract = _binary(jnp.subtract, "subtract")
multiply = _binary(jnp.multiply, "multiply")
divide = _binary(jnp.divide, "divide")
mod = _binary(jnp.mod, "mod")
power = _binary(jnp.power, "power")
maximum = _binary(jnp.maximum, "maximum")
minimum = _binary(jnp.minimum, "minimum")
hypot = _binary(jnp.hypot, "hypot")
arctan2 = _binary(jnp.arctan2, "arctan2")
equal = _binary(lambda a, b: (a == b).astype(jnp.result_type(a, b)), "equal")
not_equal = _binary(lambda a, b: (a != b).astype(jnp.result_type(a, b)), "not_equal")
greater = _binary(lambda a, b: (a > b).astype(jnp.result_type(a, b)), "greater")
greater_equal = _binary(lambda a, b: (a >= b).astype(jnp.result_type(a, b)), "greater_equal")
lesser = _binary(lambda a, b: (a < b).astype(jnp.result_type(a, b)), "lesser")
lesser_equal = _binary(lambda a, b: (a <= b).astype(jnp.result_type(a, b)), "lesser_equal")
logical_and = _binary(lambda a, b: ((a != 0) & (b != 0)).astype(jnp.result_type(a, b)), "logical_and")
logical_or = _binary(lambda a, b: ((a != 0) | (b != 0)).astype(jnp.result_type(a, b)), "logical_or")
logical_xor = _binary(lambda a, b: ((a != 0) ^ (b != 0)).astype(jnp.result_type(a, b)), "logical_xor")

# the reference distinguishes elemwise_* (same-shape) from broadcast_* ops;
# jnp broadcasts everywhere so these are exact aliases
for _nm, _op in [
    ("broadcast_add", add), ("broadcast_plus", add),
    ("broadcast_sub", subtract), ("broadcast_minus", subtract),
    ("broadcast_mul", multiply), ("broadcast_div", divide),
    ("broadcast_mod", mod), ("broadcast_power", power),
    ("broadcast_maximum", maximum), ("broadcast_minimum", minimum),
    ("broadcast_hypot", hypot),
    ("broadcast_equal", equal), ("broadcast_not_equal", not_equal),
    ("broadcast_greater", greater), ("broadcast_greater_equal", greater_equal),
    ("broadcast_lesser", lesser), ("broadcast_lesser_equal", lesser_equal),
    ("broadcast_logical_and", logical_and), ("broadcast_logical_or", logical_or),
    ("broadcast_logical_xor", logical_xor),
    ("elemwise_add", add), ("elemwise_sub", subtract),
    ("elemwise_mul", multiply), ("elemwise_div", divide),
]:
    globals()[_nm] = _op


def add_n(*args, **kw):
    return _apply(lambda *xs: functools.reduce(jnp.add, xs), list(args),
                  "add_n", fuse=("add_n", len(args)))


ElementWiseSum = add_n


# ----------------------------------------------------------------------------
# reductions
# ----------------------------------------------------------------------------
def _norm_axis(axis):
    if isinstance(axis, list):
        return tuple(axis)
    return axis


def _reduce(jfn, name):
    def op(data, axis=None, keepdims=False, exclude=False, **kw):
        ax = _norm_axis(axis)
        if exclude and ax is not None:
            nd_ = data.ndim if hasattr(data, "ndim") else jnp.asarray(data).ndim
            axset = {a % nd_ for a in (ax if isinstance(ax, tuple) else (ax,))}
            ax = tuple(i for i in range(nd_) if i not in axset)
        # the "reduce tail" of a fusible chain; resolved axis/keepdims are
        # the closure's only state, so they complete the key
        return _apply(lambda x: jfn(x, axis=ax, keepdims=keepdims), [data],
                      name, fuse=(name, ax, keepdims))
    op.__name__ = name
    return op


sum = _reduce(jnp.sum, "sum")
mean = _reduce(jnp.mean, "mean")
prod = _reduce(jnp.prod, "prod")
max = _reduce(jnp.max, "max")
min = _reduce(jnp.min, "min")
nansum = _reduce(jnp.nansum, "nansum")
nanprod = _reduce(jnp.nanprod, "nanprod")
sum_axis = sum
max_axis = max
min_axis = min


def argmax(data, axis=None, keepdims=False, **kw):
    return _apply(lambda x: jnp.argmax(x, axis=axis, keepdims=keepdims).astype(jnp.float32),
                  [data], "argmax", nondiff=True,
                  fuse=("argmax", axis, keepdims))


def argmin(data, axis=None, keepdims=False, **kw):
    return _apply(lambda x: jnp.argmin(x, axis=axis, keepdims=keepdims).astype(jnp.float32),
                  [data], "argmin", nondiff=True,
                  fuse=("argmin", axis, keepdims))


def argmax_channel(data, **kw):
    """Argmax over the channel axis (axis 1), returned as float
    (REF:src/operator/tensor/broadcast_reduce_op_index.cc
    argmax_channel — the metric/accuracy helper)."""
    return _apply(lambda x: jnp.argmax(x, axis=1).astype(jnp.float32),
                  [data], "argmax_channel", nondiff=True)


def norm(data, ord=2, axis=None, keepdims=False, **kw):
    ax = _norm_axis(axis)

    def f(x):
        if ord == 1:
            return jnp.sum(jnp.abs(x), axis=ax, keepdims=keepdims)
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=ax, keepdims=keepdims))

    return _apply(f, [data], "norm",
                  fuse=("norm",) + _scalar_key(ord) + (ax, keepdims))


def cumsum(data, axis=None, dtype=None):
    return _apply(lambda x: jnp.cumsum(x, axis=axis, dtype=dtype), [data], "cumsum")


# ----------------------------------------------------------------------------
# shape manipulation
# ----------------------------------------------------------------------------
def reshape(data, shape=None, reverse=False, **kw):
    """MXNet reshape with special codes 0 (keep), -1 (infer), -2.. subset."""
    target = tuple(shape)

    def f(x):
        out, src = [], list(x.shape)
        i = 0
        for s in target:
            if s == 0:
                out.append(src[i]); i += 1
            elif s == -1:
                out.append(-1); i += 1
            elif s == -2:
                out.extend(src[i:]); i = len(src)
            elif s == -3:
                out.append(src[i] * src[i + 1]); i += 2
            elif s == -4:
                continue  # handled by following explicit dims
            else:
                out.append(s); i += 1
        return jnp.reshape(x, tuple(out))

    return _apply(f, [data], "reshape")


def reshape_like(lhs, rhs, **kw):
    return _apply(lambda x, y: jnp.reshape(x, y.shape), [lhs, rhs], "reshape_like")


def flatten(data, **kw):
    return _apply(lambda x: jnp.reshape(x, (x.shape[0], -1)), [data], "flatten")


Flatten = flatten


def transpose(data, axes=None, **kw):
    ax = tuple(axes) if axes else None
    return _apply(lambda x: jnp.transpose(x, ax), [data], "transpose")


def swapaxes(data, dim1=0, dim2=0, **kw):
    return _apply(lambda x: jnp.swapaxes(x, dim1, dim2), [data], "swapaxes")


SwapAxis = swapaxes


def expand_dims(data, axis, **kw):
    return _apply(lambda x: jnp.expand_dims(x, axis), [data], "expand_dims")


def space_to_depth(data, block_size, **kw):
    """REF:src/operator/tensor/matrix_op.cc space_to_depth — NCHW:
    (N,C,H,W) -> (N, b²C, H/b, W/b), block offsets leading the channels."""
    b = int(block_size)

    def f(x):
        n, c, h, w = x.shape
        y = jnp.reshape(x, (n, c, h // b, b, w // b, b))
        y = jnp.transpose(y, (0, 3, 5, 1, 2, 4))
        return jnp.reshape(y, (n, b * b * c, h // b, w // b))

    return _apply(f, [data], "space_to_depth")


def depth_to_space(data, block_size, **kw):
    """Inverse of space_to_depth (REF:src/operator/tensor/matrix_op.cc)."""
    b = int(block_size)

    def f(x):
        n, c, h, w = x.shape
        y = jnp.reshape(x, (n, b, b, c // (b * b), h, w))
        y = jnp.transpose(y, (0, 3, 4, 1, 5, 2))
        return jnp.reshape(y, (n, c // (b * b), h * b, w * b))

    return _apply(f, [data], "depth_to_space")


def squeeze(data, axis=None, **kw):
    return _apply(lambda x: jnp.squeeze(x, axis=axis), [data], "squeeze")


def broadcast_to(data, shape, **kw):
    tgt = tuple(shape)

    def f(x):
        # MXNet allows 0 meaning "keep this dim"
        full = tuple(x.shape[i] if s == 0 else s for i, s in enumerate(tgt))
        return jnp.broadcast_to(x, full)

    return _apply(f, [data], "broadcast_to")


def broadcast_axis(data, axis=0, size=1, **kw):
    axes = axis if isinstance(axis, (list, tuple)) else (axis,)
    sizes = size if isinstance(size, (list, tuple)) else (size,)

    def f(x):
        shp = list(x.shape)
        for a, s in zip(axes, sizes):
            shp[a] = s
        return jnp.broadcast_to(x, tuple(shp))

    return _apply(f, [data], "broadcast_axis")


def broadcast_like(lhs, rhs, **kw):
    return _apply(lambda x, y: jnp.broadcast_to(x, y.shape), [lhs, rhs], "broadcast_like")


def flip(data, axis, **kw):
    return _apply(lambda x: jnp.flip(x, axis=axis), [data], "flip")


reverse = flip


def tile(data, reps, **kw):
    return _apply(lambda x: jnp.tile(x, reps), [data], "tile")


def repeat(data, repeats, axis=None, **kw):
    return _apply(lambda x: jnp.repeat(x, repeats, axis=axis), [data], "repeat")


def pad(data, mode="constant", pad_width=None, constant_value=0, **kw):
    """Reference pad op: pad_width is the flat (before,after) per-dim tuple."""
    pw = list(zip(pad_width[::2], pad_width[1::2]))
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]

    def f(x):
        if jmode == "constant":
            return jnp.pad(x, pw, mode="constant", constant_values=constant_value)
        return jnp.pad(x, pw, mode=jmode)

    return _apply(f, [data], "pad")


Pad = pad


def concat(*data, dim=1, **kw):
    if len(data) == 1 and isinstance(data[0], (list, tuple)):
        data = tuple(data[0])
    return _apply(lambda *xs: jnp.concatenate(xs, axis=dim), list(data), "concat")


Concat = concat


def stack(*data, axis=0, **kw):
    if len(data) == 1 and isinstance(data[0], (list, tuple)):
        data = tuple(data[0])
    return _apply(lambda *xs: jnp.stack(xs, axis=axis), list(data), "stack")


def split(data, num_outputs, axis=1, squeeze_axis=False, **kw):
    def f(x):
        parts = jnp.split(x, num_outputs, axis=axis)
        if squeeze_axis:
            parts = [jnp.squeeze(p, axis=axis) for p in parts]
        return tuple(parts)

    out = _apply(f, [data], "split")
    return out


SliceChannel = split


def slice(data, begin, end, step=None, **kw):
    def f(x):
        idx = []
        for i in range(len(begin)):
            b = begin[i]
            e = end[i] if end[i] is not None else x.shape[i]
            s = (step[i] if step else None) or 1
            idx.append(builtins.slice(b, e, s))
        return x[tuple(idx)]

    return _apply(f, [data], "slice")


def slice_axis(data, axis, begin, end, **kw):
    def f(x):
        e = end if end is not None else x.shape[axis]
        return lax.slice_in_dim(x, begin, e, axis=axis)

    return _apply(f, [data], "slice_axis")


def slice_like(data, shape_like, axes=None, **kw):
    def f(x, y):
        idx = [builtins.slice(None)] * x.ndim
        dims = axes if axes is not None else range(y.ndim)
        for a in dims:
            idx[a] = builtins.slice(0, y.shape[a])
        return x[tuple(idx)]

    return _apply(f, [data, shape_like], "slice_like")


def clip(data, a_min, a_max, **kw):
    fuse = ("clip",) + _scalar_key(a_min, a_max) \
        if isinstance(a_min, (int, float)) and isinstance(a_max, (int, float)) \
        else None
    return _apply(lambda x: jnp.clip(x, a_min, a_max), [data], "clip",
                  fuse=fuse)


def where(condition, x, y, **kw):
    return _apply(lambda c, a, b: jnp.where(c != 0, a, b), [condition, x, y],
                  "where", fuse="where")


# ----------------------------------------------------------------------------
# indexing ops
# ----------------------------------------------------------------------------
def take(a, indices, axis=0, mode="clip", **kw):
    jmode = {"clip": "clip", "wrap": "wrap", "raise": "clip"}[mode]
    return _apply(
        lambda x, i: jnp.take(x, i.astype(jnp.int32), axis=axis, mode=jmode),
        [a, indices], "take")


def pick(data, index, axis=-1, keepdims=False, **kw):
    def f(x, i):
        out = jnp.take_along_axis(
            x, jnp.expand_dims(i.astype(jnp.int32), axis=axis), axis=axis)
        return out if keepdims else jnp.squeeze(out, axis=axis)

    return _apply(f, [data, index], "pick")


def gather_nd(data, indices, **kw):
    def f(x, i):
        i = i.astype(jnp.int32)
        return x[tuple(i[k] for k in range(i.shape[0]))]

    return _apply(f, [data, indices], "gather_nd")


def scatter_nd(data, indices, shape, **kw):
    def f(d, i):
        i = i.astype(jnp.int32)
        out = jnp.zeros(tuple(shape), d.dtype)
        return out.at[tuple(i[k] for k in range(i.shape[0]))].add(d)

    return _apply(f, [data, indices], "scatter_nd")


def one_hot(indices, depth, on_value=1.0, off_value=0.0, dtype="float32", **kw):
    def f(i):
        oh = jax.nn.one_hot(i.astype(jnp.int32), depth, dtype=jnp.dtype(dtype))
        return oh * (on_value - off_value) + off_value

    return _apply(f, [indices], "one_hot", nondiff=True)


def Embedding(data, weight, input_dim=None, output_dim=None, dtype="float32",
              sparse_grad=False, **kw):
    """Embedding lookup (REF:src/operator/tensor/indexing_op.cc).  `sparse_grad`
    (row_sparse in the reference) has no TPU analog; gradients are dense —
    XLA turns the gather-vjp into an efficient scatter-add (SURVEY §7.3.4)."""
    return _apply(lambda i, w: jnp.take(w, i.astype(jnp.int32), axis=0),
                  [data, weight], "Embedding")


def SequenceMask(data, sequence_length=None, use_sequence_length=False, value=0.0,
                 axis=0, **kw):
    if not use_sequence_length or sequence_length is None:
        return identity(data)

    def f(x, sl):
        steps = jnp.arange(x.shape[axis])
        mask = steps[:, None] < sl[None, :]  # (T, B)
        if axis == 1:
            mask = mask.T
        mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
        return jnp.where(mask, x, jnp.asarray(value, x.dtype))

    return _apply(f, [data, sequence_length], "SequenceMask")


def SequenceReverse(data, sequence_length=None, use_sequence_length=False, axis=0, **kw):
    if not use_sequence_length or sequence_length is None:
        return flip(data, axis=axis)

    def f(x, sl):
        T = x.shape[axis]
        idx = jnp.arange(T)[:, None]  # (T,1)
        rev = sl[None, :].astype(jnp.int32) - 1 - idx
        gather_idx = jnp.where(idx < sl[None, :], rev, idx)  # (T,B)
        return jnp.take_along_axis(
            x, gather_idx.reshape(gather_idx.shape + (1,) * (x.ndim - 2)), axis=0)

    return _apply(f, [data, sequence_length], "SequenceReverse")


def SequenceLast(data, sequence_length=None, use_sequence_length=False, axis=0, **kw):
    def f(x, *sl):
        if sl:
            idx = sl[0].astype(jnp.int32) - 1
        else:
            idx = jnp.full((x.shape[1],), x.shape[axis] - 1, jnp.int32)
        return jnp.take_along_axis(
            x, idx.reshape((1, -1) + (1,) * (x.ndim - 2)), axis=0)[0]

    args = [data] + ([sequence_length] if use_sequence_length else [])
    return _apply(f, args, "SequenceLast")


# ----------------------------------------------------------------------------
# ordering
# ----------------------------------------------------------------------------
def sort(data, axis=-1, is_ascend=True, **kw):
    def f(x):
        s = jnp.sort(x, axis=axis)
        return s if is_ascend else jnp.flip(s, axis=axis)

    return _apply(f, [data], "sort")


def argsort(data, axis=-1, is_ascend=True, dtype="float32", **kw):
    def f(x):
        s = jnp.argsort(x, axis=axis)
        if not is_ascend:
            s = jnp.flip(s, axis=axis)
        return s.astype(jnp.dtype(dtype))

    return _apply(f, [data], "argsort", nondiff=True)


def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32", **kw):
    def f(x):
        xm = jnp.moveaxis(x, axis, -1)
        vals, idx = lax.top_k(-xm if is_ascend else xm, k)
        if is_ascend:
            vals = -vals
        vals = jnp.moveaxis(vals, -1, axis)
        idx = jnp.moveaxis(idx, -1, axis)
        if ret_typ == "value":
            return vals
        if ret_typ == "indices":
            return idx.astype(jnp.dtype(dtype))
        if ret_typ == "both":
            return (vals, idx.astype(jnp.dtype(dtype)))
        if ret_typ == "mask":
            m = jnp.zeros_like(xm, dtype=jnp.dtype(dtype))
            m = m.at[..., :].set(0)
            oh = jax.nn.one_hot(jnp.moveaxis(idx, axis, -1), x.shape[axis],
                                dtype=jnp.dtype(dtype)).sum(-2)
            return jnp.moveaxis(oh, -1, axis)
        raise ValueError(ret_typ)

    nondiff = ret_typ != "value"
    return _apply(f, [data], "topk", nondiff=nondiff)


# ----------------------------------------------------------------------------
# linear algebra
# ----------------------------------------------------------------------------
def dot(lhs, rhs, transpose_a=False, transpose_b=False, **kw):
    """Reference `dot`: contracts last axis of lhs with first of rhs; the
    transpose flags apply matrix-transpose semantics (2-D fast path hits the
    MXU as a single matmul)."""

    def f(a, b):
        if transpose_a:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_b:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        if a.ndim == 2 and b.ndim == 2:
            return a @ b
        return jnp.tensordot(a, b, axes=([-1], [0]))

    return _apply(f, [lhs, rhs], "dot")


def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False, **kw):
    def f(a, b):
        if transpose_a:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_b:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)

    return _apply(f, [lhs, rhs], "batch_dot")


def linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0, **kw):
    def f(a, b):
        if transpose_a:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_b:
            b = jnp.swapaxes(b, -1, -2)
        return alpha * jnp.matmul(a, b)

    return _apply(f, [A, B], "linalg_gemm2")


def linalg_potrf(A, **kw):
    return _apply(lambda a: jnp.linalg.cholesky(a), [A], "linalg_potrf")


def linalg_syrk(A, transpose=False, alpha=1.0, **kw):
    def f(a):
        at = jnp.swapaxes(a, -1, -2)
        return alpha * (jnp.matmul(at, a) if transpose else jnp.matmul(a, at))

    return _apply(f, [A], "linalg_syrk")


# ----------------------------------------------------------------------------
# neural-net ops (REF:src/operator/nn/**) — XLA-native forms
# ----------------------------------------------------------------------------
def _pair(v, n):
    if v is None:
        return (0,) * n if n else ()
    if isinstance(v, int):
        return (v,) * n
    t = tuple(v)
    return t if len(t) == n else t + t[-1:] * (n - len(t))


def FullyConnected(data, weight, bias=None, num_hidden=None, no_bias=False,
                   flatten=True, **kw):
    """y = x·Wᵀ + b (REF:src/operator/nn/fully_connected.cc).  Contracted as a
    single MXU matmul; `flatten` collapses trailing dims like the reference."""

    def f(x, w, *b):
        if flatten and x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        y = jnp.matmul(x, w.T) if x.ndim <= 2 else jnp.einsum("...i,oi->...o", x, w)
        if b:
            y = y + b[0]
        return y

    args = [data, weight] + ([] if (no_bias or bias is None) else [bias])
    return _apply(f, args, "FullyConnected")


def Convolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                pad=None, num_filter=None, num_group=1, no_bias=False,
                layout=None, **kw):
    """N-D convolution (REF:src/operator/nn/convolution.cc; cuDNN path replaced
    by `lax.conv_general_dilated`, which XLA tiles onto the MXU).

    `layout` selects the data layout as in the reference ("NCHW", "NHWC",
    "NCW", "NWC", "NCDHW", "NDHWC"; default channels-first).  Channels-last
    puts C in the TPU lane dimension, so prefer NHWC for the image path
    (weight layout is then O<spatial>I, matching the reference's NHWC
    convention)."""
    nd_ = len(kernel)
    strides = _pair(stride, nd_) if stride else (1,) * nd_
    dilation = _pair(dilate, nd_) if dilate else (1,) * nd_
    padding = [(p, p) for p in (_pair(pad, nd_) if pad else (0,) * nd_)]
    spatial = "DHW"[-nd_:]
    if layout is None:
        layout = "NC" + spatial
    channels_last = _layout_mod.is_channels_last(layout)
    wspec = ("O" + spatial + "I") if channels_last else ("OI" + spatial)
    dn = (layout, wspec, layout)
    bshape = ((1,) * (nd_ + 1) + (-1,)) if channels_last \
        else ((1, -1) + (1,) * nd_)

    def f(x, w, *b):
        # NOTE: no preferred_element_type — jax 0.9's conv transpose rule
        # emits mismatched-dtype convs under grad with it; XLA:TPU already
        # accumulates bf16 convs in f32 on the MXU
        y = lax.conv_general_dilated(
            x, w, window_strides=strides, padding=padding,
            rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=num_group)
        if b:
            y = y + b[0].reshape(bshape)
        return y

    args = [data, weight] + ([] if (no_bias or bias is None) else [bias])
    return _apply(f, args, "Convolution")


def Deconvolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                  pad=None, adj=None, num_filter=None, num_group=1, no_bias=True,
                  layout=None, **kw):
    """Transposed conv (REF:src/operator/nn/deconvolution.cc).  `adj` (the
    output_padding) extends the trailing pad so out = (i-1)*s - 2p + d*(k-1)
    + 1 + adj, matching the reference's output-size formula.  `layout` as in
    Convolution; channels-last weights are I<spatial>O."""
    nd_ = len(kernel)
    strides = _pair(stride, nd_) if stride else (1,) * nd_
    dilation = _pair(dilate, nd_) if dilate else (1,) * nd_
    padding = _pair(pad, nd_) if pad else (0,) * nd_
    adjust = _pair(adj, nd_) if adj else (0,) * nd_
    spatial = "DHW"[-nd_:]
    if layout is None:
        layout = "NC" + spatial
    channels_last = _layout_mod.is_channels_last(layout)
    wspec = ("I" + spatial + "O") if channels_last else ("IO" + spatial)
    dn = (layout, wspec, layout)
    bshape = ((1,) * (nd_ + 1) + (-1,)) if channels_last \
        else ((1, -1) + (1,) * nd_)

    def f(x, w, *b):
        pads = [(d * (k - 1) - p, d * (k - 1) - p + a)
                for k, p, a, d in zip(kernel, padding, adjust, dilation)]
        y = lax.conv_general_dilated(
            x, w, window_strides=(1,) * nd_, padding=pads,
            lhs_dilation=strides, rhs_dilation=dilation,
            dimension_numbers=dn, feature_group_count=num_group)
        if b:
            y = y + b[0].reshape(bshape)
        return y

    args = [data, weight] + ([] if (no_bias or bias is None) else [bias])
    return _apply(f, args, "Deconvolution")


def Pooling(data, kernel=None, pool_type="max", global_pool=False, stride=None,
            pad=None, pooling_convention="valid", count_include_pad=True,
            layout=None, **kw):
    """Max/avg/sum pooling via `lax.reduce_window`
    (REF:src/operator/nn/pooling.cc).  `layout` as in Convolution."""
    channels_last = _layout_mod.is_channels_last(layout)

    def f(x):
        nd_ = x.ndim - 2
        spatial_axes = tuple(range(1, x.ndim - 1)) if channels_last \
            else tuple(range(2, x.ndim))
        if global_pool:
            return x.mean(axis=spatial_axes, keepdims=True) \
                if pool_type == "avg" else (
                    x.max(axis=spatial_axes, keepdims=True)
                    if pool_type == "max"
                    else x.sum(axis=spatial_axes, keepdims=True))
        k = _pair(kernel, nd_)
        s = _pair(stride, nd_) if stride else k
        p = _pair(pad, nd_) if pad else (0,) * nd_
        if pooling_convention == "full":
            # ceil-mode: extend right/bottom padding so no element is dropped
            spad = [(pp, pp + st - 1) for pp, st in zip(p, s)]
        else:
            spad = [(pp, pp) for pp in p]
        if channels_last:
            window, strides = (1,) + k + (1,), (1,) + s + (1,)
            padding = [(0, 0)] + spad + [(0, 0)]
        else:
            window, strides = (1, 1) + k, (1, 1) + s
            padding = [(0, 0), (0, 0)] + spad
        if pool_type == "max":
            init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
            return lax.reduce_window(x, init, lax.max, window, strides, padding)
        ssum = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
        if pool_type == "sum":
            return ssum
        if count_include_pad:
            return ssum / _np.prod(k)
        ones_ = jnp.ones_like(x)
        cnt = lax.reduce_window(ones_, 0.0, lax.add, window, strides, padding)
        return ssum / cnt

    return _apply(f, [data], "Pooling")


def Activation(data, act_type="relu", **kw):
    fns = {
        "relu": jax.nn.relu,
        "sigmoid": jax.nn.sigmoid,
        "tanh": jnp.tanh,
        "softrelu": jax.nn.softplus,
        "softsign": jax.nn.soft_sign,
    }
    return _apply(fns[act_type], [data], f"Activation[{act_type}]")


def LeakyReLU(data, gamma=None, act_type="leaky", slope=0.25, lower_bound=0.125,
              upper_bound=0.334, **kw):
    if act_type == "leaky":
        return _apply(lambda x: jax.nn.leaky_relu(x, slope), [data], "LeakyReLU")
    if act_type == "elu":
        return _apply(lambda x: jax.nn.elu(x, slope), [data], "elu")
    if act_type == "selu":
        return _apply(jax.nn.selu, [data], "selu")
    if act_type == "gelu":
        return _apply(lambda x: jax.nn.gelu(x, approximate=False), [data], "gelu")
    if act_type == "prelu":
        return _apply(lambda x, g: jnp.where(x >= 0, x, g * x), [data, gamma], "prelu")
    raise ValueError(act_type)


def gelu(data, **kw):
    return _apply(lambda x: jax.nn.gelu(x, approximate=False), [data], "gelu")


def gelu_tanh(data, **kw):
    return _apply(lambda x: jax.nn.gelu(x, approximate=True), [data], "gelu_tanh")


def softmax(data, axis=-1, temperature=None, length=None, **kw):
    def f(x, *ln):
        z = x / temperature if temperature else x
        if ln:
            steps = jnp.arange(x.shape[axis])
            shape = [1] * x.ndim
            shape[axis] = x.shape[axis]
            mask = steps.reshape(shape) < ln[0].reshape(
                ln[0].shape + (1,) * (x.ndim - ln[0].ndim))
            z = jnp.where(mask, z, -jnp.inf)
        return jax.nn.softmax(z, axis=axis)

    args = [data] + ([length] if length is not None else [])
    fuse = ("softmax", axis) + _scalar_key(temperature) if length is None \
        and isinstance(temperature, (int, float, type(None))) else None
    return _apply(f, args, "softmax", fuse=fuse)


def log_softmax(data, axis=-1, temperature=None, **kw):
    def f(x):
        z = x / temperature if temperature else x
        return jax.nn.log_softmax(z, axis=axis)

    fuse = ("log_softmax", axis) + _scalar_key(temperature) \
        if isinstance(temperature, (int, float, type(None))) else None
    return _apply(f, [data], "log_softmax", fuse=fuse)


def softmin(data, axis=-1, **kw):
    return _apply(lambda x: jax.nn.softmax(-x, axis=axis), [data], "softmin")


def softmax_cross_entropy(data, label, **kw):
    def f(x, y):
        logp = jax.nn.log_softmax(x, axis=-1)
        oh = jax.nn.one_hot(y.astype(jnp.int32), x.shape[-1], dtype=x.dtype)
        return -jnp.sum(oh * logp)

    return _apply(f, [data, label], "softmax_cross_entropy")


def SoftmaxActivation(data, mode="instance", **kw):
    axis = 1 if mode == "channel" else -1
    return softmax(data, axis=axis)


def _onepass_stats(xf, axis, keepdims=True):
    """(mean, var) via sum / sum-of-squares in ONE pass.  ONLY for
    reductions that span non-minor axes over more data than a VMEM tile
    (BatchNorm's (N, *S) reduce): there the classic mean->var chain
    forces two real HBM reads, while sibling sums fuse into one.  For
    ROW-LOCAL norms (LayerNorm & friends, minor-axis reduce) XLA already
    fuses the whole chain into one pass per row — use the two-pass
    mean/var there: it costs nothing and is cancellation-safe, whereas
    E[x^2]-E[x]^2 in f32 collapses for |mean|/std ≳ 1e3 (var rounds to
    the 0-clamp and rsqrt(eps) amplifies garbage).  BatchNorm inputs are
    post-conv/near-zero-mean, where the cancellation is benign."""
    n = 1
    ax = axis if isinstance(axis, tuple) else (axis,)
    for a in ax:
        n *= xf.shape[a]
    s1 = xf.sum(axis=axis, keepdims=keepdims)
    s2 = jnp.square(xf).sum(axis=axis, keepdims=keepdims)
    mu = s1 / n
    return mu, jnp.maximum(s2 / n - jnp.square(mu), 0.0)


def LayerNorm(data, gamma=None, beta=None, axis=-1, eps=1e-5, **kw):
    """REF:src/operator/nn/layer_norm.cc — fp32 statistics for bf16
    inputs.  Two-pass mean/var on purpose: the reduce is row-local
    (minor axis), which XLA fuses into one HBM pass anyway, and the
    two-pass form is cancellation-safe (see _onepass_stats)."""

    def f(x, g, b):
        xf = x.astype(jnp.float32)
        mu = xf.mean(axis=axis, keepdims=True)
        var = jnp.square(xf - mu).mean(axis=axis, keepdims=True)
        y = (xf - mu) * lax.rsqrt(var + eps)
        shape = [1] * x.ndim
        shape[axis] = x.shape[axis]
        return (y * g.reshape(shape) + b.reshape(shape)).astype(x.dtype)

    return _apply(f, [data, gamma, beta], "LayerNorm")


def RMSNorm(data, gamma=None, axis=-1, eps=1e-6, **kw):
    def f(x, g):
        xf = x.astype(jnp.float32)
        ms = jnp.square(xf).mean(axis=axis, keepdims=True)
        shape = [1] * x.ndim
        shape[axis] = x.shape[axis]
        return (xf * lax.rsqrt(ms + eps) * g.reshape(shape)).astype(x.dtype)

    return _apply(f, [data, gamma], "RMSNorm")


def InstanceNorm(data, gamma, beta, eps=1e-3, **kw):
    def f(x, g, b):
        ax = tuple(range(2, x.ndim))
        xf = x.astype(jnp.float32)
        mu = xf.mean(axis=ax, keepdims=True)
        var = jnp.square(xf - mu).mean(axis=ax, keepdims=True)
        shape = (1, -1) + (1,) * (x.ndim - 2)
        gf = g.reshape(shape).astype(jnp.float32)
        bf = b.reshape(shape).astype(jnp.float32)
        return ((xf - mu) * lax.rsqrt(var + eps) * gf + bf).astype(x.dtype)

    return _apply(f, [data, gamma, beta], "InstanceNorm")


def GroupNorm(data, gamma, beta, num_groups=1, eps=1e-5, **kw):
    """REF:src/operator/nn/group_norm.cc — (N, C, *S) input, C split into
    num_groups; f32 statistics for low-precision inputs."""
    def f(x, g, b):
        n = x.shape[0]
        xf = x.astype(jnp.float32).reshape((n, num_groups, -1))
        mu = xf.mean(axis=2, keepdims=True)
        var = jnp.square(xf - mu).mean(axis=2, keepdims=True)
        yf = (xf - mu) * lax.rsqrt(var + eps)
        # affine is PER GROUP, matching the reference's (num_groups,)
        # gamma/beta (REF:src/operator/nn/group_norm.cc)
        yf = yf * g.reshape((1, -1, 1)).astype(jnp.float32) + \
            b.reshape((1, -1, 1)).astype(jnp.float32)
        return yf.reshape(x.shape).astype(x.dtype)

    return _apply(f, [data, gamma, beta], "GroupNorm")


def L2Normalization(data, eps=1e-10, mode="instance", **kw):
    def f(x):
        if mode == "channel":
            ax = (1,)
        elif mode == "spatial":
            ax = tuple(range(2, x.ndim))
        else:
            ax = tuple(range(1, x.ndim))
        # norm-op precision policy (docs r5): the sum-of-squares
        # accumulates in f32 (XLA fuses the convert into the reduce
        # read), result back in x.dtype — a bf16 accumulation over 512
        # channels costs ~1% on the denominator
        xf = x.astype(jnp.float32)
        nrm = jnp.sqrt(jnp.sum(jnp.square(xf), axis=ax, keepdims=True)
                       + eps)
        return (xf / nrm).astype(x.dtype)

    return _apply(f, [data], "L2Normalization")


def batch_norm_core(x, gamma, beta, moving_mean, moving_var, eps, use_batch_stats,
                    axis=1, fix_gamma=False):
    """Pure BN forward; returns (out, batch_mean, batch_var).  Gluon's
    BatchNorm layer owns the running-stat update (the reference did it via
    FMutateInputs on aux states — here state flows functionally, SURVEY §7.1).
    One-pass sum/sum-of-squares statistics (no mean->var reduce dependency,
    so XLA sibling-fuses both into a single read of x) and a folded
    per-channel scale/bias applied in x.dtype — the r5 HBM byte diet;
    same formulation as gluon.nn.BatchNorm."""
    axis = axis % x.ndim
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    if use_batch_stats:
        red = tuple(i for i in range(x.ndim) if i != axis)
        mu, var = _onepass_stats(x.astype(jnp.float32), red,
                                 keepdims=False)
    else:
        mu = moving_mean.astype(jnp.float32)
        var = moving_var.astype(jnp.float32)
    scale = lax.rsqrt(var + eps) * g.astype(jnp.float32)
    bias = beta.astype(jnp.float32) - mu * scale
    y = x * scale.reshape(shape).astype(x.dtype) + \
        bias.reshape(shape).astype(x.dtype)
    return y, mu, var


def BatchNorm(data, gamma, beta, moving_mean, moving_var, eps=1e-5, momentum=0.9,
              fix_gamma=True, use_global_stats=False, axis=1, **kw):
    """Op-level BatchNorm (inference-style unless recording; Gluon layer drives
    the training path with running-stat updates)."""
    training = autograd.is_training() and not use_global_stats

    def f(x, g, b, mm, mv):
        y, _, _ = batch_norm_core(x, g, b, mm, mv, eps, training, axis, fix_gamma)
        return y

    return _apply(f, [data, gamma, beta, moving_mean, moving_var], "BatchNorm")


def Dropout(data, p=0.5, mode="training", axes=None, **kw):
    """REF:src/operator/nn/dropout.cc — inverted dropout; key from the RNG
    stream (traced key inside hybridize, eager split otherwise)."""
    if not (autograd.is_training() or mode == "always") or p <= 0:
        return identity(data)
    from .. import random as _random
    key = _random.take_key()

    def f(x):
        shape = x.shape
        if axes:
            shape = tuple(1 if i in axes else s for i, s in enumerate(x.shape))
        keep = jax.random.bernoulli(key, 1.0 - p, shape)
        return jnp.where(keep, x / (1.0 - p), jnp.zeros((), x.dtype)).astype(x.dtype)

    return _apply(f, [data], "Dropout")


# ----------------------------------------------------------------------------
# optimizer update ops (REF:src/operator/optimizer_op.cc fused updates).
# Pure cores used by both the imperative optimizer and jitted train steps.
# ----------------------------------------------------------------------------
def sgd_update_core(weight, grad, lr, wd, rescale_grad=1.0, clip_gradient=None):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return weight - lr * (g + wd * weight)


def sgd_mom_update_core(weight, grad, mom, lr, momentum, wd, rescale_grad=1.0,
                        clip_gradient=None):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mom = momentum * mom - lr * (g + wd * weight)
    return weight + new_mom, new_mom


def adam_update_core(weight, grad, mean, var, lr, beta1, beta2, epsilon, wd, t,
                     rescale_grad=1.0, clip_gradient=None, lazy_update=False):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    mhat = m / (1 - beta1 ** t)
    vhat = v / (1 - beta2 ** t)
    return weight - lr * mhat / (jnp.sqrt(vhat) + epsilon), m, v


def sgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0, clip_gradient=-1,
               out=None, **kw):
    cg = clip_gradient if clip_gradient and clip_gradient > 0 else None
    # fusible elementwise update (an engine.bulk() around a parameter loop
    # bulks the whole sweep); all hyper-params ride the key — a schedule
    # changing lr compiles a fresh chain, same as the reference re-bulking
    fuse = ("sgd_update",) + _scalar_key(lr, wd, rescale_grad, cg) \
        if all(isinstance(v, (int, float, type(None)))
               for v in (lr, wd, rescale_grad, cg)) else None
    res = _apply(lambda w, g: sgd_update_core(w, g, lr, wd, rescale_grad, cg),
                 [weight, grad], "sgd_update", nondiff=True, fuse=fuse)
    if out is not None:
        out._rebind(res._data)
        return out
    return res


# ----------------------------------------------------------------------------
# random samplers (REF:src/operator/random/**) — see tpu_mx.random for state
# ----------------------------------------------------------------------------
def _rand(shape, sampler, dtype, ctx):
    from .. import random as _random
    key = _random.take_key()
    data = sampler(key, tuple(shape) if shape else ())
    return _place(data.astype(dtype), ctx)


def random_uniform(low=0.0, high=1.0, shape=(1,), dtype="float32", ctx=None, **kw):
    return _rand(shape, lambda k, s: jax.random.uniform(k, s, minval=low, maxval=high),
                 dtype, ctx)


def random_normal(loc=0.0, scale=1.0, shape=(1,), dtype="float32", ctx=None, **kw):
    return _rand(shape, lambda k, s: loc + scale * jax.random.normal(k, s), dtype, ctx)


def random_randint(low, high, shape=(1,), dtype="int32", ctx=None, **kw):
    return _rand(shape, lambda k, s: jax.random.randint(k, s, low, high), dtype, ctx)


def random_gamma(alpha=1.0, beta=1.0, shape=(1,), dtype="float32", ctx=None, **kw):
    return _rand(shape, lambda k, s: jax.random.gamma(k, alpha, s) * beta, dtype, ctx)


def random_exponential(scale=1.0, shape=(1,), dtype="float32", ctx=None, **kw):
    return _rand(shape, lambda k, s: jax.random.exponential(k, s) * scale, dtype, ctx)


def random_poisson(lam=1.0, shape=(1,), dtype="float32", ctx=None, **kw):
    return _rand(shape, lambda k, s: jax.random.poisson(k, lam, s), dtype, ctx)


def random_bernoulli(prob=0.5, shape=(1,), dtype="float32", ctx=None, **kw):
    return _rand(shape, lambda k, s: jax.random.bernoulli(k, prob, s), dtype, ctx)


def sample_multinomial(data, shape=1, get_prob=False, dtype="int32", **kw):
    from .. import random as _random
    key = _random.take_key()
    n = shape if isinstance(shape, int) else int(_np.prod(shape))

    def f(p):
        logits = jnp.log(jnp.maximum(p, 1e-30))
        return jax.random.categorical(key, logits, axis=-1,
                                      shape=(n,) + p.shape[:-1]).astype(jnp.dtype(dtype))

    res = _apply(lambda p: jnp.moveaxis(f(p), 0, -1).squeeze(-1) if n == 1
                 else jnp.moveaxis(f(p), 0, -1), [data], "sample_multinomial",
                 nondiff=True)
    return res


def shuffle(data, **kw):
    from .. import random as _random
    key = _random.take_key()
    return _apply(lambda x: jax.random.permutation(key, x, axis=0), [data], "shuffle",
                  nondiff=True)


# ---------------------------------------------------------------------------
# legacy output heads (REF:src/operator/softmax_output.cc,
# REF:src/operator/regression_output-inl.h, REF:src/operator/make_loss.cc).
# These are loss layers: forward is the prediction, backward *injects* the
# loss gradient regardless of the incoming head gradient — realized here with
# `jax.custom_vjp` so the same semantics hold under the symbolic executor.
# ---------------------------------------------------------------------------

def _output_head(fwd_fn, grad_fn, name):
    @jax.custom_vjp
    def head(x, y):
        return fwd_fn(x, y)

    def head_fwd(x, y):
        out = fwd_fn(x, y)
        return out, (out, x, y)

    def head_bwd(res, g):
        out, x, y = res
        del g  # loss layer: incoming head grad ignored (reference semantics)
        ylike = jnp.zeros_like(y) if isinstance(y, jnp.ndarray) else 0.0
        return grad_fn(out, x, y), ylike

    head.defvjp(head_fwd, head_bwd)
    head.__name__ = name
    return head


def SoftmaxOutput(data, label, grad_scale=1.0, ignore_label=-1.0,
                  multi_output=False, use_ignore=False, preserve_shape=False,
                  normalization="null", out_grad=False, smooth_alpha=0.0, **kw):
    """Softmax forward + injected cross-entropy gradient
    (REF:src/operator/softmax_output.cc)."""
    axis = 1 if multi_output else -1

    def fwd(x, y):
        return jax.nn.softmax(x, axis=axis)

    def grad(p, x, y):
        n_class = x.shape[axis]
        yi = y.astype(jnp.int32)
        oh = jax.nn.one_hot(yi, n_class, axis=axis, dtype=x.dtype)
        if smooth_alpha:
            oh = oh * (1.0 - smooth_alpha) + smooth_alpha / n_class
        g = p - oh
        if use_ignore:
            valid = (y != ignore_label).astype(x.dtype)
            g = g * jnp.expand_dims(valid, axis if axis != -1 else x.ndim - 1)
        if normalization == "batch":
            g = g / x.shape[0]
        elif normalization == "valid":
            if use_ignore:
                cnt = jnp.maximum(jnp.sum(y != ignore_label), 1).astype(x.dtype)
            else:
                cnt = jnp.asarray(float(_np.prod(y.shape)), x.dtype)
            g = g / cnt
        return g * grad_scale

    return _apply(_output_head(fwd, grad, "SoftmaxOutput"), [data, label],
                  "SoftmaxOutput")


def SVMOutput(data, label, margin=1.0, regularization_coefficient=1.0,
              use_linear=False, **kw):
    """Hinge-loss output layer (REF:src/operator/svm_output.cc): forward
    is identity (scores pass through), backward injects the L2-SVM (or
    L1 with use_linear) subgradient — for j≠y: λ·h (L1) or 2λ·h (L2)
    with h = max(0, margin + x_j − x_y); for j=y the negative sum."""

    def fwd(x, y):
        return x

    def grad(out, x, y):
        yi = y.astype(jnp.int32)
        n_class = x.shape[-1]
        xy = jnp.take_along_axis(x, yi[..., None], axis=-1)     # (..., 1)
        h = jnp.maximum(0.0, margin + x - xy)                   # (..., C)
        lam = regularization_coefficient
        g = jnp.where(h > 0, lam, 0.0) if use_linear else 2.0 * lam * h
        oh = jax.nn.one_hot(yi, n_class, dtype=x.dtype)
        g = g * (1 - oh)                       # j≠y terms
        g = g - oh * jnp.sum(g, axis=-1, keepdims=True)  # j=y pulls down
        return g.astype(x.dtype)

    return _apply(_output_head(fwd, grad, "SVMOutput"), [data, label],
                  "SVMOutput")


def _regression_head(link, residual, name):
    def make(data, label, grad_scale=1.0, **kw):
        def fwd(x, y):
            return link(x)

        def grad(out, x, y):
            yb = y.reshape(out.shape)
            return residual(out, yb) * (grad_scale / out.shape[0])

        return _apply(_output_head(fwd, grad, name), [data, label], name)

    make.__name__ = name
    return make


LinearRegressionOutput = _regression_head(
    lambda x: x, lambda o, y: o - y, "LinearRegressionOutput")
MAERegressionOutput = _regression_head(
    lambda x: x, lambda o, y: jnp.sign(o - y), "MAERegressionOutput")
LogisticRegressionOutput = _regression_head(
    jax.nn.sigmoid, lambda o, y: o - y, "LogisticRegressionOutput")


def MakeLoss(data, grad_scale=1.0, valid_thresh=0.0, normalization="null", **kw):
    """REF:src/operator/make_loss.cc — treat `data` as a loss value; backward
    injects `grad_scale` (normalized) into it."""

    def fwd(x, y):
        return x

    def grad(out, x, y):
        g = jnp.full_like(x, grad_scale)
        if normalization == "batch":
            g = g / x.shape[0]
        elif normalization == "valid":
            cnt = jnp.maximum(jnp.sum(x > valid_thresh), 1).astype(x.dtype)
            g = g / cnt
        return g

    return _apply(_output_head(fwd, grad, "MakeLoss"), [data, 0.0], "MakeLoss")


# namespace-style aliases matching mx.nd.random.* / mx.random.*
class _RandomNS:
    uniform = staticmethod(random_uniform)
    normal = staticmethod(random_normal)
    randint = staticmethod(random_randint)
    gamma = staticmethod(random_gamma)
    exponential = staticmethod(random_exponential)
    poisson = staticmethod(random_poisson)
    bernoulli = staticmethod(random_bernoulli)
    multinomial = staticmethod(sample_multinomial)
    shuffle = staticmethod(shuffle)


random = _RandomNS()
uniform = random_uniform
normal = random_normal
randn = lambda *shape, **kw: random_normal(shape=shape, **kw)


def Custom(*args, op_type=None, **op_params):
    """User-registered custom op (REF:src/operator/custom/custom.cc);
    register with @mx.operator.register(name), invoke as
    nd.Custom(x, ..., op_type=name, **params)."""
    from .. import operator as _op_mod
    if op_type is None:
        raise ValueError("Custom requires op_type=")
    return _op_mod._invoke_custom(args, op_type, **op_params)


# ----------------------------------------------------------------------------
# extended operator families (separate modules, one public namespace — the
# reference's registry likewise flattens src/operator/** into mx.nd.*)
# ----------------------------------------------------------------------------
from .linalg_ops import *      # noqa: F401,F403,E402
from .vision_ops import *      # noqa: F401,F403,E402
from .ctc import *             # noqa: F401,F403,E402
from .rnn_op import *          # noqa: F401,F403,E402
from .quantized_ops import *   # noqa: F401,F403,E402
from .sample_ops import *      # noqa: F401,F403,E402


# ----------------------------------------------------------------------------
# long-tail parity ops (REF:src/operator/tensor/*, src/operator/*.cc)
# ----------------------------------------------------------------------------
def linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0,
                beta=1.0, **kw):
    """C' = alpha·op(A)·op(B) + beta·C (REF:src/operator/tensor/la_op.cc)."""

    def f(a, b, c):
        if transpose_a:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_b:
            b = jnp.swapaxes(b, -1, -2)
        return alpha * jnp.matmul(a, b) + beta * c

    return _apply(f, [A, B, C], "linalg_gemm")


def batch_take(a, indices, **kw):
    """out[i] = a[i, indices[i]] (REF:src/operator/tensor/indexing_op.cc)."""
    return _apply(
        lambda x, idx: jnp.take_along_axis(
            x, idx.astype(jnp.int32)[:, None], axis=1)[:, 0],
        [a, indices], "batch_take")


def diag(data, k=0, axis1=0, axis2=1, **kw):
    """1-D in: build a k-diagonal matrix; N-D in: extract the k-diagonal
    over (axis1, axis2) — reference defaults (0, 1), NOT numpy's last-two
    (REF:src/operator/tensor/diag_op.cc)."""

    def f(x):
        if x.ndim == 1:
            return jnp.diag(x, k)
        return jnp.diagonal(x, offset=k, axis1=axis1, axis2=axis2)

    return _apply(f, [data], "diag")


def smooth_l1(data, scalar=1.0, **kw):
    """Huber-style loss elementwise (REF:src/operator/tensor/
    elemwise_unary_op_basic.cc smooth_l1): 0.5(σx)²/σ² if |x|<1/σ² else
    |x|-0.5/σ²."""
    s2 = float(scalar) ** 2

    def f(x):
        ax = jnp.abs(x)
        return jnp.where(ax < 1.0 / s2, 0.5 * s2 * x * x, ax - 0.5 / s2)

    return _apply(f, [data], "smooth_l1")


def make_loss(data, **kw):
    """Mark a symbol/array as a loss output (REF:src/operator/
    make_loss.cc) — identity forward; gradient of ones flows from it."""
    return _apply(lambda x: x, [data], "make_loss")


def unravel_index(data, shape=None, **kw):
    """Flat indices -> coordinate rows (REF:src/operator/tensor/
    ravel.cc): out is (ndim, N) like the reference."""
    dims = tuple(int(s) for s in shape)

    def f(x):
        return jnp.stack(jnp.unravel_index(x.astype(jnp.int32), dims))

    return _apply(f, [data], "unravel_index")


def ravel_multi_index(data, shape=None, **kw):
    """Coordinate rows (ndim, N) -> flat indices (REF:src/operator/tensor/
    ravel.cc)."""
    dims = tuple(int(s) for s in shape)

    def f(x):
        coords = tuple(x[i].astype(jnp.int32) for i in range(len(dims)))
        return jnp.ravel_multi_index(coords, dims, mode="clip")

    return _apply(f, [data], "ravel_multi_index")


def hard_sigmoid(data, alpha=0.2, beta=0.5, **kw):
    """clip(alpha·x + beta, 0, 1) (REF:src/operator/tensor/
    elemwise_unary_op_basic.cc)."""
    return _apply(lambda x: jnp.clip(alpha * x + beta, 0.0, 1.0), [data],
                  "hard_sigmoid")


def softrelu(data, **kw):
    """log(1+exp(x)) — softplus (Activation('softrelu') as a free op)."""
    return _apply(lambda x: jax.nn.softplus(x), [data], "softrelu")


def Crop(data, *like, offset=(0, 0), h_w=(0, 0), center_crop=False, **kw):
    """Spatial crop (REF:src/operator/crop.cc, NCHW): to `h_w`, or to the
    second input's spatial size; offset or center anchoring."""

    if not like and (int(h_w[0]) <= 0 or int(h_w[1]) <= 0):
        raise ValueError("Crop: pass a crop_like second input or a "
                         "positive h_w target size")

    def f(x, *rest):
        th, tw = (rest[0].shape[2:4] if rest else
                  (int(h_w[0]), int(h_w[1])))
        H, W = x.shape[2], x.shape[3]
        if center_crop:
            oy, ox = (H - th) // 2, (W - tw) // 2
        else:
            oy, ox = int(offset[0]), int(offset[1])
        return x[:, :, oy:oy + th, ox:ox + tw]

    return _apply(f, [data] + list(like), "Crop")


Reshape = reshape
astype = cast


# ----------------------------------------------------------------------------
# round-3 long tail (REF:src/operator/{tensor,nn,contrib}/** families)
# ----------------------------------------------------------------------------
log_sigmoid = _unary(jax.nn.log_sigmoid, "log_sigmoid")
mish = _unary(lambda x: x * jnp.tanh(jax.nn.softplus(x)), "mish")
hard_swish = _unary(lambda x: x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0,
                    "hard_swish")
digamma = _unary(jax.scipy.special.digamma, "digamma")
# erfcinv via ndtri, NOT erfinv(1-x): the subtraction cancels
# catastrophically in f32 for small x (erfcinv(1e-8) would return inf)
erfcinv = _unary(
    lambda x: -jax.scipy.special.ndtri(x.astype(jnp.float32) / 2.0)
    / jnp.sqrt(2.0).astype(jnp.float32), "erfcinv")


def polygamma(n, data, **kw):
    """REF:src/operator/tensor/elemwise_unary_op: polygamma(n, x)."""
    return _apply(lambda x: jax.scipy.special.polygamma(int(n), x), [data],
                  "polygamma")


def gammainc(a, x, **kw):
    """Regularized lower incomplete gamma (REF unary family)."""
    return _apply(jax.scipy.special.gammainc, [a, x], "gammainc")


def nextafter(lhs, rhs, **kw):
    return _apply(jnp.nextafter, [lhs, rhs], "nextafter", nondiff=True)


def moments(data, axes=None, keepdims=False, **kw):
    """(mean, variance) in one pass (REF:src/operator/nn/moments.cc)."""
    def f(x):
        ax = tuple(axes) if axes is not None else tuple(range(x.ndim))
        mu = jnp.mean(x, axis=ax, keepdims=keepdims)
        mu_b = mu if keepdims else jnp.expand_dims(
            mu, ax) if ax else mu
        var = jnp.mean(jnp.square(x - mu_b), axis=ax, keepdims=keepdims)
        return mu, var

    return _apply(f, [data], "moments")


def khatri_rao(*matrices, **kw):
    """Column-wise Kronecker product (REF:src/operator/contrib/krprod.cc):
    inputs (r, c_i) … -> (r? no: prod over rows) — reference semantics:
    for matrices with the SAME number of columns k, output has
    prod(rows_i) rows and k columns."""
    def f(*ms):
        out = ms[0]
        for m in ms[1:]:
            out = jnp.einsum("ik,jk->ijk", out, m).reshape(
                -1, out.shape[-1])
        return out

    return _apply(f, list(matrices), "khatri_rao")


def multi_all_finite(*arrays, num_arrays=None, init_output=True, **kw):
    """1 iff every element of every input is finite
    (REF:src/operator/contrib/all_finite.cc — the AMP overflow probe)."""
    def f(*xs):
        ok = jnp.ones((1,), jnp.float32)
        for x in xs:
            ok = ok * jnp.isfinite(x.astype(jnp.float32)).all().astype(
                jnp.float32)
        return ok

    return _apply(f, list(arrays), "multi_all_finite", nondiff=True)


all_finite = multi_all_finite


def masked_softmax(data, mask, axis=-1, temperature=1.0, **kw):
    """softmax over positions where mask!=0; fully-masked rows -> 0
    (REF:src/operator/nn/softmax.cc masked_softmax [ver>=1.8-era])."""
    def f(x, m):
        neg = jnp.finfo(jnp.float32).min
        z = jnp.where(m != 0, x.astype(jnp.float32) / temperature, neg)
        p = jax.nn.softmax(z, axis=axis)
        return jnp.where(m != 0, p, 0.0).astype(x.dtype)

    return _apply(f, [data, mask], "masked_softmax")


def masked_log_softmax(data, mask, axis=-1, temperature=1.0, **kw):
    def f(x, m):
        neg = jnp.finfo(jnp.float32).min
        z = jnp.where(m != 0, x.astype(jnp.float32) / temperature, neg)
        p = jax.nn.log_softmax(z, axis=axis)
        return jnp.where(m != 0, p, -jnp.inf).astype(x.dtype)

    return _apply(f, [data, mask], "masked_log_softmax")


def _im2col_params(kernel, stride, dilate, pad):
    kh, kw_ = (kernel, kernel) if isinstance(kernel, int) else tuple(kernel)
    sh, sw = _pair(stride, 2) if stride else (1, 1)
    dh, dw = _pair(dilate, 2) if dilate else (1, 1)
    ph, pw = _pair(pad, 2) if pad else (0, 0)
    return kh, kw_, sh, sw, dh, dw, ph, pw


def _patches(x, kh, kw_, sh, sw, dh, dw, ph, pw):
    """The ONE patch-extraction both im2col and col2im's vjp use —
    col2im is exact only while they share this code."""
    p = lax.conv_general_dilated_patches(
        x, (kh, kw_), (sh, sw), [(ph, ph), (pw, pw)],
        rhs_dilation=(dh, dw),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return p.reshape(x.shape[0], x.shape[1] * kh * kw_, -1)


def im2col(data, kernel, stride=None, dilate=None, pad=None, **kw):
    """Sliding-window patches as columns (REF:src/operator/nn/im2col.h):
    (N, C, H, W) -> (N, C*kh*kw, L) with L output positions."""
    prm = _im2col_params(kernel, stride, dilate, pad)
    return _apply(lambda x: _patches(x, *prm), [data], "im2col")


def col2im(data, output_size, kernel, stride=None, dilate=None, pad=None,
           **kw):
    """Inverse of im2col: scatter-add columns back to the image
    (REF:src/operator/nn/im2col.h col2im) — implemented as the exact vjp
    of the im2col patch extraction, which IS the scatter-add."""
    prm = _im2col_params(kernel, stride, dilate, pad)
    kh, kw_ = prm[0], prm[1]
    oh, ow = tuple(output_size)

    def f(cols):
        n = cols.shape[0]
        c = cols.shape[1] // (kh * kw_)
        zeros = jnp.zeros((n, c, oh, ow), cols.dtype)
        _, vjp = jax.vjp(lambda img: _patches(img, *prm), zeros)
        return vjp(cols)[0]

    return _apply(f, [data], "col2im")


def fill_element_0index(lhs, mhs, rhs, **kw):
    """lhs[i, rhs[i]] = mhs[i] (REF:src/operator/tensor/
    fill_element_0index — the bucketing trick for masking outputs)."""
    def f(l, m, r):
        idx = r.astype(jnp.int32)
        return l.at[jnp.arange(l.shape[0]), idx].set(m)

    return _apply(f, [lhs, mhs, rhs], "fill_element_0index")


def choose_element_0index(lhs, rhs, **kw):
    """out[i] = lhs[i, rhs[i]] (REF tensor family; pick's ancestor)."""
    def f(l, r):
        return l[jnp.arange(l.shape[0]), r.astype(jnp.int32)]

    return _apply(f, [lhs, rhs], "choose_element_0index")


def LRN(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5, **kw):
    """Local response normalization across channels
    (REF:src/operator/nn/lrn.cc — AlexNet-era)."""
    def f(x):
        sq = jnp.square(x.astype(jnp.float32))
        half = nsize // 2
        # windowed channel sum via padding + cumulative slicing
        padded = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
        # NB: module-level `sum` is the reduction OP; use the builtin
        acc = _sum(padded[:, i:i + x.shape[1]] for i in range(nsize))
        norm = (knorm + alpha * acc / nsize) ** beta
        return (x.astype(jnp.float32) / norm).astype(x.dtype)

    return _apply(f, [data], "LRN")


broadcast_axes = broadcast_axis


# ----------------------------------------------------------------------------
# deprecated 0.x-era aliases (REF:src/operator/{batch_norm_v1,convolution_v1,
# pooling_v1}.cc — upstream kept them registered for old symbol JSON; here
# they forward to the current ops with a DeprecationWarning)
# ----------------------------------------------------------------------------
def _deprecated_v1(new_fn, old_name, ref_file):
    import warnings as _warnings

    @functools.wraps(new_fn)  # real signature: the symbol autogen stubs
    def op(*args, **kw):      # classify by inspect.signature, and a bare
        # (*args, **kw) would take the variadic path and skip the
        # auto-created weight/bias/gamma Variables
        _warnings.warn(
            f"{old_name} is the deprecated 0.x alias of "
            f"{new_fn.__name__}; it forwards with identical semantics",
            DeprecationWarning, stacklevel=2)
        return new_fn(*args, **kw)

    op.__name__ = old_name
    op.__qualname__ = old_name
    op.__doc__ = (f"Deprecated alias of :func:`{new_fn.__name__}` "
                  f"(REF:src/operator/{ref_file} kept old symbol JSON "
                  "loadable).")
    return op


BatchNorm_v1 = _deprecated_v1(BatchNorm, "BatchNorm_v1",
                              "batch_norm_v1.cc")
# upstream: NNVM_REGISTER_OP(SoftmaxOutput).add_alias("Softmax") — the 0.x
# name is the SAME OP (softmax fwd + injected CE grad), not nd.softmax
Softmax = _deprecated_v1(SoftmaxOutput, "Softmax", "softmax_output.cc")
Convolution_v1 = _deprecated_v1(Convolution, "Convolution_v1",
                                "convolution_v1.cc")
Pooling_v1 = _deprecated_v1(Pooling, "Pooling_v1", "pooling_v1.cc")


def IdentityAttachKLSparseReg(data, sparseness_target=0.1, penalty=0.001,
                              momentum=0.9, moving_avg=None, **kw):
    """Identity forward + KL sparsity-regularization gradient
    (REF:src/operator/identity_attach_KL_sparse_reg.cc — the sparse-
    autoencoder penalty).  The forward passes `data` through unchanged;
    the backward ADDS penalty·KL'(ρ‖ρ̂) per hidden unit, where ρ is
    `sparseness_target` and ρ̂ the (moving-average) mean activation of
    that unit over the batch: d/da = penalty·(−ρ/ρ̂ + (1−ρ)/(1−ρ̂)).

    `moving_avg` (units,) carries ρ̂ across calls with `momentum` and is
    REBOUND in place (the op's aux state upstream — the FMutateInputs
    idiom used by the raw optimizer kernels here); omit it to use the
    current batch mean alone.  Activations are expected in (0, 1)
    (post-sigmoid), as upstream assumes; ρ̂ is clamped away from {0, 1}."""
    rho = float(sparseness_target)
    pen = float(penalty)
    mom = float(momentum)
    use_ma = moving_avg is not None
    if use_ma and _functional.active():
        from ..base import MXNetError
        raise MXNetError(
            "IdentityAttachKLSparseReg: the moving_avg aux cannot be "
            "updated inside a hybridize/compiled trace (the rebind would "
            "silently freeze at the trace-time value); use the batch-mean "
            "mode (moving_avg=None) under hybridize, or train this block "
            "eagerly")
    from .. import autograd as _ag
    # aux semantics match upstream: ρ̂ updates only on TRAINING forwards
    # (inference passes must not corrupt the training statistics), and
    # the blend is computed exactly once
    rho_hat_const = None
    if use_ma:
        x_now = _raw(data)
        batch_mean = x_now.reshape(x_now.shape[0], -1).mean(axis=0)
        ma_val = _raw(moving_avg)
        new_ma = mom * ma_val.reshape(-1) + (1 - mom) * batch_mean
        rho_hat_const = jnp.clip(new_ma, 1e-6, 1.0 - 1e-6)
        if _ag.is_recording():
            moving_avg._rebind(
                new_ma.reshape(ma_val.shape).astype(moving_avg.dtype))

    @jax.custom_vjp
    def head(x):
        return x

    def head_fwd(x):
        if rho_hat_const is not None:
            rho_hat = rho_hat_const
        else:
            rho_hat = jnp.clip(x.reshape(x.shape[0], -1).mean(axis=0),
                               1e-6, 1.0 - 1e-6)
        return x, (x.shape, rho_hat)

    def head_bwd(res, g):
        shape, rho_hat = res
        kl_grad = pen * (-rho / rho_hat + (1 - rho) / (1 - rho_hat))
        return (g + kl_grad.reshape((1,) + shape[1:]),)

    head.defvjp(head_fwd, head_bwd)
    return _apply(head, [data], "IdentityAttachKLSparseReg")
