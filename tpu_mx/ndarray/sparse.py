"""Sparse NDArray: `row_sparse` and `csr` storage types
(REF:python/mxnet/ndarray/sparse.py, REF:include/mxnet/ndarray.h storage
types, REF:src/operator/tensor/dot.cc sparse kernels).

TPU divergence note (SURVEY §7.3 hard-part 4): TPUs have no sparse memory
format — XLA computes on dense tiles.  Storage here is genuinely compact
(index + value arrays on device), and the compute kernels are expressed as
gather + segment-sum, which XLA lowers to TPU-efficient embedding-style
ops.  `row_sparse` exists chiefly as the gradient type of Embedding-like
lookups (the reference's main use), `csr` for sample-feature matrices
(LibSVM-style input).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .ndarray import NDArray

__all__ = ["BaseSparseNDArray", "CSRNDArray", "RowSparseNDArray",
           "csr_matrix", "row_sparse_array", "zeros", "dot", "retain",
           "cast_storage", "elemwise_add", "tostype"]


class BaseSparseNDArray:
    """Common surface of the compressed formats.  Deliberately NOT an
    NDArray subclass: dense ops must not silently consume compressed
    handles (the reference raises the same way via storage-type dispatch)."""

    @property
    def stype(self):
        raise NotImplementedError

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return str(self._values.dtype)

    @property
    def context(self):
        return NDArray(self._values).context

    ctx = context

    def asnumpy(self):
        return np.asarray(self.todense()._data)

    def astype(self, dtype):
        out = self.copy()
        out._values = out._values.astype(dtype)
        return out

    def wait_to_read(self):
        self._values.block_until_ready()
        return self

    def __repr__(self):
        return "\n<%s %s @%s>" % (type(self).__name__, self._shape,
                                  self.context)


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (2D).  data/indices/indptr layout is
    bit-compatible with the reference's csr storage."""

    def __init__(self, data, indices, indptr, shape):
        self._values = jnp.asarray(data)
        self._indices = jnp.asarray(indices, dtype=jnp.int32)
        self._indptr = jnp.asarray(indptr, dtype=jnp.int32)
        self._shape = tuple(shape)
        if len(self._shape) != 2:
            raise ValueError("csr storage is 2-D only")

    @property
    def stype(self):
        return "csr"

    @property
    def data(self):
        return NDArray(self._values)

    @property
    def indices(self):
        return NDArray(self._indices)

    @property
    def indptr(self):
        return NDArray(self._indptr)

    @property
    def nnz(self):
        return int(self._values.shape[0])

    def copy(self):
        return CSRNDArray(self._values, self._indices, self._indptr,
                          self._shape)

    def _row_ids(self):
        """nnz-length row id per stored element, from indptr: TPU-friendly
        (one searchsorted, no host loop)."""
        nnz = self._values.shape[0]
        return jnp.searchsorted(self._indptr[1:], jnp.arange(nnz),
                                side="right").astype(jnp.int32)

    def todense(self):
        rows = self._row_ids()
        dense = jnp.zeros(self._shape, self._values.dtype)
        return NDArray(dense.at[rows, self._indices].add(self._values))

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype == "default":
            return self.todense()
        raise ValueError(f"cannot convert csr to {stype}")

    def slice(self, start, stop):
        """Row slice (the reference supports csr row slicing)."""
        start, stop = int(start), int(stop)
        if not (0 <= start <= stop <= self._shape[0]):
            raise IndexError(
                f"csr slice [{start}:{stop}] out of bounds for "
                f"{self._shape[0]} rows")
        ptr = self._indptr[start:stop + 1]
        lo, hi = int(ptr[0]), int(ptr[-1])
        return CSRNDArray(self._values[lo:hi], self._indices[lo:hi],
                          ptr - ptr[0], (stop - start, self._shape[1]))


class RowSparseNDArray(BaseSparseNDArray):
    """First-dim sparse tensor: (indices, values) where values[i] is the
    full row `indices[i]`.  The gradient type of embedding lookups."""

    def __init__(self, data, indices, shape):
        self._values = jnp.asarray(data)
        self._indices = jnp.asarray(indices, dtype=jnp.int32)
        self._shape = tuple(shape)
        if self._values.shape[0] != self._indices.shape[0]:
            raise ValueError("row_sparse: len(data) != len(indices)")
        if self._values.shape[1:] != self._shape[1:]:
            raise ValueError("row_sparse: row shape mismatch")

    @property
    def stype(self):
        return "row_sparse"

    @property
    def data(self):
        return NDArray(self._values)

    @property
    def indices(self):
        return NDArray(self._indices)

    def copy(self):
        return RowSparseNDArray(self._values, self._indices, self._shape)

    def todense(self):
        dense = jnp.zeros(self._shape, self._values.dtype)
        # .add (not .set): duplicate indices accumulate, matching the
        # reference's reduce-on-conversion semantics for unmerged grads
        return NDArray(dense.at[self._indices].add(self._values))

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype == "default":
            return self.todense()
        raise ValueError(f"cannot convert row_sparse to {stype}")


# ----------------------------------------------------------------------------
# constructors (REF sparse.py csr_matrix / row_sparse_array)
# ----------------------------------------------------------------------------
def _unwrap(x):
    if isinstance(x, NDArray):
        return x._data
    return jnp.asarray(x)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """``csr_matrix((data, indices, indptr), shape)`` or from a dense
    array/NDArray."""
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = (_unwrap(a) for a in arg1)
        if dtype is not None:
            data = data.astype(dtype)
        if shape is None:
            raise ValueError("shape is required for the 3-tuple form")
        return CSRNDArray(data, indices, indptr, shape)
    dense = np.asarray(_unwrap(arg1))
    if dtype is not None:
        dense = dense.astype(dtype)
    if dense.ndim != 2:
        raise ValueError("csr_matrix: dense input must be 2-D")
    mask = dense != 0
    indptr = np.concatenate([[0], np.cumsum(mask.sum(axis=1))]).astype(np.int32)
    indices = np.nonzero(mask)[1].astype(np.int32)
    data = dense[mask]
    return CSRNDArray(data, indices, indptr, dense.shape)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """``row_sparse_array((data, indices), shape)`` or from dense."""
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = (_unwrap(a) for a in arg1)
        if dtype is not None:
            data = data.astype(dtype)
        if shape is None:
            raise ValueError("shape is required for the 2-tuple form")
        return RowSparseNDArray(data, indices, shape)
    dense = np.asarray(_unwrap(arg1))
    if dtype is not None:
        dense = dense.astype(dtype)
    nz_rows = np.nonzero(np.any(dense != 0, axis=tuple(range(1, dense.ndim))))[0]
    return RowSparseNDArray(dense[nz_rows], nz_rows.astype(np.int32),
                            dense.shape)


def zeros(stype, shape, ctx=None, dtype="float32"):
    if stype == "csr":
        return CSRNDArray(jnp.zeros((0,), dtype), jnp.zeros((0,), jnp.int32),
                          jnp.zeros((shape[0] + 1,), jnp.int32), shape)
    if stype == "row_sparse":
        return RowSparseNDArray(jnp.zeros((0,) + tuple(shape[1:]), dtype),
                                jnp.zeros((0,), jnp.int32), shape)
    if stype == "default":
        return NDArray(jnp.zeros(shape, dtype))
    raise ValueError(f"unknown storage type {stype!r}")


# ----------------------------------------------------------------------------
# ops
# ----------------------------------------------------------------------------
def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-aware dot.  csr·dense (fwd) and csrᵀ·dense are the two
    kernels the reference optimizes (REF:src/operator/tensor/dot-inl.h);
    both lower to gather + segment_sum on TPU."""
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, NDArray):
        rows = lhs._row_ids()
        vals = lhs._values
        cols = lhs._indices
        rhs_mat = rhs._data.T if transpose_b else rhs._data
        if not transpose_a:
            contrib = vals[:, None] * rhs_mat[cols]              # (nnz, N)
            out = jax.ops.segment_sum(contrib, rows,
                                      num_segments=lhs._shape[0])
            return NDArray(out)
        # csrᵀ · dense: scatter by column id
        contrib = vals[:, None] * rhs_mat[rows]
        out = jax.ops.segment_sum(contrib, cols,
                                  num_segments=lhs._shape[1])
        return NDArray(out)
    if isinstance(lhs, NDArray) and isinstance(rhs, CSRNDArray):
        # dense · csr = (csrᵀ · denseᵀ)ᵀ
        lhs_mat = lhs._data.T if transpose_a else lhs._data
        return NDArray(dot(rhs, NDArray(lhs_mat.T),
                           transpose_a=not transpose_b)._data.T)
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        from . import ops
        return ops.dot(lhs, rhs, transpose_a=transpose_a,
                       transpose_b=transpose_b)
    raise TypeError(f"sparse.dot: unsupported operands "
                    f"({type(lhs).__name__}, {type(rhs).__name__})")


def retain(rsp, indices):
    """Keep only the listed rows of a row_sparse array
    (REF sparse_retain op — used by the sparse optimizer path)."""
    if not isinstance(rsp, RowSparseNDArray):
        raise TypeError("retain expects a RowSparseNDArray")
    want = _unwrap(indices).astype(jnp.int32)
    # membership mask over stored rows (static shapes: O(k·m) compare)
    keep = (rsp._indices[:, None] == want[None, :]).any(axis=1)
    kept_idx = np.nonzero(np.asarray(keep))[0]
    return RowSparseNDArray(rsp._values[kept_idx], rsp._indices[kept_idx],
                            rsp._shape)


def cast_storage(arr, stype):
    """Dense ⇄ sparse conversion (REF cast_storage op)."""
    if isinstance(arr, BaseSparseNDArray):
        return arr.tostype(stype)
    if stype == "default":
        return arr
    if stype == "csr":
        return csr_matrix(arr)
    if stype == "row_sparse":
        return row_sparse_array(arr)
    raise ValueError(f"unknown storage type {stype}")


def elemwise_add(a, b):
    """row_sparse + row_sparse → row_sparse (gradient accumulation).
    The result is canonical: unique sorted indices, duplicates summed —
    the invariant the reference guarantees for row_sparse."""
    if isinstance(a, RowSparseNDArray) and isinstance(b, RowSparseNDArray):
        if a._shape != b._shape:
            raise ValueError("shape mismatch")
        idx = np.concatenate([np.asarray(a._indices), np.asarray(b._indices)])
        vals = jnp.concatenate([a._values, b._values])
        uniq, inv = np.unique(idx, return_inverse=True)
        merged = jax.ops.segment_sum(vals, jnp.asarray(inv),
                                     num_segments=len(uniq))
        return RowSparseNDArray(merged, uniq.astype(np.int32), a._shape)
    return cast_storage(a, "default") + cast_storage(b, "default")


def tostype(arr, stype):
    return cast_storage(arr, stype)
