"""NDArray: the imperative tensor handle over an immutable `jax.Array`.

TPU-native analog of the reference NDArray (REF:include/mxnet/ndarray.h,
REF:src/ndarray/ndarray.cc).  Design (SURVEY §7.1): the reference pairs a
mutable buffer with an async-engine variable; here the buffer is an immutable
`jax.Array` whose dispatch is already async, so the handle provides
*mutation semantics* (``x[:]=v``, ``+=``, slice-assign) by functional rebind
(`.at[].set()`) plus a version counter, and ``wait_to_read`` maps to
``block_until_ready``.  The engine's read/write ordering is inherited from
XLA program order — no thread pool to manage.
"""
from __future__ import annotations

import functools
import struct

import jax
import jax.numpy as jnp
import numpy as np

from .. import autograd
from .. import fusion as _fusion
from ..context import Context, current_context, default_context

__all__ = ["NDArray", "array", "save", "load", "waitall", "concatenate", "from_numpy"]

_FLOAT_DTYPES = (jnp.float16, jnp.bfloat16, jnp.float32, jnp.float64)


def _is_float(dtype):
    return jnp.issubdtype(jnp.dtype(dtype), jnp.floating)


def _ctx_of(data):
    try:
        dev = list(data.devices())[0]
        kind = "cpu" if dev.platform == "cpu" else "tpu"
        # Context ids are indices into this process's local device list, not
        # raw jax device ids (under jax.distributed a worker's only local CPU
        # device can carry a global id like 2048).
        locals_ = [d for d in jax.local_devices() if d.platform == dev.platform]
        try:
            return Context(kind, locals_.index(dev))
        except ValueError:
            return Context(kind, 0)  # non-addressable/global array
    except Exception:
        return default_context()


def _to_ctx_device(data, ctx):
    """Place `data` on ctx's device if it isn't already there."""
    if ctx is None:
        return data
    try:
        dev = ctx.jax_device()
    except RuntimeError:
        return data
    try:
        cur = list(data.devices())
        if len(cur) == 1 and cur[0] == dev:
            return data
    except Exception:
        pass
    return jax.device_put(data, dev)


class NDArray:
    """Mutable tensor handle; wraps an immutable jax.Array + autograd hooks.

    The buffer lives behind the ``_data`` property: ``_buf`` is the
    concrete jax.Array, or None while ``_lazy`` points at a pending
    fusion-segment node (engine bulking, see tpu_mx/fusion.py).  Every
    read path goes through the property, so ANY buffer access is a flush
    barrier that realizes the lazy thunk; shape/dtype queries answer from
    the segment's abstract eval without forcing execution."""

    __slots__ = ("_buf", "_lazy", "_grad", "_grad_req", "_tape_node",
                 "_version", "__weakref__")

    def __init__(self, data, ctx=None):
        if isinstance(data, NDArray):
            data = data._data
        elif not isinstance(data, jax.Array):
            data = jnp.asarray(data)
        self._buf = _to_ctx_device(data, ctx)
        self._lazy = None
        self._grad = None
        self._grad_req = "write"
        self._tape_node = None
        self._version = 0

    @property
    def _data(self):
        if self._lazy is not None:
            _fusion.realize(self)
        return self._buf

    @_data.setter
    def _data(self, value):
        self._buf = value
        self._lazy = None

    # ------------------------------------------------------------------ meta
    @property
    def shape(self):
        if self._lazy is not None:
            return tuple(_fusion.aval_of(self._lazy).shape)
        return tuple(self._buf.shape)

    @property
    def dtype(self):
        if self._lazy is not None:
            return _fusion.aval_of(self._lazy).dtype
        return self._buf.dtype

    @property
    def size(self):
        shape = self.shape
        return int(np.prod(shape)) if shape else 1

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def context(self):
        return _ctx_of(self._data)

    ctx = context

    @property
    def stype(self):
        return "default"

    def tostype(self, stype):
        """Convert to another storage type (csr / row_sparse / default)."""
        if stype == "default":
            return self
        from . import sparse as _sparse
        return _sparse.cast_storage(self, stype)

    @property
    def grad(self):
        return self._grad

    @property
    def handle(self):
        return self._data  # "handle" = the underlying buffer in this stack

    def attach_grad(self, grad_req="write", stype=None):
        """Allocate a gradient buffer so backward() deposits into ``.grad``.

        Divergence (SURVEY §7.3.4): ``stype='row_sparse'`` gradients are
        DENSE here — XLA:TPU has no sparse gradient storage; the request is
        honored numerically (same values, dense layout) and warned about.
        """
        if stype not in (None, "default"):
            import warnings
            warnings.warn(
                f"attach_grad(stype={stype!r}): TPU gradients are always "
                "dense; storing dense values (documented divergence, "
                "SURVEY §7.3.4)", stacklevel=2)
        self._grad = NDArray(jnp.zeros(self.shape, self.dtype))
        self._grad_req = grad_req

    def drop_grad(self):
        self._grad = None
        self._grad_req = "null"

    # -------------------------------------------------------------- transfer
    def asnumpy(self):
        return np.asarray(jax.device_get(self._data))

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def copy(self):
        return NDArray(self._data)

    def copyto(self, other):
        if isinstance(other, NDArray):
            if other.shape != self.shape:
                raise ValueError(f"copyto shape mismatch {self.shape} vs {other.shape}")
            other._rebind(_to_ctx_device(self._data.astype(other.dtype), other.context))
            return other
        if isinstance(other, Context):
            return NDArray(self._data, ctx=other)
        raise TypeError(f"copyto: unsupported target {type(other)}")

    def as_in_context(self, ctx):
        if ctx == self.context:
            return self
        return NDArray(self._data, ctx=ctx)

    as_in_ctx = as_in_context

    def astype(self, dtype, copy=True):
        from . import ops
        return ops.cast(self, dtype=dtype)

    def as_nd_ndarray(self):
        return self

    def tolist(self):
        return self.asnumpy().tolist()

    # --------------------------------------------------------- sync / engine
    def wait_to_read(self):
        """Engine WaitForVar analog: block until this buffer is computed."""
        self._data.block_until_ready()
        return self

    wait_to_write = wait_to_read

    def detach(self):
        out = NDArray(self._data)
        return out

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        """Run autograd back-prop from this array (reference: NDArray.backward)."""
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    # ----------------------------------------------------------- mutation
    def _rebind(self, new_data):
        """In-place semantics: swap the underlying buffer, bump the version.
        (The reference bumps the engine var version on each write.)"""
        self._data = new_data
        self._version += 1
        self._tape_node = None

    def __setitem__(self, key, value):
        v = value._data if isinstance(value, NDArray) else value
        if isinstance(key, slice) and key == slice(None):
            if hasattr(v, "shape") and tuple(getattr(v, "shape", ())) == self.shape:
                self._rebind(jnp.asarray(v).astype(self.dtype))
            else:
                self._rebind(jnp.broadcast_to(jnp.asarray(v, self.dtype), self.shape))
            return
        key = _canonical_index(key)
        self._rebind(self._data.at[key].set(jnp.asarray(v, dtype=self.dtype)))

    def __getitem__(self, key):
        from . import ops
        if isinstance(key, NDArray):
            key = key._data
        return ops._index(self, _canonical_index(key))

    # ----------------------------------------------------------- arithmetic
    def _binop(self, other, name):
        from . import ops
        return getattr(ops, name)(self, other)

    def __add__(self, o): return self._binop(o, "add")
    def __radd__(self, o): return self._binop(o, "add")
    def __sub__(self, o): return self._binop(o, "subtract")
    def __rsub__(self, o):
        from . import ops
        return ops.subtract(o, self)
    def __mul__(self, o): return self._binop(o, "multiply")
    def __rmul__(self, o): return self._binop(o, "multiply")
    def __truediv__(self, o): return self._binop(o, "divide")
    def __rtruediv__(self, o):
        from . import ops
        return ops.divide(o, self)
    def __mod__(self, o): return self._binop(o, "mod")
    def __pow__(self, o): return self._binop(o, "power")
    def __neg__(self):
        from . import ops
        return ops.negative(self)
    def __abs__(self):
        from . import ops
        return ops.abs(self)

    def __iadd__(self, o):
        from . import ops
        res = ops.add(self, o)
        self._rebind(res._data)
        self._tape_node = res._tape_node
        return self

    def __isub__(self, o):
        from . import ops
        res = ops.subtract(self, o)
        self._rebind(res._data)
        self._tape_node = res._tape_node
        return self

    def __imul__(self, o):
        from . import ops
        res = ops.multiply(self, o)
        self._rebind(res._data)
        self._tape_node = res._tape_node
        return self

    def __itruediv__(self, o):
        from . import ops
        res = ops.divide(self, o)
        self._rebind(res._data)
        self._tape_node = res._tape_node
        return self

    # comparisons return 0/1 arrays like the reference
    def __eq__(self, o): return self._binop(o, "equal")
    def __ne__(self, o): return self._binop(o, "not_equal")
    def __gt__(self, o): return self._binop(o, "greater")
    def __ge__(self, o): return self._binop(o, "greater_equal")
    def __lt__(self, o): return self._binop(o, "lesser")
    def __le__(self, o): return self._binop(o, "lesser_equal")
    __hash__ = object.__hash__

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of a 0-d NDArray")
        return self.shape[0]

    def __bool__(self):
        if self.size != 1:
            raise ValueError("ambiguous truth value of multi-element NDArray")
        return bool(self.asscalar())

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self):
        return f"\n{self.asnumpy()}\n<NDArray {self.shape} @{self.context}>"

    # ------------------------------------------------------- method mirrors
    def reshape(self, *shape, **kwargs):
        from . import ops
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        return ops.reshape(self, shape=shape)

    def reshape_like(self, other):
        from . import ops
        return ops.reshape(self, shape=other.shape)

    def transpose(self, axes=None):
        from . import ops
        return ops.transpose(self, axes=axes)

    @property
    def T(self):
        return self.transpose()

    def flatten(self):
        from . import ops
        return ops.flatten(self)

    def expand_dims(self, axis):
        from . import ops
        return ops.expand_dims(self, axis=axis)

    def squeeze(self, axis=None):
        from . import ops
        return ops.squeeze(self, axis=axis)

    def broadcast_to(self, shape):
        from . import ops
        return ops.broadcast_to(self, shape=shape)

    def broadcast_like(self, other):
        from . import ops
        return ops.broadcast_to(self, shape=other.shape)

    def slice_axis(self, axis, begin, end):
        from . import ops
        return ops.slice_axis(self, axis=axis, begin=begin, end=end)

    def clip(self, a_min, a_max):
        from . import ops
        return ops.clip(self, a_min=a_min, a_max=a_max)

    def abs(self):
        from . import ops
        return ops.abs(self)

    def sqrt(self):
        from . import ops
        return ops.sqrt(self)

    def square(self):
        from . import ops
        return ops.square(self)

    def exp(self):
        from . import ops
        return ops.exp(self)

    def log(self):
        from . import ops
        return ops.log(self)

    def sum(self, axis=None, keepdims=False):
        from . import ops
        return ops.sum(self, axis=axis, keepdims=keepdims)

    def mean(self, axis=None, keepdims=False):
        from . import ops
        return ops.mean(self, axis=axis, keepdims=keepdims)

    def max(self, axis=None, keepdims=False):
        from . import ops
        return ops.max(self, axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False):
        from . import ops
        return ops.min(self, axis=axis, keepdims=keepdims)

    def prod(self, axis=None, keepdims=False):
        from . import ops
        return ops.prod(self, axis=axis, keepdims=keepdims)

    def argmax(self, axis=None):
        from . import ops
        return ops.argmax(self, axis=axis)

    def argmin(self, axis=None):
        from . import ops
        return ops.argmin(self, axis=axis)

    def norm(self, ord=2, axis=None, keepdims=False):
        from . import ops
        return ops.norm(self, ord=ord, axis=axis, keepdims=keepdims)


    def softmax(self, axis=-1):
        from . import ops
        return ops.softmax(self, axis=axis)

    def log_softmax(self, axis=-1):
        from . import ops
        return ops.log_softmax(self, axis=axis)




    def one_hot(self, depth, on_value=1.0, off_value=0.0):
        from . import ops
        return ops.one_hot(self, depth=depth, on_value=on_value, off_value=off_value)

    def take(self, indices, axis=0):
        from . import ops
        return ops.take(self, indices, axis=axis)

    def flip(self, axis):
        from . import ops
        return ops.flip(self, axis=axis)

    def repeat(self, repeats, axis=None):
        from . import ops
        return ops.repeat(self, repeats=repeats, axis=axis)

    def tile(self, reps):
        from . import ops
        return ops.tile(self, reps=reps)

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        from . import ops
        return ops.split(self, num_outputs=num_outputs, axis=axis, squeeze_axis=squeeze_axis)



    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    # DLPack interop (reference: NDArray::ToDLPack / FromDLPack)
    def to_dlpack_for_read(self):
        return self._data.__dlpack__()

    to_dlpack_for_write = to_dlpack_for_read


def _canonical_index(key):
    """Convert NDArray indices inside fancy-index tuples to raw arrays."""
    if isinstance(key, NDArray):
        return key._data
    if isinstance(key, tuple):
        return tuple(k._data if isinstance(k, NDArray) else k for k in key)
    return key


# ----------------------------------------------------------------------------
# free functions
# ----------------------------------------------------------------------------
def array(source_array, ctx=None, dtype=None):
    """mx.nd.array — create from any array-like (reference: ndarray.py:array)."""
    if isinstance(source_array, NDArray):
        data = source_array._data
    else:
        data = np.asarray(source_array)
    if dtype is None:
        dtype = data.dtype if data.dtype != np.float64 else np.float32
    return NDArray(jnp.asarray(data, dtype=dtype), ctx=ctx or current_context())


def from_numpy(a, zero_copy=False):
    return array(a)


def waitall():
    """Engine WaitForAll analog (REF:include/mxnet/engine.h WaitForAll).

    Blocks until every live jax.Array in the process is ready — a real sync
    of all previously dispatched device work, not just a fresh dummy
    computation (which would only bound the dispatch queue, not completion
    on every device).  A pending fused op segment flushes first: waitall
    is a full engine barrier."""
    _fusion.flush("waitall")
    for a in jax.live_arrays():
        try:
            a.block_until_ready()
        except RuntimeError as e:
            # deleted/donated buffers are expected flotsam; real async
            # computation failures must surface (WaitForAll semantics)
            if "deleted" in str(e).lower() or "donated" in str(e).lower():
                continue
            raise
    try:
        jax.effects_barrier()
    except Exception:
        pass


def concatenate(arrays, axis=0):
    from . import ops
    return ops.concat(*arrays, dim=axis)


# -- save/load: reference-compatible capability (REF:src/ndarray/ndarray.cc
#    Save/Load) realized with the .npz container --------------------------------
def save(fname, data):
    """Save list/dict of NDArray (mx.nd.save)."""
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, (list, tuple)):
        payload = {f"arr_{i}": a.asnumpy() for i, a in enumerate(data)}
        meta = "list"
    elif isinstance(data, dict):
        payload = {k: v.asnumpy() for k, v in data.items()}
        meta = "dict"
    else:
        raise TypeError("save: need NDArray, list or dict of NDArray")
    # serialize to memory first, then one atomic_write: (a) np.savez on a
    # bare path appends .npz, breaking `<prefix>-NNNN.params` parity; (b) a
    # single linear write keeps the durability layer's intended-bytes
    # digest exact (zipfile seeks would invalidate it); (c) a crash mid-save
    # can then never leave a truncated destination (docs/robustness.md)
    import io as _io
    from ..checkpoint import atomic_write
    bio = _io.BytesIO()
    np.savez(bio, __layout__=np.array(meta), **payload)
    with atomic_write(fname) as f:
        f.write(bio.getbuffer())


def load(fname):
    """Load what `save` wrote (mx.nd.load)."""
    import os
    if not os.path.exists(fname) and os.path.exists(str(fname) + ".npz"):
        fname = str(fname) + ".npz"   # files written by older revisions
    with np.load(fname, allow_pickle=False) as z:
        layout = str(z["__layout__"]) if "__layout__" in z else "dict"
        items = {k: NDArray(jnp.asarray(v)) for k, v in z.items() if k != "__layout__"}
    if layout == "list":
        return [items[f"arr_{i}"] for i in range(len(items))]
    return items


# ---------------------------------------------------------------------------
# remaining method-form op delegators (REF:python/mxnet/ndarray/ndarray.py
# exposes most ops as methods; the explicit ones above carry custom
# signatures, these are straight passthroughs)
# ---------------------------------------------------------------------------
def _delegate_method(name):
    def method(self, *args, **kwargs):
        from . import ops
        return getattr(ops, name)(self, *args, **kwargs)
    method.__name__ = name
    method.__doc__ = f"Method form of mx.nd.{name} (self as first input)."
    setattr(NDArray, name, method)


for _m in ("round", "floor", "ceil", "pick", "pad", "sort", "argsort",
           "topk", "slice", "slice_like", "swapaxes", "sign", "rint",
           "log2", "log10", "log1p", "expm1", "rsqrt", "cbrt",
           "reciprocal", "diag", "relu", "sigmoid", "tanh", "dot",
           "zeros_like", "ones_like"):
    _delegate_method(_m)
del _m
