"""Fused RNN operator — mx.nd.RNN (REF:src/operator/rnn.cc: the cuDNN
RNN/LSTM/GRU fused kernel with packed parameter blob).

TPU-native design: one `lax.scan` per layer/direction with the input
projection for ALL timesteps hoisted into a single (T*N, I)x(I, G*H) matmul
before the scan (the MXU-friendly shape; inside the scan only the (N, H)
recurrent matmul remains).  The packed `parameters` blob uses the
reference's cuDNN layout — per layer/direction: Wx gates, Wh gates, then
all biases (bx, bh per gate) at the tail of the blob — so checkpoints and
Module code that treat the blob as opaque keep working.

Gate orders (cuDNN = reference): LSTM i,f,g,o ; GRU r,z,n.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .ops import _apply

__all__ = ["RNN", "rnn_param_size"]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(mode, input_size, state_size, num_layers=1,
                   bidirectional=False):
    """Total packed-parameter count (matches the reference's blob size)."""
    g = _GATES[mode]
    d = 2 if bidirectional else 1
    total = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * d
        for _ in range(d):
            total += g * state_size * in_sz + g * state_size * state_size
            total += 2 * g * state_size
    return total


def _unpack(params, mode, input_size, state_size, num_layers, bidirectional):
    """Slice the flat blob into per-layer/direction (Wx, Wh, bx, bh)."""
    g = _GATES[mode]
    d = 2 if bidirectional else 1
    H = state_size
    out = []
    off = 0
    # weights first for ALL layers, then all biases (cuDNN blob layout)
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else H * d
        dirs = []
        for _ in range(d):
            wx = lax.dynamic_slice(params, (off,), (g * H * in_sz,)
                                   ).reshape(g * H, in_sz)
            off += g * H * in_sz
            wh = lax.dynamic_slice(params, (off,), (g * H * H,)
                                   ).reshape(g * H, H)
            off += g * H * H
            dirs.append([wx, wh])
        out.append(dirs)
    for layer in range(num_layers):
        for di in range(d):
            bx = lax.dynamic_slice(params, (off,), (g * H,))
            off += g * H
            bh = lax.dynamic_slice(params, (off,), (g * H,))
            off += g * H
            out[layer][di] += [bx, bh]
    return out


def _cell_step(mode, H):
    if mode == "lstm":
        def step(carry, xproj, wh, bh):
            h, c = carry
            gates = xproj + h @ wh.T + bh
            i, f, gq, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            gq = jnp.tanh(gq)
            c2 = f * c + i * gq
            h2 = o * jnp.tanh(c2)
            return (h2, c2), h2
        return step
    if mode == "gru":
        def step(carry, xproj, wh, bh):
            h = carry[0]
            rx, zx, nx = jnp.split(xproj, 3, axis=-1)
            rh, zh, nh = jnp.split(h @ wh.T + bh, 3, axis=-1)
            r = jax.nn.sigmoid(rx + rh)
            z = jax.nn.sigmoid(zx + zh)
            n = jnp.tanh(nx + r * nh)
            h2 = (1 - z) * n + z * h
            return (h2,), h2
        return step

    act = jax.nn.relu if mode == "rnn_relu" else jnp.tanh

    def step(carry, xproj, wh, bh):
        h = carry[0]
        h2 = act(xproj + h @ wh.T + bh)
        return (h2,), h2
    return step


def RNN(data, parameters, state, state_cell=None, state_size=None,
        num_layers=1, mode="lstm", bidirectional=False, p=0.0,
        state_outputs=False, **kw):
    """Fused multi-layer (bi)RNN.  data: (T, N, I); state: (L*D, N, H);
    state_cell (LSTM): (L*D, N, H).  Returns output (T, N, H*D), and with
    `state_outputs` the final h (and c for LSTM) — reference semantics."""
    H = state_size
    d = 2 if bidirectional else 1
    is_lstm = mode == "lstm"

    def f(x, params, h0, *maybe_c):
        T, N, I = x.shape
        c0 = maybe_c[0] if is_lstm else None
        layers = _unpack(params, mode, I, H, num_layers, bidirectional)
        step_cell = _cell_step(mode, H)
        hs_out, cs_out = [], []
        inp = x
        for li, dirs in enumerate(layers):
            outs = []
            for di, (wx, wh, bx, bh) in enumerate(dirs):
                seq = inp if di == 0 else jnp.flip(inp, 0)
                # hoisted input projection: one big MXU matmul over T*N rows
                xproj = (seq.reshape(T * N, -1) @ wx.T + bx).reshape(
                    T, N, -1)
                idx = li * d + di
                carry = (h0[idx], c0[idx]) if is_lstm else (h0[idx],)

                def scan_step(carry, xp):
                    return step_cell(carry, xp, wh, bh)

                carry, ys = lax.scan(scan_step, carry, xproj)
                if di == 1:
                    ys = jnp.flip(ys, 0)
                outs.append(ys)
                hs_out.append(carry[0])
                if is_lstm:
                    cs_out.append(carry[1])
            inp = outs[0] if d == 1 else jnp.concatenate(outs, -1)
        out = inp
        if state_outputs:
            hN = jnp.stack(hs_out, 0)
            if is_lstm:
                return out, hN, jnp.stack(cs_out, 0)
            return out, hN
        return out

    args = [data, parameters, state] + ([state_cell] if is_lstm else [])
    return _apply(f, args, "RNN")
