"""mx.nd.contrib — detection ops (REF:src/operator/contrib/multibox_prior.cc,
multibox_target.cc, multibox_detection.cc, bounding_box.cc).

TPU-native design: the reference's CUDA kernels produce *fixed-size padded*
outputs already (invalid entries are -1), which is exactly XLA's static-shape
model — so every op here is a pure fixed-shape function: IoU matching and
target encoding are vectorized (`vmap` over batch), greedy NMS is a
`lax.fori_loop` over score-sorted candidates (sequential dependence is
inherent to greedy NMS; each step is O(A) vector work on-chip).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .ops import _apply

__all__ = ["box_iou", "box_nms", "MultiBoxPrior", "MultiBoxTarget",
           "MultiBoxDetection", "DeformableConvolution", "count_sketch",
           "boolean_mask"]


# --------------------------------------------------------------------------
# geometry helpers (corner format: x1 y1 x2 y2)
# --------------------------------------------------------------------------

def _iou_corner(a, b):
    """a: (..., A, 4), b: (..., M, 4) -> (..., A, M)."""
    ax1, ay1, ax2, ay2 = jnp.split(a, 4, axis=-1)          # (A,1)
    bx1, by1, bx2, by2 = [x.squeeze(-1) for x in jnp.split(b, 4, axis=-1)]
    ix1 = jnp.maximum(ax1, bx1[..., None, :])
    iy1 = jnp.maximum(ay1, by1[..., None, :])
    ix2 = jnp.minimum(ax2, bx2[..., None, :])
    iy2 = jnp.minimum(ay2, by2[..., None, :])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    area_a = jnp.maximum(ax2 - ax1, 0) * jnp.maximum(ay2 - ay1, 0)
    area_b = jnp.maximum(bx2 - bx1, 0) * jnp.maximum(by2 - by1, 0)
    union = area_a + area_b[..., None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _center_to_corner(x):
    cx, cy, w, h = jnp.split(x, 4, axis=-1)
    return jnp.concatenate([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                           axis=-1)


def _corner_to_center(x):
    x1, y1, x2, y2 = jnp.split(x, 4, axis=-1)
    return jnp.concatenate([(x1 + x2) / 2, (y1 + y2) / 2, x2 - x1, y2 - y1],
                           axis=-1)


def box_iou(lhs, rhs, format="corner", **kw):
    """Pairwise IoU (REF:src/operator/contrib/bounding_box.cc box_iou)."""

    def f(a, b):
        if format == "center":
            a, b = _center_to_corner(a), _center_to_corner(b)
        return _iou_corner(a, b)

    return _apply(f, [lhs, rhs], "box_iou", nondiff=True)


# --------------------------------------------------------------------------
# greedy NMS core: returns keep mask over entries ordered as given
# --------------------------------------------------------------------------

def _nms_keep(boxes, scores, ids, valid, thresh, topk, force_suppress):
    """boxes (A,4) already score-sorted desc; sequential greedy suppression.
    `topk` bounds the candidate set (reference semantics: everything beyond
    the top-k scores is discarded outright)."""
    A = boxes.shape[0]
    ar = jnp.arange(A)
    n_iter = A if topk < 0 else min(int(topk), A)
    if topk >= 0:
        valid = valid & (ar < topk)
    iou = _iou_corner(boxes, boxes)                       # (A, A)
    same = jnp.ones((A, A), bool) if force_suppress else \
        (ids[:, None] == ids[None, :])

    def body(i, keep):
        sup = (iou[i] > thresh) & same[i] & (ar > i) & keep[i] & valid[i]
        return keep & ~sup

    keep = jax.lax.fori_loop(0, n_iter, body, valid)
    return keep


def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, background_id=-1,
            force_suppress=False, in_format="corner", out_format="corner",
            **kw):
    """REF:src/operator/contrib/bounding_box.cc BoxNMS.  Output keeps the
    score-sorted order; suppressed/invalid rows are all -1 (fixed shape)."""

    def f(x):
        shape = x.shape
        flat = x.reshape((-1,) + shape[-2:]) if x.ndim > 2 else x[None]

        def one(batch):
            scores = batch[:, score_index]
            boxes = jax.lax.dynamic_slice_in_dim(batch, coord_start, 4, axis=1)
            if in_format == "center":
                boxes = _center_to_corner(boxes)
            if id_index >= 0:
                ids = batch[:, id_index]
            else:
                ids = jnp.zeros_like(scores)
            valid = scores > valid_thresh
            if id_index >= 0 and background_id >= 0:
                valid &= ids != background_id
            order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf))
            b_s, s_s, i_s, v_s = (boxes[order], scores[order], ids[order],
                                  valid[order])
            keep = _nms_keep(b_s, s_s, i_s, v_s, overlap_thresh, topk,
                             force_suppress)
            out_rows = batch[order]
            # b_s is always corner-format working coords; rewrite the coord
            # columns in the requested out_format regardless of in_format
            coords = _corner_to_center(b_s) if out_format == "center" else b_s
            out_rows = jax.lax.dynamic_update_slice_in_dim(
                out_rows, coords, coord_start, axis=1)
            return jnp.where(keep[:, None], out_rows, -jnp.ones_like(out_rows))

        out = jax.vmap(one)(flat)
        return out.reshape(shape)

    return _apply(f, [data], "box_nms", nondiff=True)


# --------------------------------------------------------------------------
# MultiBoxPrior
# --------------------------------------------------------------------------

def MultiBoxPrior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                  steps=(-1.0, -1.0), offsets=(0.5, 0.5), **kw):
    """Anchor generation (REF:src/operator/contrib/multibox_prior.cc).
    data (N,C,H,W) -> (1, H*W*(S+R-1), 4) normalized corner boxes."""
    sizes = tuple(float(s) for s in _tuple(sizes))
    ratios = tuple(float(r) for r in _tuple(ratios))
    steps = tuple(float(s) for s in _tuple(steps))
    offsets = tuple(float(o) for o in _tuple(offsets))

    def f(x):
        H, W = x.shape[-2], x.shape[-1]
        step_y = steps[0] if steps[0] > 0 else 1.0 / H
        step_x = steps[1] if steps[1] > 0 else 1.0 / W
        cy = (jnp.arange(H, dtype=jnp.float32) + offsets[0]) * step_y
        cx = (jnp.arange(W, dtype=jnp.float32) + offsets[1]) * step_x
        cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")      # (H, W)
        # anchor set per cell: (s_k, r_0) for all k, then (s_0, r_k) k>=1
        whs = [(s * np.sqrt(ratios[0]), s / np.sqrt(ratios[0]))
               for s in sizes]
        whs += [(sizes[0] * np.sqrt(r), sizes[0] / np.sqrt(r))
                for r in ratios[1:]]
        wh = jnp.asarray(whs, jnp.float32)                  # (K, 2)
        K = wh.shape[0]
        centers = jnp.stack([cxg, cyg], axis=-1)[:, :, None, :]   # (H,W,1,2)
        half = wh[None, None, :, :] / 2                      # (1,1,K,2)
        lo = centers - half
        hi = centers + half
        anchors = jnp.concatenate([lo, hi], axis=-1).reshape(H * W * K, 4)
        if clip:
            anchors = jnp.clip(anchors, 0.0, 1.0)
        return anchors[None]

    return _apply(f, [data], "MultiBoxPrior", nondiff=True)


def _tuple(v):
    if isinstance(v, (int, float)):
        return (v,)
    if isinstance(v, str):
        return tuple(float(t) for t in
                     v.strip("()[] ").replace(",", " ").split())
    return tuple(v)


# --------------------------------------------------------------------------
# MultiBoxTarget
# --------------------------------------------------------------------------

def MultiBoxTarget(anchor, label, cls_pred, overlap_threshold=0.5,
                   ignore_label=-1.0, negative_mining_ratio=-1.0,
                   negative_mining_thresh=0.5, minimum_negative_samples=0,
                   variances=(0.1, 0.1, 0.2, 0.2), **kw):
    """Anchor matching + target encoding
    (REF:src/operator/contrib/multibox_target.cc).

    anchor (1,A,4) corner; label (B,M,5) rows [cls,x1,y1,x2,y2], pad=-1;
    cls_pred (B,C+1,A) (class scores, used for hard negative mining).
    Returns [loc_target (B,A*4), loc_mask (B,A*4), cls_target (B,A)].
    Matching is argmax-threshold plus per-gt forced best-anchor (bipartite
    approximated by scatter; ties resolved by later gt index, deterministic).
    """
    variances = tuple(float(v) for v in _tuple(variances))

    def f(anc, lab, pred):
        A = anc.shape[1]
        anc2 = anc.reshape(A, 4)
        anc_c = _corner_to_center(anc2)                    # (A,4) cx cy w h

        def one(lab_b, pred_b):
            M = lab_b.shape[0]
            gt_cls = lab_b[:, 0]
            gt_box = lab_b[:, 1:5]
            valid_gt = gt_cls >= 0                          # (M,)
            iou = _iou_corner(anc2, gt_box)                 # (A, M)
            iou = jnp.where(valid_gt[None, :], iou, 0.0)
            best_gt = jnp.argmax(iou, axis=1)               # (A,)
            best_iou = jnp.max(iou, axis=1)
            matched = best_iou >= overlap_threshold
            # forced bipartite-ish: each valid gt claims its best anchor
            best_anchor_per_gt = jnp.argmax(iou, axis=0)    # (M,)
            gt_has_overlap = jnp.max(iou, axis=0) > 1e-12
            force = valid_gt & gt_has_overlap
            matched = matched.at[best_anchor_per_gt].set(
                jnp.where(force, True, matched[best_anchor_per_gt]))
            best_gt = best_gt.at[best_anchor_per_gt].set(
                jnp.where(force, jnp.arange(M), best_gt[best_anchor_per_gt]))
            # classification targets: matched -> cls+1, else background 0
            cls_t = jnp.where(matched, gt_cls[best_gt] + 1.0, 0.0)
            if negative_mining_ratio > 0:
                # hardness = max non-background class score
                hard = jnp.max(pred_b[1:], axis=0)          # (A,)
                is_neg = (~matched) & (best_iou < negative_mining_thresh)
                num_pos = jnp.sum(matched)
                num_neg = jnp.maximum(
                    num_pos * negative_mining_ratio,
                    float(minimum_negative_samples))
                neg_rank = jnp.argsort(
                    jnp.argsort(-jnp.where(is_neg, hard, -jnp.inf)))
                selected_neg = is_neg & (neg_rank < num_neg)
                cls_t = jnp.where(matched, cls_t,
                                  jnp.where(selected_neg, 0.0,
                                            float(ignore_label)))
            # location targets (center offsets / variances)
            g = _corner_to_center(gt_box)[best_gt]          # (A,4)
            eps = 1e-12
            tx = (g[:, 0] - anc_c[:, 0]) / jnp.maximum(anc_c[:, 2], eps) / variances[0]
            ty = (g[:, 1] - anc_c[:, 1]) / jnp.maximum(anc_c[:, 3], eps) / variances[1]
            tw = jnp.log(jnp.maximum(g[:, 2], eps) /
                         jnp.maximum(anc_c[:, 2], eps)) / variances[2]
            th = jnp.log(jnp.maximum(g[:, 3], eps) /
                         jnp.maximum(anc_c[:, 3], eps)) / variances[3]
            loc_t = jnp.stack([tx, ty, tw, th], axis=1)     # (A,4)
            loc_t = jnp.where(matched[:, None], loc_t, 0.0)
            loc_m = jnp.where(matched[:, None],
                              jnp.ones_like(loc_t), jnp.zeros_like(loc_t))
            return loc_t.reshape(-1), loc_m.reshape(-1), cls_t

        loc_t, loc_m, cls_t = jax.vmap(one)(lab, pred)
        return loc_t, loc_m, cls_t

    return _apply(f, [anchor, label, cls_pred], "MultiBoxTarget",
                  nondiff=True)


# --------------------------------------------------------------------------
# MultiBoxDetection
# --------------------------------------------------------------------------

def MultiBoxDetection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                      background_id=0, nms_threshold=0.5,
                      force_suppress=False,
                      variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1, **kw):
    """Decode + confidence filter + per-class NMS
    (REF:src/operator/contrib/multibox_detection.cc).
    cls_prob (B,C+1,A), loc_pred (B,A*4), anchor (1,A,4) ->
    (B, A, 6) rows [class_id, score, x1, y1, x2, y2], invalid = -1."""
    variances = tuple(float(v) for v in _tuple(variances))

    def f(prob, loc, anc):
        A = anc.shape[1]
        anc_c = _corner_to_center(anc.reshape(A, 4))

        def one(prob_b, loc_b):
            # class selection (excluding background row `background_id`)
            C1 = prob_b.shape[0]
            mask = jnp.arange(C1)[:, None] != background_id
            scores_nb = jnp.where(mask, prob_b, -jnp.inf)
            best_cls = jnp.argmax(scores_nb, axis=0)        # (A,)
            score = jnp.max(scores_nb, axis=0)
            cls_id = jnp.where(best_cls > background_id, best_cls - 1,
                               best_cls).astype(jnp.float32)
            valid = score > threshold
            # decode
            l = loc_b.reshape(A, 4)
            cx = l[:, 0] * variances[0] * anc_c[:, 2] + anc_c[:, 0]
            cy = l[:, 1] * variances[1] * anc_c[:, 3] + anc_c[:, 1]
            w = jnp.exp(l[:, 2] * variances[2]) * anc_c[:, 2]
            h = jnp.exp(l[:, 3] * variances[3]) * anc_c[:, 3]
            boxes = _center_to_corner(jnp.stack([cx, cy, w, h], axis=1))
            if clip:
                boxes = jnp.clip(boxes, 0.0, 1.0)
            order = jnp.argsort(-jnp.where(valid, score, -jnp.inf))
            b_s, s_s, c_s, v_s = (boxes[order], score[order], cls_id[order],
                                  valid[order])
            keep = _nms_keep(b_s, s_s, c_s, v_s, nms_threshold, nms_topk,
                             force_suppress)
            rows = jnp.concatenate(
                [c_s[:, None], s_s[:, None], b_s], axis=1)  # (A,6)
            return jnp.where(keep[:, None], rows, -jnp.ones_like(rows))

        return jax.vmap(one)(prob, loc)

    return _apply(f, [cls_prob, loc_pred, anchor], "MultiBoxDetection",
                  nondiff=True)


# --------------------------------------------------------------------------
# deformable convolution, count_sketch, boolean_mask
# (REF:src/operator/contrib/{deformable_convolution,count_sketch,
#  boolean_mask}.cc)
# --------------------------------------------------------------------------

def _bilinear_zero(feat, ys, xs):
    """feat: (C, H, W); sample at fractional (ys, xs) with ZERO padding —
    each bilinear corner contributes only if it is a real pixel (the DCN
    im2col contract, unlike the ROI ops' border-clamp)."""
    H, W = feat.shape[-2:]
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy1 = ys - y0
    wx1 = xs - x0
    out = None
    for dy, wy in ((0, 1.0 - wy1), (1, wy1)):
        for dx, wx in ((0, 1.0 - wx1), (1, wx1)):
            yi = y0.astype(jnp.int32) + dy
            xi = x0.astype(jnp.int32) + dx
            ok = ((yi >= 0) & (yi < H) & (xi >= 0) & (xi < W))
            v = feat[:, jnp.clip(yi, 0, H - 1), jnp.clip(xi, 0, W - 1)]
            term = v * (wy * wx * ok.astype(feat.dtype))
            out = term if out is None else out + term
    return out


def DeformableConvolution(data, offset, weight, bias=None, kernel=None,
                          stride=(1, 1), dilate=(1, 1), pad=(0, 0),
                          num_filter=None, num_group=1,
                          num_deformable_group=1, no_bias=False, **kw):
    """Deformable convolution v1 (REF:src/operator/contrib/
    deformable_convolution.cc, Dai et al. 2017).

    TPU-native design: instead of the reference's deformable_im2col CUDA
    kernel, the offset taps are gathered with a vectorized zero-padded
    bilinear sampler into an (N, C·KH·KW, Ho·Wo) patch tensor, and the
    convolution itself is ONE MXU matmul against the (Cout, C·KH·KW)
    weight — gather feeds the systolic array.

    data: (N, C, H, W); offset: (N, 2·dg·KH·KW, Ho, Wo) interleaved
    (dy, dx) per tap; weight: (Cout, C/num_group, KH, KW)."""
    if num_group != 1:
        raise ValueError("DeformableConvolution: num_group>1 not supported")
    kh, kw_ = kernel
    sh, sw = stride if isinstance(stride, (tuple, list)) else (stride,) * 2
    dh, dw = dilate if isinstance(dilate, (tuple, list)) else (dilate,) * 2
    ph, pw = pad if isinstance(pad, (tuple, list)) else (pad,) * 2
    dg = num_deformable_group

    def f(x, off, w, *b):
        N, C, H, W = x.shape
        Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        Wo = (W + 2 * pw - dw * (kw_ - 1) - 1) // sw + 1
        base_y = (jnp.arange(Ho) * sh - ph)[:, None, None]      # (Ho,1,1)
        base_x = (jnp.arange(Wo) * sw - pw)[None, :, None]      # (1,Wo,1)
        tap_y = (jnp.arange(kh) * dh)[None, None, :, None]      # (1,1,kh,1)
        tap_x = (jnp.arange(kw_) * dw)[None, None, None, :]     # (1,1,1,kw)

        def one(feat, o):
            # o: (2*dg*kh*kw, Ho, Wo) -> (dg, kh, kw, 2, Ho, Wo)
            o = o.reshape(dg, kh, kw_, 2, Ho, Wo)

            # positions: (Ho, Wo, kh, kw) per deformable group
            ys = (base_y[..., None] + tap_y)                     # (Ho,1,kh,1)
            xs = (base_x[..., None] + tap_x)                     # (1,Wo,1,kw)
            ys = jnp.broadcast_to(ys, (Ho, Wo, kh, kw_))
            xs = jnp.broadcast_to(xs, (Ho, Wo, kh, kw_))
            outs = []
            cg = C // dg
            for g in range(dg):
                dy = jnp.transpose(o[g, :, :, 0], (2, 3, 0, 1))  # (Ho,Wo,kh,kw)
                dx = jnp.transpose(o[g, :, :, 1], (2, 3, 0, 1))
                sampled = _bilinear_zero(feat[g * cg:(g + 1) * cg],
                                         ys + dy, xs + dx)       # (cg,Ho,Wo,kh,kw)
                outs.append(sampled)
            return jnp.concatenate(outs, axis=0)                 # (C,Ho,Wo,kh,kw)

        patches = jax.vmap(one)(x, off)                          # (N,C,Ho,Wo,kh,kw)
        patches = jnp.transpose(patches, (0, 1, 4, 5, 2, 3))     # (N,C,kh,kw,Ho,Wo)
        col = patches.reshape(N, C * kh * kw_, Ho * Wo)
        wmat = w.reshape(num_filter, C * kh * kw_)
        out = jnp.einsum("ok,nkp->nop", wmat, col).reshape(
            N, num_filter, Ho, Wo)
        if b:
            out = out + b[0][None, :, None, None]
        return out

    args = [data, offset, weight] + ([] if (no_bias or bias is None)
                                     else [bias])
    return _apply(f, args, "DeformableConvolution")


def count_sketch(data, h, s, out_dim=None, **kw):
    """Count sketch projection (REF:src/operator/contrib/count_sketch.cc,
    compact bilinear pooling): out[:, h[i]] += s[i]·data[:, i] — one XLA
    scatter-add, differentiable w.r.t. data."""
    out_dim = int(out_dim)

    def f(x, hh, ss):
        n = x.shape[0]
        idx = hh.astype(jnp.int32)
        zero = jnp.zeros((n, out_dim), x.dtype)
        return zero.at[:, idx].add(x * ss.astype(x.dtype))

    return _apply(f, [data, h, s], "count_sketch")


def boolean_mask(data, index, axis=0, **kw):
    """Select rows where index != 0 (REF:src/operator/contrib/
    boolean_mask.cc).  DATA-DEPENDENT output shape: eager-only by design —
    XLA requires static shapes, so inside hybridize/jit use
    `where`/`SequenceMask` style masking instead (documented divergence)."""
    from .. import _functional
    if _functional.active():
        from ..base import MXNetError
        raise MXNetError(
            "boolean_mask has a data-dependent output shape and cannot be "
            "traced into a compiled graph; use where()/SequenceMask-style "
            "masking inside hybridized blocks")

    def f(x, idx):
        keep = jnp.asarray(idx) != 0
        return jnp.compress(keep, x, axis=axis)

    return _apply(f, [data, index], "boolean_mask")


# --------------------------------------------------------------------------
# control flow (REF:src/operator/control_flow.cc — foreach/while_loop/cond;
# the reference executed a cached sub-graph per step, here the TPU-native
# forms are lax.scan / lax.while_loop / lax.cond inside traces and plain
# Python in eager mode, where every op records on the autograd tape)
# --------------------------------------------------------------------------

def _as_state_list(states):
    single = not isinstance(states, (list, tuple))
    return ([states] if single else list(states)), single


def _raw(x):
    """Unwrap NDArray -> raw jax value (creation ops hand back NDArray
    wrappers even inside functional traces; lax control flow needs raw
    pytree leaves)."""
    from .ndarray import NDArray
    return x._data if isinstance(x, NDArray) else x


def foreach(body, data, init_states):
    """Scan `body(x_t, states) -> (out_t, new_states)` over data's leading
    axis (REF control_flow.cc:foreach).  data: array or list of arrays that
    share the leading axis; states: array or list.  Inside a compiled trace
    this is ONE `lax.scan` (sequential op count independent of length);
    eagerly it is a Python loop whose every op lands on the autograd tape.
    Returns (stacked_outputs, final_states) with the states in the same
    single/list form they came in."""
    from .. import _functional
    from . import ops as F
    states, single = _as_state_list(init_states)
    multi_data = isinstance(data, (list, tuple))

    if _functional.active():
        states = [_raw(s) for s in states]
        xs = tuple(_raw(d) for d in data) if multi_data else _raw(data)

        def scan_body(carry, x):
            xt = list(x) if multi_data else x
            out, new_states = body(xt, list(carry) if not single
                                   else carry[0])
            ns, _ = _as_state_list(new_states)
            return tuple(_raw(v) for v in ns), _raw(out)

        carry, ys = jax.lax.scan(scan_body, tuple(states), xs)
        final = carry[0] if single else list(carry)
        return ys, final

    length = (data[0] if multi_data else data).shape[0]
    outputs = []
    cur = states[0] if single else states
    for t in range(length):
        xt = [d[t] for d in data] if multi_data else data[t]
        out, cur = body(xt, cur)
        outputs.append(out)
    stacked = F.stack(*outputs, axis=0)
    return stacked, cur


def while_loop(cond, func, loop_vars, max_iterations):
    """`while cond(*loop_vars): out, loop_vars = func(*loop_vars)`
    (REF control_flow.cc:while_loop).  Outputs are stacked into a
    fixed (max_iterations, ...) buffer — rows beyond the actual trip count
    are zeros — plus the final loop_vars and the step count; XLA's static
    shapes make max_iterations mandatory, exactly as the reference did.
    The traced form is `lax.while_loop` (NOT differentiable — same
    limitation as the reference's); differentiate through `foreach` with a
    fixed length instead when gradients are needed."""
    from .. import _functional
    from . import ops as F
    lvars, single = _as_state_list(loop_vars)
    if max_iterations is None or max_iterations <= 0:
        raise ValueError("while_loop requires a positive max_iterations")

    def _pred(vs):
        c = cond(*vs)
        c = c.asnumpy() if hasattr(c, "asnumpy") else np.asarray(c)
        return bool(np.ravel(c)[0])

    if not _functional.active():
        outputs = []
        steps = 0
        cur = lvars
        while steps < max_iterations and _pred(cur):
            out, new_vars = func(*cur)
            cur, _ = _as_state_list(new_vars)
            outputs.append(out)
            steps += 1
        if not outputs:
            # zero-trip loop: infer the row shape abstractly so eager and
            # traced agree (both return an all-zero buffer, steps=0)
            row = jax.eval_shape(lambda vs: _raw(func(*vs)[0]),
                                 tuple(_raw(v) for v in cur))
            from .ndarray import NDArray
            zeros = NDArray(jnp.zeros((max_iterations,) + tuple(row.shape),
                                      row.dtype))
            return zeros, (cur[0] if single else cur), 0
        pad = [F.zeros_like(outputs[0]) for _ in
               range(max_iterations - steps)]
        stacked = F.stack(*(outputs + pad), axis=0)
        return stacked, (cur[0] if single else cur), steps

    # traced: probe one func application for output structure, then run a
    # fixed-bound while loop writing into a preallocated buffer
    lvars = [_raw(v) for v in lvars]
    out0_shape = jax.eval_shape(lambda vs: _raw(func(*vs)[0]), tuple(lvars))
    buf = jnp.zeros((max_iterations,) + tuple(out0_shape.shape),
                    out0_shape.dtype)

    def w_cond(carry):
        i, _, vs = carry
        return jnp.logical_and(i < max_iterations,
                               jnp.asarray(_raw(cond(*vs))).reshape(()))

    def w_body(carry):
        i, b, vs = carry
        out, new_vars = func(*vs)
        nv, _ = _as_state_list(new_vars)
        b = jax.lax.dynamic_update_index_in_dim(b, _raw(out), i, axis=0)
        return i + 1, b, tuple(_raw(v) for v in nv)

    steps, buf, fin = jax.lax.while_loop(w_cond, w_body,
                                         (jnp.int32(0), buf, tuple(lvars)))
    return buf, (fin[0] if single else list(fin)), steps


def cond(pred, then_func, else_func):
    """`then_func() if pred else else_func()` (REF control_flow.cc:cond).
    Traced: `lax.cond` — both branches must produce matching shapes/dtypes;
    eager: plain Python branch."""
    from .. import _functional
    if not _functional.active():
        p = pred.asnumpy() if hasattr(pred, "asnumpy") else np.asarray(pred)
        return then_func() if bool(np.ravel(p)[0]) else else_func()
    return jax.lax.cond(jnp.asarray(_raw(pred)).reshape(()).astype(bool),
                        lambda: _raw(then_func()), lambda: _raw(else_func()))


__all__ += ["foreach", "while_loop", "cond"]


# ----------------------------------------------------------------------------
# long-tail contrib ops (REF:src/operator/contrib/**) — r4 parity sweep
# ----------------------------------------------------------------------------
def quadratic(data, a=0.0, b=0.0, c=0.0, **kw):
    """a·x² + b·x + c (REF:contrib/quadratic_op.cc — upstream's tutorial
    op; kept for parity)."""
    return _apply(lambda x: a * jnp.square(x) + b * x + c, [data],
                  "quadratic")


def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None, **kw):
    """arange shaped like `data` (REF:contrib/arange_like — position-id
    helper for transformer embeddings): axis=None → data's full shape,
    else a 1-D range of that axis' length."""
    def fn(x):
        if axis is None:
            n = int(np.prod(x.shape))
            out = jnp.arange(n, dtype=x.dtype) * step + start
            return jnp.repeat(out, repeat)[:n].reshape(x.shape) \
                if repeat != 1 else out.reshape(x.shape)
        n = x.shape[axis]
        out = jnp.arange(n, dtype=x.dtype) * step + start
        return jnp.repeat(out, repeat)[:n] if repeat != 1 else out
    return _apply(fn, [data], "arange_like", nondiff=True)


def allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False, **kw):
    """Scalar 1.0/0.0 closeness test (REF:contrib/allclose_op.cc)."""
    return _apply(lambda x, y: jnp.allclose(
        x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)
        .astype(jnp.float32), [a, b], "allclose", nondiff=True)


def div_sqrt_dim(data, **kw):
    """data / √(last-dim size) (REF:contrib/transformer.cc div_sqrt_dim
    — the attention-score scaling helper)."""
    return _apply(lambda x: x / jnp.sqrt(jnp.asarray(
        float(x.shape[-1]), x.dtype)), [data], "div_sqrt_dim")


def index_copy(old, index, new, **kw):
    """Copy rows of `new` into `old` at `index` along axis 0
    (REF:contrib/index_copy.cc).  Functional: returns the updated array
    (the reference mutates out-of-place too unless out=old)."""
    return _apply(lambda o, i, n: o.at[i.astype(jnp.int32)].set(n),
                  [old, index, new], "index_copy")


def index_array(data, axes=None, **kw):
    """Per-element index coordinates (REF:contrib/index_array.cc):
    output shape data.shape + (len(axes) or ndim,), int64→int32 here
    (TPU-native: int32 index space)."""
    def fn(x):
        axs = tuple(range(x.ndim)) if axes is None else tuple(axes)
        grids = jnp.meshgrid(*[jnp.arange(s) for s in x.shape],
                             indexing="ij")
        return jnp.stack([grids[a] for a in axs], axis=-1).astype(
            jnp.int32)
    return _apply(fn, [data], "index_array", nondiff=True)


def gradientmultiplier(data, scalar=1.0, **kw):
    """Identity forward, gradient scaled by `scalar` on backward
    (REF:contrib/gradient_multiplier_op.cc — gradient-reversal layers
    use scalar=-lambda)."""
    @jax.custom_vjp
    def gm(x):
        return x

    def gm_fwd(x):
        return x, None

    def gm_bwd(_, g):
        return (g * scalar,)

    gm.defvjp(gm_fwd, gm_bwd)
    return _apply(gm, [data], "gradientmultiplier")


def fft(data, compute_size=128, **kw):
    """FFT over the last axis (REF:contrib/fft.cc, cuFFT upstream —
    XLA-native here).  Real input (..., n) → interleaved re/im output
    (..., 2n), matching the reference's layout."""
    def fn(x):
        f = jnp.fft.fft(x.astype(jnp.float32), axis=-1)
        return jnp.stack([f.real, f.imag], axis=-1).reshape(
            *x.shape[:-1], 2 * x.shape[-1]).astype(jnp.float32)
    return _apply(fn, [data], "fft")


def ifft(data, compute_size=128, **kw):
    """Inverse FFT of the interleaved re/im layout (..., 2n) → real
    (..., n).  UNNORMALIZED like the reference's cuFFT path — callers
    divide by n (REF:contrib/ifft.cc docs)."""
    def fn(x):
        n = x.shape[-1] // 2
        c = x.reshape(*x.shape[:-1], n, 2)
        z = c[..., 0] + 1j * c[..., 1]
        return (jnp.fft.ifft(z, axis=-1).real * n).astype(jnp.float32)
    return _apply(fn, [data], "ifft")


def AdaptiveAvgPooling2D(data, output_size=1, **kw):
    """NCHW adaptive average pooling (REF:contrib/adaptive_avg_pooling.cc).
    TPU-native formulation: the variable-size bin averages are expressed
    as two small averaging matrices (P_h · X · P_wᵀ via einsum) — dense
    MXU work instead of ragged windows."""
    os_ = (output_size, output_size) if isinstance(output_size, int) \
        else tuple(output_size)

    def pool_matrix(n_in, n_out):
        m = np.zeros((n_out, n_in), np.float32)
        for i in range(n_out):
            s = int(np.floor(i * n_in / n_out))
            e = int(np.ceil((i + 1) * n_in / n_out))
            m[i, s:e] = 1.0 / (e - s)
        return m

    def fn(x):
        ph = jnp.asarray(pool_matrix(x.shape[2], os_[0]), x.dtype)
        pw = jnp.asarray(pool_matrix(x.shape[3], os_[1]), x.dtype)
        return jnp.einsum("oh,nchw,pw->ncop", ph, x, pw)
    return _apply(fn, [data], "AdaptiveAvgPooling2D")


def bipartite_matching(data, is_ascend=False, threshold=None, topk=-1,
                       **kw):
    """Greedy bipartite matching on a (B, N, M) score matrix
    (REF:src/operator/contrib/bounding_box.cc bipartite_matching — the
    anchor-assignment primitive under MultiBoxTarget).  Returns
    (row_assignments (B, N), col_assignments (B, M)) with -1 for
    unmatched.  Fixed min(N, M) (or topk) rounds of masked argmax —
    static shapes, lax.fori_loop, vmapped over batch."""
    if threshold is None:
        raise ValueError("bipartite_matching requires threshold")

    def one(s):
        n, m = s.shape
        rounds = min(n, m) if topk < 0 else min(topk, n, m)
        big = jnp.asarray(np.finfo(np.float32).max, jnp.float32)
        sc = s.astype(jnp.float32)
        if is_ascend:
            sc = -sc
            thr = -threshold
        else:
            thr = threshold

        def body(_, carry):
            sc, row, col = carry
            flat = jnp.argmax(sc)
            i, j = flat // m, flat % m
            ok = sc[i, j] >= thr
            row = jnp.where(ok, row.at[i].set(j), row)
            col = jnp.where(ok, col.at[j].set(i), col)
            sc = jnp.where(ok, sc.at[i, :].set(-big).at[:, j].set(-big),
                           sc)
            return sc, row, col

        row0 = jnp.full((n,), -1.0, jnp.float32)
        col0 = jnp.full((m,), -1.0, jnp.float32)
        _, row, col = jax.lax.fori_loop(0, rounds, body, (sc, row0, col0))
        return row, col

    def fn(x):
        return jax.vmap(one)(x)

    res = _apply(fn, [data], "bipartite_matching", nondiff=True)
    return res


__all__ += ["quadratic", "arange_like", "allclose", "div_sqrt_dim",
            "index_copy", "index_array", "gradientmultiplier", "fft",
            "ifft", "AdaptiveAvgPooling2D", "bipartite_matching"]


def hawkesll(lda, alpha, beta, state, lags, marks, valid_length, max_time,
             **kw):
    """Log-likelihood of a multivariate Hawkes process with exponential
    kernels (REF:src/operator/contrib/hawkes_ll.cc).

    lda (N, K): background rates μ; alpha (K,): branching ratios;
    beta (K,): decay rates; state (N, K): the per-mark excitation
    recursion carried across calls (truncated sequences); lags (N, T):
    INTER-ARRIVAL times; marks (N, T) int: event types; valid_length
    (N,): events actually present; max_time (N,): the ABSOLUTE end of
    the observation window measured from this call's origin (t=0) — NOT
    a delta after the last event.  Returns (loglik (N,),
    new_state (N, K)).

    λ_k(t) = μ_k + α_k β_k Σ_{t_j<t, m_j=k} exp(−β_k (t−t_j)); the sum
    rides the standard O(1) per-event recursion — a `lax.scan` over the
    padded event axis (compiler-friendly: no data-dependent trip counts;
    padded steps are masked by valid_length)."""

    def f(lda_, alpha_, beta_, state_, lags_, marks_, vl_, mt_):
        N, K = lda_.shape
        T = lags_.shape[1]
        a = alpha_.astype(jnp.float32)
        b = beta_.astype(jnp.float32)
        mu = lda_.astype(jnp.float32)

        def seq_ll(mu_i, s0, lag_i, mark_i, vl_i, mt_i):
            def step(carry, inp):
                r, ll, t_ = carry            # r: (K,) excitation sums
                lag, mark, idx = inp
                valid = idx < vl_i
                decay = jnp.exp(-b * lag)
                r_dec = r * decay
                lam = mu_i[mark] + a[mark] * b[mark] * r_dec[mark]
                ll = ll + jnp.where(valid, jnp.log(jnp.maximum(lam, 1e-30)),
                                    0.0)
                r_new = r_dec.at[mark].add(1.0)
                r = jnp.where(valid, r_new, r)
                t_ = t_ + jnp.where(valid, lag, 0.0)
                return (r, ll, t_), None

            init = (s0.astype(jnp.float32), jnp.float32(0.0),
                    jnp.float32(0.0))
            (r, ll, t_last), _ = jax.lax.scan(
                step, init,
                (lag_i.astype(jnp.float32), mark_i.astype(jnp.int32),
                 jnp.arange(T)))
            # compensator: ∫_0^{mt} λ_k dt = μ_k·mt + α_k·(r0_k + n_k −
            # r_k·e^{−β_k (mt − t_last)}) — each event (and the carried-in
            # excitation r0) contributes α(1 − e^{−β(mt − t_i)}); the
            # scan's r already holds Σ e^{−β(t_last − t_i)} including the
            # decayed r0, so only the COUNT n_k needs separate masking
            valid_mask = (jnp.arange(T) < vl_i).astype(jnp.float32)
            n_k = (jax.nn.one_hot(mark_i.astype(jnp.int32), K,
                                  dtype=jnp.float32) *
                   valid_mask[:, None]).sum(axis=0)          # (K,)
            tail = jnp.exp(-b * jnp.maximum(mt_i - t_last, 0.0))
            comp = jnp.sum(mu_i * mt_i +
                           a * (s0.astype(jnp.float32) + n_k - r * tail))
            new_state = r * tail  # decay the carry to the horizon
            return ll - comp, new_state

        return jax.vmap(seq_ll)(mu, state_.astype(jnp.float32),
                                lags_, marks_, vl_.astype(jnp.int32),
                                mt_.astype(jnp.float32))

    res = _apply(f, [lda, alpha, beta, state, lags, marks, valid_length,
                     max_time], "hawkesll")
    return res


__all__ += ["hawkesll"]
