"""Vision operator family: ROI pooling/align, proposals, spatial transforms
(REF:src/operator/roi_pooling.cc, contrib/roi_align.cc, contrib/proposal.cc,
bilinear_sampler.cc, grid_generator.cc, spatial_transformer.cc,
contrib/bilinear_resize.cc, nn/upsampling.cc).

TPU-native design: the reference's kernels loop over ROIs/pixels with atomic
scatter; here everything is expressed as dense gathers + weighted sums that
vmap over ROIs/batch and compile to XLA gather/dot — static shapes
throughout (ROI count is fixed per batch, the reference pads the same way).
All ops are differentiable through jax.vjp (the reference hand-wrote each
backward kernel).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .ops import _apply

__all__ = ["ROIPooling", "ROIAlign", "PSROIPooling",
           "DeformablePSROIPooling", "BilinearSampler", "GridGenerator",
           "SpatialTransformer", "BilinearResize2D", "UpSampling",
           "Proposal", "MultiProposal", "Correlation"]


# ---------------------------------------------------------------------------
# bilinear interpolation helper: sample feature map at fractional coords
# ---------------------------------------------------------------------------
def _bilinear_gather(feat, ys, xs, chan=None):
    """Bilinear sampling with true border extension: feat (C, H, W);
    ys/xs fractional pixel coords of any shape.  Coordinates are CLAMPED
    to the image box BEFORE the weights are computed, so an out-of-range
    sample converges exactly to the border value (a blend of border and
    interior rows with weights from the unclipped fractional part is
    wrong — learned deformable offsets routinely leave the image).
    `chan` (int32, broadcastable to ys/xs) switches to channel-indexed
    gathering: each sample reads ONLY its own channel — the
    position-sensitive ops' pattern, with nothing bigger than the sample
    grid materialized."""
    H, W = feat.shape[-2:]
    ys = jnp.clip(ys, 0.0, H - 1.0)
    xs = jnp.clip(xs, 0.0, W - 1.0)
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy1 = ys - y0
    wx1 = xs - x0
    y0i = y0.astype(jnp.int32)
    y1i = jnp.clip(y0i + 1, 0, H - 1)
    x0i = x0.astype(jnp.int32)
    x1i = jnp.clip(x0i + 1, 0, W - 1)
    if chan is None:
        g = lambda yi, xi: feat[:, yi, xi]                  # (C, ...)
    else:
        g = lambda yi, xi: feat[chan, yi, xi]
    return (g(y0i, x0i) * (1 - wy1) * (1 - wx1)
            + g(y0i, x1i) * (1 - wy1) * wx1
            + g(y1i, x0i) * wy1 * (1 - wx1)
            + g(y1i, x1i) * wy1 * wx1)


def _ps_chan(output_dim, k, g):
    """(D, k, k) position-sensitive channel index: out dim d at bin
    (i, j) reads input channel (d·g + gh)·g + gw (REF psroi mapping)."""
    gh = jnp.clip((jnp.arange(k) * g) // k, 0, g - 1)
    d = jnp.arange(output_dim)
    return (d[:, None, None] * g + gh[None, :, None]) * g + \
        gh[None, None, :]


def ROIPooling(data, rois, pooled_size=None, spatial_scale=1.0, **kw):
    """Max-pool each ROI to a fixed grid (REF:src/operator/roi_pooling.cc).
    data: (N, C, H, W); rois: (R, 5) [batch_idx, x1, y1, x2, y2] in image
    coords; out: (R, C, ph, pw)."""
    ph, pw = pooled_size

    def f(x, r):
        H, W = x.shape[-2:]

        def one_roi(roi):
            b = roi[0].astype(jnp.int32)
            feat = x[b]                                     # (C, H, W)
            x1, y1, x2, y2 = [jnp.round(roi[i + 1] * spatial_scale)
                              for i in range(4)]
            roi_h = jnp.maximum(y2 - y1 + 1, 1.0)
            roi_w = jnp.maximum(x2 - x1 + 1, 1.0)
            bin_h = roi_h / ph
            bin_w = roi_w / pw
            # reference quantizes bin borders then max-pools; static-shape
            # version: sample a dense S x S grid per bin and take the max
            S = 4
            gy = (y1 + bin_h * (jnp.arange(ph)[:, None] +
                                (jnp.arange(S)[None, :] + 0.5) / S))  # (ph,S)
            gx = (x1 + bin_w * (jnp.arange(pw)[:, None] +
                                (jnp.arange(S)[None, :] + 0.5) / S))  # (pw,S)
            yi = jnp.clip(jnp.floor(gy).astype(jnp.int32), 0, H - 1)
            xi = jnp.clip(jnp.floor(gx).astype(jnp.int32), 0, W - 1)
            # (C, ph, S, pw, S)
            vals = feat[:, yi[:, :, None, None], xi[None, None, :, :]]
            return vals.max(axis=(2, 4))                    # (C, ph, pw)

        return jax.vmap(one_roi)(r)

    return _apply(f, [data, rois], "ROIPooling")


def ROIAlign(data, rois, pooled_size=None, spatial_scale=1.0, sample_ratio=2,
             position_sensitive=False, **kw):
    """Average of bilinear samples per bin, no quantization
    (REF:src/operator/contrib/roi_align.cc — Mask R-CNN's RoIAlign).
    rois: (R, 5) [batch_idx, x1, y1, x2, y2]."""
    ph, pw = pooled_size
    S = max(int(sample_ratio), 1)

    def f(x, r):
        def one_roi(roi):
            b = roi[0].astype(jnp.int32)
            feat = x[b]
            x1, y1, x2, y2 = [roi[i + 1] * spatial_scale for i in range(4)]
            roi_h = jnp.maximum(y2 - y1, 1.0)
            roi_w = jnp.maximum(x2 - x1, 1.0)
            bin_h = roi_h / ph
            bin_w = roi_w / pw
            gy = y1 + bin_h * (jnp.arange(ph)[:, None] +
                               (jnp.arange(S)[None, :] + 0.5) / S)   # (ph,S)
            gx = x1 + bin_w * (jnp.arange(pw)[:, None] +
                               (jnp.arange(S)[None, :] + 0.5) / S)   # (pw,S)
            if position_sensitive:
                # R-FCN mode (REF roi_align.cc position_sensitive=True):
                # C = output_dim·ph·pw; out channel d at bin (i, j) reads
                # ONLY input channel d·ph·pw + i·pw + j — gather that one
                # channel per bin (not all C then discard ph·pw−1 of them)
                out_dim = feat.shape[0] // (ph * pw)
                d = jnp.arange(out_dim)
                chan = (d[:, None, None] * ph * pw +
                        jnp.arange(ph)[None, :, None] * pw +
                        jnp.arange(pw)[None, None, :])     # (D, ph, pw)
                ys = jnp.broadcast_to(
                    gy[None, :, None, :, None],
                    (out_dim, ph, pw, S, S))
                xs = jnp.broadcast_to(
                    gx[None, None, :, None, :],
                    (out_dim, ph, pw, S, S))
                vals = _bilinear_gather(
                    feat, ys, xs, chan=chan[:, :, :, None, None])
                return vals.mean(axis=(3, 4))              # (D, ph, pw)
            ys = jnp.broadcast_to(gy[:, :, None, None], (ph, S, pw, S))
            xs = jnp.broadcast_to(gx[None, None, :, :], (ph, S, pw, S))
            vals = _bilinear_gather(feat, ys, xs)           # (C, ph,S,pw,S)
            return vals.mean(axis=(2, 4))                   # (C, ph, pw)

        return jax.vmap(one_roi)(r)

    return _apply(f, [data, rois], "ROIAlign")


def PSROIPooling(data, rois, spatial_scale=1.0, output_dim=None,
                 pooled_size=None, group_size=0, **kw):
    """Position-sensitive ROI pooling (REF:src/operator/contrib/
    psroi_pooling.cc — R-FCN's head).  data: (N, output_dim·g·g, H, W);
    rois: (R, 5) [batch_idx, x1, y1, x2, y2] in image coords; out:
    (R, output_dim, k, k) with k = pooled_size.  Out channel d at bin
    (i, j) AVERAGE-pools input channel (d·g + gh)·g + gw where
    (gh, gw) = the bin's group cell — each spatial bin reads its own
    score-map slice.  Static-shape DIVERGENCE from the CUDA kernel: each
    bin is averaged over a fixed S=4×4 floor-sampled grid rather than
    every quantized cell, so bins spanning more than ~4 feature cells
    are a subsample of the reference's average (exact for smaller bins,
    the common R-FCN regime)."""
    k = int(pooled_size)
    g = int(group_size) or k

    def f(x, r):
        H, W = x.shape[-2:]

        def one_roi(roi):
            b = roi[0].astype(jnp.int32)
            feat = x[b]                                    # (C, H, W)
            # reference rounds ROI corners before scaling; end is +1
            x1 = jnp.round(roi[1]) * spatial_scale
            y1 = jnp.round(roi[2]) * spatial_scale
            x2 = jnp.round(roi[3] + 1.0) * spatial_scale
            y2 = jnp.round(roi[4] + 1.0) * spatial_scale
            roi_h = jnp.maximum(y2 - y1, 0.1)
            roi_w = jnp.maximum(x2 - x1, 0.1)
            bin_h, bin_w = roi_h / k, roi_w / k
            S = 4
            gy = y1 + bin_h * (jnp.arange(k)[:, None] +
                               (jnp.arange(S)[None, :] + 0.5) / S)
            gx = x1 + bin_w * (jnp.arange(k)[:, None] +
                               (jnp.arange(S)[None, :] + 0.5) / S)
            yi = jnp.clip(jnp.floor(gy).astype(jnp.int32), 0, H - 1)
            xi = jnp.clip(jnp.floor(gx).astype(jnp.int32), 0, W - 1)
            # gather ONLY each bin's own channel (D, k, k, S, S)
            chan = _ps_chan(output_dim, k, g)              # (D, k, k)
            yi5 = jnp.broadcast_to(yi[None, :, None, :, None],
                                   (output_dim, k, k, S, S))
            xi5 = jnp.broadcast_to(xi[None, None, :, None, :],
                                   (output_dim, k, k, S, S))
            vals = feat[chan[:, :, :, None, None], yi5, xi5]
            return vals.mean(axis=(3, 4))                  # (D, k, k)

        return jax.vmap(one_roi)(r)

    return _apply(f, [data, rois], "PSROIPooling")


def DeformablePSROIPooling(data, rois, trans=None, spatial_scale=1.0,
                           output_dim=None, group_size=1, pooled_size=None,
                           part_size=0, sample_per_part=1, trans_std=0.0,
                           no_trans=False, **kw):
    """Deformable position-sensitive ROI pooling (REF:src/operator/
    contrib/deformable_psroi_pooling.cc, Deformable ConvNets).  Like
    PSROIPooling but each bin's sampling window is shifted by a learned
    normalized offset from `trans` (R, 2·num_cls, part, part), scaled by
    trans_std and the ROI size; samples are BILINEAR (the deformable
    papers' sampler).  no_trans=True (or trans None) runs the undeformed
    bilinear variant.  Divergence from the CUDA kernel: out-of-bounds
    samples are edge-clamped rather than dropped from the average —
    identical for interior ROIs."""
    k = int(pooled_size)
    g = int(group_size) or k
    part = int(part_size) or k
    S = max(int(sample_per_part), 1)
    if not no_trans and trans is None:
        raise ValueError(
            "DeformablePSROIPooling: no_trans=False requires the `trans` "
            "offset input (the reference errors too); pass no_trans=True "
            "for the undeformed variant")
    use_trans = not no_trans and trans is not None

    def f(x, r, *maybe_trans):
        t = maybe_trans[0] if use_trans else None

        def one_roi(roi, troi):
            b = roi[0].astype(jnp.int32)
            feat = x[b]                                    # (C, H, W)
            x1 = jnp.round(roi[1]) * spatial_scale - 0.5
            y1 = jnp.round(roi[2]) * spatial_scale - 0.5
            x2 = jnp.round(roi[3] + 1.0) * spatial_scale - 0.5
            y2 = jnp.round(roi[4] + 1.0) * spatial_scale - 0.5
            roi_h = jnp.maximum(y2 - y1, 0.1)
            roi_w = jnp.maximum(x2 - x1, 0.1)
            bin_h, bin_w = roi_h / k, roi_w / k
            ii = jnp.arange(k)
            # per-bin offsets from the (2·ncls, part, part) trans block
            if use_trans:
                ncls = troi.shape[0] // 2
                ch_per_cls = max(output_dim // ncls, 1)
                pi = jnp.clip((ii * part) // k, 0, part - 1)   # (k,)
                dy = troi[0::2][:, pi[:, None], pi[None, :]]   # (ncls,k,k)
                dx = troi[1::2][:, pi[:, None], pi[None, :]]
                cls_of_d = jnp.arange(output_dim) // ch_per_cls
                off_y = dy[cls_of_d] * trans_std * roi_h       # (D, k, k)
                off_x = dx[cls_of_d] * trans_std * roi_w
            else:
                off_y = jnp.zeros((output_dim, k, k))
                off_x = jnp.zeros((output_dim, k, k))
            sub = (jnp.arange(S) + 0.5) / S
            # sample coords per (D, bin_i, bin_j, si, sj)
            base_y = y1 + ii[:, None] * bin_h + \
                jnp.zeros((k, k))                              # (k, k)
            base_x = x1 + ii[None, :] * bin_w + jnp.zeros((k, k))
            ys = (base_y[None, :, :, None, None] +
                  off_y[:, :, :, None, None] +
                  bin_h * sub[None, None, None, :, None])
            xs = (base_x[None, :, :, None, None] +
                  off_x[:, :, :, None, None] +
                  bin_w * sub[None, None, None, None, :])
            # position-sensitive channel per (D, i, j): sample each bin
            # from ONLY its own channel — no (D, k, k, H, W) intermediate
            chan = _ps_chan(output_dim, k, g)                  # (D, k, k)
            vals = _bilinear_gather(
                feat, ys, xs,
                chan=chan[:, :, :, None, None])            # (D,k,k,S,S)
            return vals.mean(axis=(3, 4))

        if use_trans:
            return jax.vmap(one_roi)(r, t)
        dummy = jnp.zeros((r.shape[0], 2, part, part), x.dtype)
        return jax.vmap(one_roi)(r, dummy)

    args = [data, rois] + ([trans] if use_trans else [])
    return _apply(f, args, "DeformablePSROIPooling")




def GridGenerator(data, transform_type="affine", target_shape=None, **kw):
    """Sampling-grid generation (REF:src/operator/grid_generator.cc).
    affine: data (N, 6) -> grid (N, 2, H, W) of (x, y) in [-1, 1];
    warp: data (N, 2, H, W) flow field -> normalized grid."""
    if transform_type == "affine":
        H, W = target_shape

        def f(theta):
            ys = jnp.linspace(-1.0, 1.0, H)
            xs = jnp.linspace(-1.0, 1.0, W)
            gx, gy = jnp.meshgrid(xs, ys)                    # (H, W)
            ones = jnp.ones_like(gx)
            base = jnp.stack([gx, gy, ones], 0).reshape(3, -1)  # (3, HW)
            t = theta.reshape(-1, 2, 3)
            out = jnp.einsum("nij,jk->nik", t, base)         # (N, 2, HW)
            return out.reshape(-1, 2, H, W)

        return _apply(f, [data], "GridGenerator")

    def f(flow):
        N, _, H, W = flow.shape
        ys = jnp.arange(H, dtype=flow.dtype)
        xs = jnp.arange(W, dtype=flow.dtype)
        gx, gy = jnp.meshgrid(xs, ys)
        px = (flow[:, 0] + gx) * 2.0 / jnp.maximum(W - 1, 1) - 1.0
        py = (flow[:, 1] + gy) * 2.0 / jnp.maximum(H - 1, 1) - 1.0
        return jnp.stack([px, py], 1)

    return _apply(f, [data], "GridGenerator")


def BilinearSampler(data, grid, **kw):
    """Sample `data` at `grid` coords (REF:src/operator/bilinear_sampler.cc —
    STN's sampler).  data: (N, C, H, W); grid: (N, 2, Ho, Wo) with (x, y) in
    [-1, 1]; zero padding outside."""

    def f(x, g):
        N, C, H, W = x.shape

        def one(feat, gr):
            xs = (gr[0] + 1.0) * (W - 1) / 2.0
            ys = (gr[1] + 1.0) * (H - 1) / 2.0
            vals = _bilinear_gather(feat, ys, xs)            # (C, Ho, Wo)
            inside = ((gr[0] >= -1.0) & (gr[0] <= 1.0)
                      & (gr[1] >= -1.0) & (gr[1] <= 1.0))
            return vals * inside[None].astype(vals.dtype)

        return jax.vmap(one)(x, g)

    return _apply(f, [data, grid], "BilinearSampler")


def SpatialTransformer(data, loc, target_shape=None,
                       transform_type="affine", sampler_type="bilinear", **kw):
    """Affine STN = GridGenerator + BilinearSampler fused
    (REF:src/operator/spatial_transformer.cc)."""
    grid = GridGenerator(loc, "affine", target_shape)
    return BilinearSampler(data, grid)


def BilinearResize2D(data, height=None, width=None, scale_height=None,
                     scale_width=None, **kw):
    """Bilinear resize (REF:src/operator/contrib/bilinear_resize.cc) via
    jax.image.resize (XLA gather/dot lowering)."""

    def f(x):
        h = height if height else int(x.shape[2] * scale_height)
        w = width if width else int(x.shape[3] * scale_width)
        return jax.image.resize(x, x.shape[:2] + (h, w), method="linear")

    return _apply(f, [data], "BilinearResize2D")


def UpSampling(*data, scale=2, sample_type="nearest", num_filter=0,
               num_args=1, **kw):
    """Nearest/bilinear upsampling (REF:src/operator/nn/upsampling.cc)."""

    def f(x):
        method = "nearest" if sample_type == "nearest" else "linear"
        return jax.image.resize(
            x, x.shape[:2] + (x.shape[2] * scale, x.shape[3] * scale),
            method=method)

    return _apply(f, [data[0]], "UpSampling")


# ---------------------------------------------------------------------------
# RPN proposals (REF:src/operator/contrib/proposal.cc / multi_proposal.cc)
# ---------------------------------------------------------------------------
def _make_anchors(base_size, ratios, scales):
    """Anchor windows around (0,0) — the reference's generate_anchors."""
    import numpy as np
    base = np.array([0, 0, base_size - 1, base_size - 1], np.float32)
    w = base[2] - base[0] + 1
    h = base[3] - base[1] + 1
    cx = base[0] + 0.5 * (w - 1)
    cy = base[1] + 0.5 * (h - 1)
    anchors = []
    for r in ratios:
        size = w * h
        ws = np.round(np.sqrt(size / r))
        hs = np.round(ws * r)
        for s in scales:
            wss, hss = ws * s, hs * s
            anchors.append([cx - 0.5 * (wss - 1), cy - 0.5 * (hss - 1),
                            cx + 0.5 * (wss - 1), cy + 0.5 * (hss - 1)])
    return jnp.asarray(anchors, jnp.float32)                 # (A, 4)


def Proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2), feature_stride=16,
             output_score=False, iou_loss=False, **kw):
    """RPN proposal generation (REF:src/operator/contrib/proposal.cc):
    anchors + bbox deltas -> clip -> size filter -> top-k -> NMS.  Output is
    the reference's fixed-size (N, post_nms_top_n, 5) ROI tensor ([batch_idx,
    x1, y1, x2, y2]; suppressed rows padded with the top box, scores -1)."""
    from .contrib import box_nms

    def f(scores, deltas, info):
        N, A2, Hf, Wf = scores.shape
        A = A2 // 2
        anchors = _make_anchors(feature_stride, ratios, scales)  # (A, 4)
        sx = jnp.arange(Wf) * feature_stride
        sy = jnp.arange(Hf) * feature_stride
        shift_x, shift_y = jnp.meshgrid(sx, sy)              # (Hf, Wf)
        shifts = jnp.stack([shift_x, shift_y, shift_x, shift_y], -1)
        all_anchors = (anchors[None, None] + shifts[:, :, None]
                       ).reshape(-1, 4)                      # (Hf*Wf*A, 4)

        def one(sc, dl, im):
            fg = sc[A:].transpose(1, 2, 0).reshape(-1)       # (Hf*Wf*A,)
            dx, dy, dw, dh = [dl[i::4].transpose(1, 2, 0).reshape(-1)
                              for i in range(4)]
            aw = all_anchors[:, 2] - all_anchors[:, 0] + 1
            ah = all_anchors[:, 3] - all_anchors[:, 1] + 1
            acx = all_anchors[:, 0] + 0.5 * (aw - 1)
            acy = all_anchors[:, 1] + 0.5 * (ah - 1)
            cx = dx * aw + acx
            cy = dy * ah + acy
            w = jnp.exp(jnp.clip(dw, -10, 10)) * aw
            h = jnp.exp(jnp.clip(dh, -10, 10)) * ah
            x1 = jnp.clip(cx - 0.5 * (w - 1), 0, im[1] - 1)
            y1 = jnp.clip(cy - 0.5 * (h - 1), 0, im[0] - 1)
            x2 = jnp.clip(cx + 0.5 * (w - 1), 0, im[1] - 1)
            y2 = jnp.clip(cy + 0.5 * (h - 1), 0, im[0] - 1)
            min_size = rpn_min_size * im[2]
            keep = ((x2 - x1 + 1 >= min_size) & (y2 - y1 + 1 >= min_size))
            fg_k = jnp.where(keep, fg, -1.0)
            k = min(rpn_pre_nms_top_n, fg_k.shape[0])
            top_sc, top_idx = lax.top_k(fg_k, k)
            boxes = jnp.stack([x1, y1, x2, y2], -1)[top_idx]  # (k, 4)
            det = jnp.concatenate([top_sc[:, None], boxes], -1)  # (k, 5)
            kept = box_nms(det[None], overlap_thresh=threshold,
                           topk=rpn_post_nms_top_n, coord_start=1,
                           score_index=0)
            kept = getattr(kept, "_data", kept)[0]  # raw inside this trace
            out = kept[:rpn_post_nms_top_n]
            # pad suppressed (-1) rows with the best box, as the reference
            # pads with duplicates of box 0
            valid = out[:, 0] >= 0
            best = out[0]
            out = jnp.where(valid[:, None], out, best[None])
            return out[:, 1:5], jnp.where(valid, out[:, 0], -1.0)

        boxes, scores_out = jax.vmap(one)(scores, deltas, info)
        bidx = jnp.broadcast_to(
            jnp.arange(N, dtype=boxes.dtype)[:, None, None],
            boxes.shape[:2] + (1,))
        rois = jnp.concatenate([bidx, boxes], -1)            # (N, top, 5)
        if output_score:
            return rois, scores_out[..., None]
        return rois

    args = [cls_prob, bbox_pred, im_info]
    return _apply(f, args, "Proposal")


MultiProposal = Proposal  # batch-aware already (REF:contrib/multi_proposal.cc)


def Correlation(data1, data2, kernel_size=1, max_displacement=1,
                stride1=1, stride2=1, pad_size=0, is_multiply=True, **kw):
    """FlowNet cost volume (REF:src/operator/correlation.cc).

    out[b, d, y, x] = mean over the K×K kernel window and channels of
    f1·shift(f2, d) for every displacement d in the
    (2·⌊md/stride2⌋+1)² neighborhood.  TPU-native formulation: a STATIC
    python loop over the D² displacements, each an elementwise
    product + channel sum (VPU) and a K×K window sum (reduce_window) —
    no gather/scatter, fully fused by XLA; stride1 subsamples the output
    grid.  is_multiply=False uses |f1 − f2| (the 'subtract' variant)."""
    if kernel_size % 2 != 1:
        raise ValueError("Correlation kernel_size must be odd")

    def f(x1, x2):
        b, c, h, w = x1.shape
        kr = (kernel_size - 1) // 2
        bd = max_displacement + kr                 # border in padded coords
        ph, pw = h + 2 * pad_size, w + 2 * pad_size
        th = int(-(-(ph - 2 * bd) // stride1))     # ceil-div, upstream
        tw = int(-(-(pw - 2 * bd) // stride1))
        if th < 1 or tw < 1:
            raise ValueError("Correlation: displacement/kernel larger "
                             "than the padded input")
        pads = [(0, 0), (0, 0), (pad_size, pad_size), (pad_size, pad_size)]
        p1 = jnp.pad(x1.astype(jnp.float32), pads)
        p2 = jnp.pad(x2.astype(jnp.float32), pads)
        norm = float(kernel_size * kernel_size * c)
        # f1's window is displacement-invariant: slice once.  All starts
        # are static, so plain slicing (not dynamic_slice) suffices; the
        # shifted f2 slices stay in bounds because |d| ≤ md ≤ border.
        y0 = x0 = bd - kr
        ext_h, ext_w = ph - 2 * (bd - kr), pw - 2 * (bd - kr)
        s1 = p1[:, :, y0:y0 + ext_h, x0:x0 + ext_w]
        r = max_displacement // stride2
        disps = range(-r * stride2, r * stride2 + 1, stride2)
        planes = []
        for dy in disps:
            for dx in disps:
                s2 = p2[:, :, y0 + dy:y0 + dy + ext_h,
                        x0 + dx:x0 + dx + ext_w]
                prod = s1 * s2 if is_multiply else jnp.abs(s1 - s2)
                csum = prod.sum(axis=1)            # (B, ext_h, ext_w)
                win = lax.reduce_window(
                    csum, 0.0, lax.add, (1, kernel_size, kernel_size),
                    (1, 1, 1), "valid")  # (B, ph-2bd, pw-2bd): ext-K+1
                # strided rows = ceil((ph-2bd)/stride1) = th exactly
                planes.append(win[:, ::stride1, ::stride1])
        out = jnp.stack(planes, axis=1) / norm     # (B, D², th, tw)
        return out.astype(x1.dtype)

    return _apply(f, [data1, data2], "Correlation")

