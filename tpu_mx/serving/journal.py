"""The committed-token journal: durable stream resumption in O(tokens).

A serving process dies — kill −9, OOM, preemption — and every in-flight
generation it carried is gone with it.  KV state is big but RECOMPUTABLE
(K/V at position p is a pure function of the tokens before it — the
PR-12 purity proof), so the only state worth making durable is the thing
that is NOT recomputable without it: the committed token stream, plus
the sampler RNG capsule for non-greedy modes (serving/sampling.py).
Both are tiny — a few bytes per token — so the journal is an append-only
JSONL file the server fsyncs once per engine step, and recovery is one
``prefill(prompt + committed_tokens)`` per sequence, never a re-decode
(docs/robustness.md "Serving recovery ladder").

Record stream (``<prefix>-journal.jsonl``)::

    {"format": "tpu_mx-serve-journal-v1"}
    {"op": "begin", "request": id, "tenant": t, "prompt": [...],
     "max_new": N, "sampler": <capsule or null>}
    {"op": "token", "request": id, "i": 0, "token": 17,
     "rng": <capsule-after-this-sample or null>}
    ...
    {"op": "end", "request": id, "reason": "length"}

Durability discipline:

- ``begin`` is flushed + fsync'd at admission — an accepted request is
  durable before its handle is returned.
- ``token`` records are buffered and fsync'd ONCE per server step,
  *before* the step returns — so every token a streaming client has
  been handed is already on disk (the step driver yields only after
  ``step()`` returns).  A token lost to a tear was never client-visible.
- ``end`` retires the request; :meth:`compact` rewrites the file without
  retired streams through ``checkpoint.atomic_write`` (tmp + fsync +
  rename — the one crash-safe whole-file commit in the tree).

Recovery semantics (:func:`load`) NEVER guess:

- A torn final line (the only record a crash mid-append can tear) was
  never fsync'd as complete and never client-visible — dropped, loudly.
- Any deeper corruption — a mid-file parse error, a token index gap, a
  token without its ``begin`` — degrades THAT stream (or, for framing
  loss, every stream after the break) to **prompt replay**: committed
  tokens are discarded, the sampler capsule falls back to the
  ``begin``-time state, and the stream re-rolls deterministically from
  the start.  ``fallback`` on the entry (and the server's
  ``serve.replay_fallbacks`` counter) says it happened.
- Duplicate ``begin`` for one id (a recovered process re-admitting) —
  last incarnation wins.
"""
from __future__ import annotations

import json
import logging
import os
import threading

from .. import telemetry as _telemetry
from ..base import MXNetError
from ..checkpoint import atomic_write

__all__ = ["JOURNAL_FORMAT", "TokenJournal", "load", "journal_path"]

log = logging.getLogger(__name__)

JOURNAL_FORMAT = "tpu_mx-serve-journal-v1"


def journal_path(prefix):
    """The journal file a ``Server(journal=prefix)`` appends to."""
    return f"{os.fspath(prefix)}-journal.jsonl"


class TokenJournal:
    """Append-only writer (one per server; see module docstring).

    Thread-safety: ``begin`` runs on submitting threads, ``commit_token``
    / ``end`` / ``flush`` on the step thread — one lock covers the
    buffer and the file handle."""

    def __init__(self, prefix):
        self.path = journal_path(prefix)
        self._lock = threading.Lock()
        self._buf = []
        fresh = not os.path.exists(self.path) \
            or os.path.getsize(self.path) == 0
        self._f = open(self.path, "a", encoding="utf-8")
        if fresh:
            self._append({"format": JOURNAL_FORMAT})
            self._fsync()

    # -- write side ----------------------------------------------------------
    def _append(self, record):
        line = json.dumps(record, separators=(",", ":")) + "\n"
        self._buf.append(line)

    def _fsync(self):
        """Drain the buffer to disk (caller holds the lock or is the
        constructor).  The fsync is the durability boundary — a record
        is only *promised* once this ran after it."""
        if self._buf:
            data = "".join(self._buf)
            self._buf = []
            self._f.write(data)
            _telemetry.counter("serve.journal_bytes").inc(
                len(data.encode("utf-8")))
        self._f.flush()
        os.fsync(self._f.fileno())

    def begin(self, req, sync=True):
        """Journal an admission — durable before the handle returns.
        ``sync=False`` (the legacy-arm re-begin: a ``replay=False``
        requeue re-rolls the stream, so its token indices restart at 0
        and the entry must restart with them — last incarnation wins)
        buffers the record for the next step-boundary flush instead:
        every requeue path flushes before the stream can advance."""
        sampler = getattr(req, "sampler", None)
        with self._lock:
            self._append({"op": "begin", "request": req.id,
                          "tenant": req.tenant,
                          "prompt": list(req.prompt),
                          "max_new": req.max_new_tokens,
                          "sampler": (sampler.state_dict()
                                      if sampler is not None else None)})
            if sync:
                self._fsync()
        if sync:
            # re-begins are incarnations of an already-counted stream —
            # journal_requests stays "streams journaled at admission"
            _telemetry.counter("serve.journal_requests").inc()

    def commit_token(self, req, token):
        """Buffer one committed token (``req.tokens`` already holds it —
        ``i`` is its stream index) plus the sampler state AFTER the
        sample, so a recovered stream continues mid-roll."""
        sampler = getattr(req, "sampler", None)
        with self._lock:
            self._append({"op": "token", "request": req.id,
                          "i": len(req.tokens) - 1, "token": int(token),
                          "rng": (sampler.state_dict()
                                  if sampler is not None else None)})
        _telemetry.counter("serve.journal_tokens").inc()

    def end(self, req, reason):
        with self._lock:
            self._append({"op": "end", "request": req.id,
                          "reason": str(reason)[:120]})

    def flush(self):
        """The once-per-step durability point (module docstring)."""
        with self._lock:
            self._fsync()

    def close(self):
        with self._lock:
            self._fsync()
            self._f.close()

    # -- maintenance ---------------------------------------------------------
    def compact(self):
        """Rewrite the journal without retired streams (atomic_write:
        tmp + fsync + rename), then reopen for append.  Returns the
        number of live streams kept."""
        with self._lock:
            self._fsync()
            self._f.close()
            entries = load(self.path)
            live = {rid: e for rid, e in entries.items()
                    if not e["ended"]}
            with atomic_write(self.path, mode="w") as f:
                f.write(json.dumps({"format": JOURNAL_FORMAT},
                                   separators=(",", ":")) + "\n")
                for rid, e in live.items():
                    f.write(json.dumps(
                        {"op": "begin", "request": rid,
                         "tenant": e["tenant"], "prompt": e["prompt"],
                         "max_new": e["max_new"],
                         "sampler": e["sampler"]},
                        separators=(",", ":")) + "\n")
                    for i, (tok, rng) in enumerate(
                            zip(e["tokens"], e["rngs"])):
                        f.write(json.dumps(
                            {"op": "token", "request": rid, "i": i,
                             "token": tok, "rng": rng},
                            separators=(",", ":")) + "\n")
            self._f = open(self.path, "a", encoding="utf-8")
            return len(live)


def _fresh_entry(rec):
    return {"tenant": rec.get("tenant"),
            "prompt": [int(t) for t in rec.get("prompt", [])],
            "max_new": int(rec.get("max_new", 1)),
            "sampler": rec.get("sampler"),
            "tokens": [], "rngs": [],
            "ended": False, "end_reason": None, "fallback": False}


def load(path):
    """Parse a journal into ``{request_id: entry}`` (module docstring
    for the never-guess rules).  Entry fields: ``prompt`` / ``tenant``
    / ``max_new`` / ``sampler`` (the begin-time capsule) / ``tokens``
    (trusted committed stream) / ``rngs`` (per-token capsules) /
    ``ended`` / ``fallback`` (True = corruption forced this stream to
    prompt replay)."""
    entries = {}
    with open(path, "r", encoding="utf-8") as f:
        lines = f.readlines()
    if not lines:
        return entries
    head = lines[0].strip()
    try:
        fmt = json.loads(head).get("format")
    except (json.JSONDecodeError, AttributeError):
        fmt = None
    if fmt != JOURNAL_FORMAT:
        raise MXNetError(
            f"serve journal {path}: unrecognized format header {head!r} "
            f"(expected {JOURNAL_FORMAT!r}) — refusing to guess")
    corrupt_at = None
    for n, line in enumerate(lines[1:], start=2):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
            op = rec["op"]
        except (json.JSONDecodeError, KeyError, TypeError):
            if n == len(lines):
                # torn final append: never fsync'd complete, never
                # client-visible — drop it and say so
                log.warning("serve journal %s: dropping torn final "
                            "record (line %d)", path, n)
                break
            corrupt_at = n
            break
        if op == "begin":
            # last incarnation wins (a recovered process re-begins)
            entries[rec["request"]] = _fresh_entry(rec)
        elif op == "token":
            e = entries.get(rec["request"])
            if e is None or e["fallback"]:
                if e is None:
                    log.error("serve journal %s: token for unknown "
                              "request %r at line %d — stream lost",
                              path, rec.get("request"), n)
                continue
            if int(rec.get("i", -1)) != len(e["tokens"]):
                log.error(
                    "serve journal %s: token index gap for %s at line "
                    "%d (got i=%s, expected %d) — degrading this "
                    "stream to prompt replay, never guessing",
                    path, rec["request"], n, rec.get("i"),
                    len(e["tokens"]))
                e["tokens"] = []
                e["rngs"] = []
                e["fallback"] = True
                continue
            e["tokens"].append(int(rec["token"]))
            e["rngs"].append(rec.get("rng"))
        elif op == "end":
            e = entries.get(rec["request"])
            if e is not None:
                e["ended"] = True
                e["end_reason"] = rec.get("reason")
    if corrupt_at is not None:
        # framing is lost mid-file: every record after the break is
        # unattributable, so every unfinished stream keeps its identity
        # (begin) but forfeits its committed tokens — prompt replay
        log.error("serve journal %s: unparseable record at line %d — "
                  "degrading ALL %d unfinished stream(s) to prompt "
                  "replay", path, corrupt_at,
                  sum(1 for e in entries.values() if not e["ended"]))
        for e in entries.values():
            if not e["ended"]:
                e["tokens"] = []
                e["rngs"] = []
                e["fallback"] = True
    return entries
