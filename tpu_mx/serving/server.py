"""The request front-end: streams of requests in, supervised decode out.

:class:`Server` turns a stream of generation requests into scheduler and
engine work (docs/serving.md):

- ``submit(prompt, max_new_tokens)`` admits a request (any thread) and
  returns its :class:`~tpu_mx.serving.scheduler.Request` handle, or
  raises :class:`~tpu_mx.serving.scheduler.AdmissionReject` with a
  reason — the bounded-queue backpressure contract.
- ``step()`` runs ONE engine iteration: admit + prefill newly admissible
  requests, decode the running batch one token, evict finished sequences
  immediately.  The caller drives the loop (``run_until_idle()``), which
  keeps the data plane single-threaded and deterministic under a fixed
  seed — the property every serving test and the bench A/B lean on.
- ``stream(prompt, ...)`` submits and yields tokens as they are
  generated, driving ``step()`` underneath.

**Self-healing** (the supervisor's patterns, reused — tpu_mx/supervisor
.py): every engine compute call runs under ``run_with_deadline`` (a hung
decode — chaos ``slow_decode_step``, a wedged dispatch — becomes a
catchable ``WatchdogTimeout``); non-finite logits raise
``NumericDivergence`` exactly like the training sentinel; both are
sorted by ``supervisor.classify`` and anything transient/numeric
triggers a **classified engine restart**: the engine (cache included) is
rebuilt from scratch, every in-flight request is requeued, a black box
is dumped (``blackbox=`` prefix, same flight-recorder format the
training supervisor writes), and a bounded restart budget degrades
gracefully.  Abandoned watchdog threads only ever touch the DISCARDED
engine's private cache (the zombie-step discipline: scheduler, request
handles, and sampler RNG state are mutated exclusively by the caller's
step thread — non-greedy engine steps hand LOGITS back and the sample
runs here, after the watchdog join, so a zombie step can never advance
a journaled RNG and fork a requeued stream).

**Zero-regeneration recovery** (ISSUE 19, docs/robustness.md "Serving
recovery ladder"): a requeued request keeps its committed tokens — the
in-memory token ledger — and the rebuilt engine re-establishes it with
ONE ``prefill(prompt + committed)`` call instead of re-decoding token by
token, so recovery cost is flat in generation length and greedy (or
journaled-RNG sampled) streams are bit-identical to the uninterrupted
run, re-yielding nothing.  ``TPUMX_PREFILL_REPLAY=0`` (or ``replay=
False``) selects the legacy prompt-replay arm for A/B.  ``journal=``
arms the durable half: every admission and committed token is fsync'd
to an append-only JSONL journal (tpu_mx/serving/journal.py) — once per
step, BEFORE tokens become client-visible — so a new process can
``recover()`` every stream after a kill −9 with zero lost, duplicated,
or re-yielded tokens.  ``drain()`` / ``handoff()`` are the planned
twins: stop admission and quiesce, or migrate every live session to a
fresh engine generation at a step boundary — zero client-visible
failures, no restart budget spent.  The degrade path reuses the same
machinery: budget exhaustion fails QUEUED work loudly but migrates the
running batch onto one final generation and drains it — mid-stream work
fails only if the fault strikes again during that drain.

Trace context: each step stamps ``step``/``generation`` (engine
generation = restart count) and per-request work stamps ``request`` —
the serving analog of the training step context, so a slow request's
black box reconstructs its admit → prefill → decode → evict timeline
(docs/observability.md).
"""
from __future__ import annotations

import logging
import os
import time

import numpy as np

from ..base import MXNetError
from .. import telemetry as _telemetry
from .. import tracing as _tracing
from ..supervisor import classify, run_with_deadline
from .engine import EngineCore
from .journal import TokenJournal, load as _journal_load
from .kv_cache import CacheExhausted
from .sampling import fold_seed, make_sampler, parse_sampling
from .scheduler import ContinuousBatchingScheduler, Request
from .slo import SLOMonitor
from .tenancy import label_for

__all__ = ["Server"]

log = logging.getLogger(__name__)


class Server:
    """See module docstring.

    ``model`` implements the decode protocol (tpu_mx/serving/model.py);
    ``scheduler`` defaults to a :class:`ContinuousBatchingScheduler`
    built from ``max_pending``/``max_batch``/``max_tokens``;
    ``block_size``/``num_blocks`` size the paged cache; ``deadline``
    arms the hung-step watchdog (seconds, None = off); ``max_restarts``
    bounds the self-healing budget; ``blackbox`` (a path prefix) arms
    the crash black box; ``eos_id`` optionally ends generation early.

    Recovery knobs (ISSUE 19): ``journal=`` (a path prefix) arms the
    durable committed-token journal — ``recover()`` in a NEW process
    resumes every unfinished stream from it; ``sampling=`` picks the
    decode mode (``"greedy"`` default, or ``"top_k:K"`` — non-greedy
    pins the fused/speculative arms off, since both sample greedily);
    ``sampling_seed=`` is the base seed each request's private RNG is
    folded from; ``replay=`` overrides the ``TPUMX_PREFILL_REPLAY``
    resolution (True = prefill replay on restarts, False = the legacy
    prompt-replay arm)."""

    def __init__(self, model, *, scheduler=None, max_pending=64,
                 max_batch=8, max_tokens=8192, block_size=16,
                 num_blocks=256, deadline=None, max_restarts=3,
                 backoff=0.05, blackbox=None, eos_id=None, slo=None,
                 tenants=None, prefix_sharing=None, dtype=np.float32,
                 journal=None, sampling="greedy", sampling_seed=0,
                 replay=None):
        self.model = model
        # the live SLO monitor (tpu_mx/serving/slo.py): True arms the
        # default targets, a list/tuple of spec strings builds a monitor
        # from them, or pass a configured SLOMonitor.  Refreshed every
        # step; its signal is published to scheduler.slo_signal (the
        # fairness hook) and force-refreshed before every black-box dump
        # so a restart's box carries the fault-time SLO window state.
        if slo is True:
            slo = SLOMonitor()
        elif not slo:
            slo = None   # False/()/[] all mean unarmed, same as None
        elif isinstance(slo, str):
            slo = SLOMonitor((slo,))
        elif isinstance(slo, (list, tuple)):
            slo = SLOMonitor(slo)
        elif not isinstance(slo, SLOMonitor):
            raise TypeError(f"slo= takes True, spec string(s), or an "
                            f"SLOMonitor — got {type(slo).__name__}")
        self.slo = slo
        # multi-tenant policy (ISSUE 12): `tenants` is anything
        # TenantTable.coerce accepts; `prefix_sharing` pins the shared-
        # prefix KV reuse knob (None = the TPUMX_PREFIX_SHARING env
        # resolution).  Both thread through engine restarts — a rebuilt
        # engine keeps the data-plane contract it degraded under.
        self.scheduler = scheduler if scheduler is not None else \
            ContinuousBatchingScheduler(max_pending=max_pending,
                                        max_batch=max_batch,
                                        max_tokens=max_tokens,
                                        tenants=tenants)
        self._block_size = int(block_size)
        self._num_blocks = int(num_blocks)
        self._dtype = dtype
        self._prefix_sharing = prefix_sharing
        self.deadline = deadline
        self.max_restarts = int(max_restarts)
        self.backoff = float(backoff)
        self.blackbox = blackbox
        self.eos_id = eos_id
        # recovery plane (ISSUE 19): sampling mode is a SERVER property
        # (one decode path per server, resolved once like the engine
        # arms), the replay arm resolves env-default-on, and the journal
        # opens (and fsyncs its header) before any request is admitted
        self._sampling_kind, self._sampling_k = parse_sampling(sampling)
        self._sampling_seed = int(sampling_seed)
        if replay is None:
            replay = os.environ.get("TPUMX_PREFILL_REPLAY", "1") != "0"
        self.replay = bool(replay)
        self.journal = TokenJournal(journal) if journal else None
        self._draining = False
        self.engine = self._new_engine()
        self.generation = 0        # engine generation (restart count)
        self.restarts = 0
        self.degraded = False
        self._steps = 0
        self._tokens_generated = 0
        self._t_first_work = None
        # tenant labels the pool-bytes gauges have published: a tenant
        # whose bytes drop to zero must be zeroed, not left frozen at
        # its last nonzero attribution (ISSUE 14)
        self._pool_tenants_seen = set()
        # capacity-publish throttle: (used_blocks at last publish,
        # monotonic time of it) — the full ledger walk runs only when
        # the pool actually moved or the refresh interval elapsed,
        # mirroring the SLO monitor's own rate limit
        self._cap_published = None

    def _new_engine(self):
        """Build one engine generation (construction, restart, handoff,
        the degraded drain): the rebuilt engine keeps every data-plane
        contract it degraded under — sharing knob, forensics, warm
        batch, and the greedy/sampled pin (non-greedy sampling forces
        the fused and speculative arms off)."""
        return EngineCore(self.model, block_size=self._block_size,
                          num_blocks=self._num_blocks, dtype=self._dtype,
                          share_prefix=self._prefix_sharing,
                          forensics=self.blackbox,
                          warm_batch=getattr(self.scheduler,
                                             "max_batch", None),
                          greedy=self._sampling_kind == "greedy")

    def _sampler_for(self, req):
        """The request's private sampler (None for greedy): seeded by
        folding the request id into the server's base seed, so a
        recovered process rebuilds the SAME sampler for the same id
        before loading its journaled state."""
        return make_sampler(self._sampling_kind, self._sampling_k,
                            fold_seed(self._sampling_seed, req.id))

    # -- admission (any thread) ----------------------------------------------
    def submit(self, prompt, max_new_tokens=16, request_id=None,
               tenant=None):
        """Admit one request; returns its handle or raises
        :class:`AdmissionReject` (reason on the exception — resubmit
        later; ``tenant_quota`` means THIS tenant is over its caps).
        ``tenant`` names the submitting tenant (fairness/quota identity
        + bounded telemetry label; None = the default tenant).  A
        degraded server rejects everything; a draining one rejects with
        ``"draining"`` until :meth:`resume_admission`."""
        req = Request(prompt, max_new_tokens, request_id=request_id,
                      tenant=tenant)
        req.tenant_weight = self.scheduler.tenants.get(req.tenant).weight
        req.sampler = self._sampler_for(req)
        # all server-side gates route through the scheduler's ONE
        # reject implementation, so a degraded-window, draining, or
        # oversized submit is counted and lands on the timeline like
        # any other
        if self.degraded:
            self.scheduler.reject(req, "degraded",
                                  "restart budget exhausted; server is "
                                  "in degraded shutdown")
        if self._draining:
            self.scheduler.reject(req, "draining",
                                  "server is quiescing for drain/"
                                  "handoff; resubmit after admission "
                                  "reopens")
        # a request whose WORST CASE can never fit the block pool would
        # preempt-loop forever — reject it at the door with the reason
        need = self.engine.cache.blocks_for(req.budget_tokens)
        if need > self._num_blocks:
            self.scheduler.reject(
                req, "request_too_large",
                f"prompt+max_new needs {need} cache blocks > pool of "
                f"{self._num_blocks}")
        if self.journal is not None:
            # fsync'd BEFORE the request becomes schedulable: the
            # any-thread-submit model lets a concurrently-stepping
            # driver prefill and buffer token records the moment
            # scheduler.submit returns, and load() treats a token
            # without its begin as a lost stream — so the begin must
            # already be on disk.  A crash between here and the first
            # token still recovers the stream (prompt-only replay).
            self.journal.begin(req)
        try:
            return self.scheduler.submit(req)
        except BaseException:
            if self.journal is not None:
                # the entry was journaled but admission refused it:
                # retire it durably so a recovering successor never
                # resurrects (and generates) a request whose client was
                # told it was rejected
                self.journal.end(req, "rejected")
                self.journal.flush()
            raise

    # -- the engine loop (one driver thread) ---------------------------------
    def step(self):
        """One engine iteration (admit → prefill → decode → evict).
        Returns True when any work was done.  Transient/numeric faults
        restart the engine in place; fatal ones propagate.  A degraded
        server still steps while its migrated running batch drains —
        only an IDLE degraded server refuses to step."""
        if self.degraded and self.scheduler.idle():
            raise MXNetError("serving: server is degraded — no further "
                             "steps will run")
        self._steps += 1
        _tracing.set_context(step=self._steps, generation=self.generation)
        try:
            return self._step_guarded()
        except BaseException as e:  # noqa: BLE001 — classified below
            kind = classify(e)
            if kind == "fatal":
                raise
            if self.degraded:
                # a SECOND fault during the degraded drain: the budget
                # is spent and there is no next generation — fail the
                # remaining in-flight work loudly instead of looping
                self._fail_inflight(
                    f"degraded: fault during degraded drain "
                    f"({type(e).__name__}: {e})"[:300])
                return True
            self._restart(e)
            return True

    def _step_guarded(self):
        worked = False
        # --- admit + prefill (split prefill queue) -------------------------
        admits = self.scheduler.take_prefills()
        for i, req in enumerate(admits):
            if self._t_first_work is None:
                self._t_first_work = time.perf_counter()
            _tracing.set_context(request=req.id)
            req.timeline.mark_prefill_start()
            try:
                first, cached = run_with_deadline(
                    lambda r=req: self.engine.prefill(r),
                    self.deadline, name=f"serve-prefill-{req.id}")
            except CacheExhausted:
                # backpressure: this request (and the rest of this
                # step's admissions) goes back to the queue front — a
                # DEFER, not a requeue: none of them started, so nothing
                # is reset or counted — and the step FALLS THROUGH to
                # decode, whose progress (and evictions) is what will
                # free the blocks; an early return here would starve
                # decode and livelock.  Attribution: the bounced attempt
                # (and the wait until its retry) is a defer_stall; the
                # admissions behind it never started — their wait keeps
                # its label until the stall begins
                req.timeline.mark_prefill_failed()
                for later in admits[i + 1:]:
                    later.timeline.mark_defer()
                self.scheduler.defer(admits[i:])
                _tracing.set_context(request=None)
                break
            except BaseException:
                # engine fault mid-prefill (numeric divergence, wedged
                # deadline): take_prefills() already popped this step's
                # admissions and the restart path only requeues RUNNING
                # requests — put them back before the classified
                # restart or they are silently lost (state "queued" in
                # neither queue; wait() hangs forever).  The faulting
                # request pays a requeue (its destroyed attempt is
                # restart_penalty); the ones behind it never started
                # and keep accruing queue wait.
                self.scheduler.defer(admits[i + 1:])
                self.scheduler.requeue(req, front=True,
                                       replay=self.replay)
                self._journal_requeue([req])
                raise
            finally:
                _tracing.set_context(request=None)
            req.timeline.mark_prefill_end(cached_tokens=cached)
            self.scheduler.mark_running(req)
            if req.sampler is not None:
                # the engine hands LOGITS back for sampled requests:
                # the sample runs HERE, on the driver thread, after the
                # watchdog join — an abandoned zombie prefill can never
                # advance the journaled RNG
                first = req.sampler.sample(first)
            self._commit_token(req, first)
            worked = True
        # --- decode (one step across the running batch: one token per
        # sequence, or an accepted speculative window) -----------------------
        batch = self.scheduler.decode_batch()
        if batch:
            if self._t_first_work is None:
                self._t_first_work = time.perf_counter()
            items = [(r, r.tokens[-1] if r.tokens else r.prompt[-1])
                     for r in batch]
            t0 = time.perf_counter()
            results, preempted = run_with_deadline(
                lambda: self.engine.decode(items), self.deadline,
                name=f"serve-decode-step{self._steps}")
            fresh = 0
            for req in batch:
                tokens = results.get(req.id)
                if tokens is None or req.done:
                    continue   # preempted, or a static-padding slot
                if isinstance(tokens, np.ndarray):
                    # a sampled row came back as LOGITS: the sample runs
                    # here on the driver thread, after the watchdog
                    # join, so a zombie decode step can never advance
                    # the journaled RNG (zombie-step discipline)
                    tokens = [req.sampler.sample(tokens)]
                # a step yields a LIST (one token, or an accepted
                # speculative window); commit in stream order and stop
                # at the first finisher — tokens past an EOS or the
                # length budget were never part of the stream (the
                # sequence's cache is evicted with it either way)
                for token in tokens:
                    fresh += 1
                    self._commit_token(req, token)
                    if req.done:
                        break
            for req in preempted:
                # a FINISHED victim was a static-batching padding slot:
                # its tokens were already delivered, so it is simply
                # dropped from the books — requeueing it would corrupt a
                # done handle and re-decode a completed request
                done_padding = req.done
                _tracing.set_context(request=req.id)
                _tracing.emit("serve.evict", request=req.id,
                              reason="padding" if done_padding
                              else "preempted",
                              generated=len(req.tokens))
                _tracing.set_context(request=None)
                if done_padding:
                    self.scheduler.discard(req)
                else:
                    self.scheduler.requeue(req, front=True,
                                           replay=self.replay)
                    self._journal_requeue([req])
            _telemetry.counter("serve.decode_steps").inc()
            _tracing.emit("serve.decode", batch=len(items), tokens=fresh,
                          t0=t0, t1=time.perf_counter())
            worked = True
        if self.journal is not None:
            # the once-per-step durability point: every token committed
            # this step hits disk BEFORE step() returns — and stream()
            # only yields after step() returns, so every client-visible
            # token is journaled
            self.journal.flush()
        self._update_gauges()
        return worked

    def _journal_requeue(self, reqs):
        """Legacy-arm journal consistency: a ``replay=False`` requeue
        discards the ledger (``reset_generation``), so the re-rolled
        stream journals token records from ``i=0`` again while the file
        already holds higher indices for the request — which load()'s
        index-gap check would misread as corruption and degrade to
        prompt replay.  Re-begin each entry (last-incarnation-wins),
        capturing the sampler's post-reset capsule — exactly the state
        the re-roll consumes.  Buffered, not fsync'd: every requeue
        path flushes before the stream can advance.  No-op on the
        replay arm, where the ledger (and its indices) survive."""
        if self.journal is None or self.replay:
            return
        for req in reqs:
            self.journal.begin(req, sync=False)

    def _commit_token(self, req, token):
        """Record one generated token and finish/evict when done."""
        req.record_token(token)
        self._tokens_generated += 1
        _telemetry.counter("serve.generated_tokens").inc()
        if self.journal is not None:
            # buffered, not fsync'd: the step-end flush() is the
            # durability point (one fsync per step, not per token)
            self.journal.commit_token(req, token)
        done_len = len(req.tokens) >= req.max_new_tokens
        done_eos = self.eos_id is not None and int(token) == self.eos_id
        if done_len or done_eos:
            reason = "eos" if done_eos else "length"
            if self.journal is not None:
                self.journal.end(req, reason)
            for ev in self.scheduler.finish(req, reason):
                self._evict(ev)

    def _evict(self, req):
        """Free a finished sequence's cache immediately (continuous
        batching's whole point) and close out its telemetry."""
        self.engine.evict(req)
        _telemetry.counter("serve.requests", state="completed").inc()
        _tracing.set_context(request=req.id)
        _tracing.emit("serve.evict", request=req.id,
                      reason=req.finish_reason or "length",
                      generated=len(req.tokens))
        _tracing.set_context(request=None)

    def _update_gauges(self):
        _telemetry.gauge("serve.cache_utilization").set(
            self.engine.cache.utilization())
        _telemetry.gauge("serve.pool_device_resident").set(
            float(self.engine.cache.device_resident))
        _telemetry.gauge("serve.queue_depth").set(
            self.scheduler.queue_depth())
        if self._t_first_work is not None:
            dt = time.perf_counter() - self._t_first_work
            if dt > 0:
                _telemetry.gauge("serve.tokens_per_sec").set(
                    self._tokens_generated / dt)
        self._publish_capacity()
        if self.slo is not None:
            # rate-limited inside the monitor; the signal lands on the
            # scheduler for admission policies that weigh it
            self.scheduler.slo_signal = self.slo.refresh()

    def _publish_capacity(self):
        """Publish the capacity ledger live (ISSUE 14): the pool-state
        gauges, the per-tenant amortized/exclusive byte attribution
        (bounded labels — tenancy.label_for; two tenants collapsed into
        the overflow label are SUMMED, preserving the accounting
        identity), and the scheduler's ``capacity_signal`` hook — the
        would-fit data admission consults before popping a prefill that
        can only bounce (the symmetric twin of ``slo_signal``).

        Throttled like the SLO monitor's refresh: the full ledger walk
        (holders + tenants + trie reclaimable + free-list sort) runs
        only when the pool's used-block count moved since the last
        publish or 0.25 s elapsed — a steady decode loop pays one O(1)
        counter read per step, not an O(pool + trie) scan."""
        used_now = self.engine.cache.allocator.used
        now = time.monotonic()
        if self._cap_published is not None:
            last_used, last_t = self._cap_published
            if used_now == last_used and now - last_t < 0.25:
                return
        self._cap_published = (used_now, now)
        cap = self.engine.cache.capacity_stats()
        _telemetry.gauge("serve.pool_used_bytes").set(
            float(cap["used_bytes"]))
        _telemetry.gauge("serve.pool_fragmentation").set(
            cap["fragmentation"])
        _telemetry.gauge("serve.pool_high_watermark_bytes").set(
            float(cap["high_watermark_bytes"]))
        _telemetry.gauge("serve.prefix_index_bytes").set(
            float(cap["index_bytes"]))
        _telemetry.gauge("serve.pool_pinned_blocks").set(
            float(cap["pinned_blocks"]))
        by_label = {}
        for tenant, d in cap["tenants"].items():
            # ledger pseudo-tenants (_index and friends) are bounded by
            # construction and keep their names; client-controlled ids
            # go through the cardinality cap
            label = tenant if tenant.startswith("_") else label_for(tenant)
            acc = by_label.setdefault(label, [0.0, 0.0])
            acc[0] += d["bytes_amortized"]
            acc[1] += float(d["bytes_exclusive"])
        for label, (amortized, exclusive) in by_label.items():
            _telemetry.gauge("serve.pool_bytes", tenant=label,
                             kind="amortized").set(amortized)
            _telemetry.gauge("serve.pool_bytes", tenant=label,
                             kind="exclusive").set(exclusive)
        for label in self._pool_tenants_seen - set(by_label):
            _telemetry.gauge("serve.pool_bytes", tenant=label,
                             kind="amortized").set(0.0)
            _telemetry.gauge("serve.pool_bytes", tenant=label,
                             kind="exclusive").set(0.0)
        self._pool_tenants_seen |= set(by_label)
        self.scheduler.capacity_signal = {
            "num_blocks": cap["num_blocks"],
            "block_size": cap["block_size"],
            "block_bytes": cap["block_bytes"],
            "used_blocks": cap["used_blocks"],
            "free_blocks": cap["free_blocks"],
            "free_bytes": cap["free_blocks"] * cap["block_bytes"],
            "reclaimable_blocks": cap["reclaimable_blocks"],
        }

    @property
    def slo_signal(self):
        """The SLO monitor's latest signal dict, or None when no
        monitor is armed (the hook the fleet-scale fairness item
        consumes — see tpu_mx/serving/slo.py).  A property, matching
        ``scheduler.slo_signal``'s attribute access — one name, one
        access style on both surfaces."""
        return self.slo.signal() if self.slo is not None else None

    @property
    def capacity_signal(self):
        """The latest capacity ledger signal published to the
        scheduler (``_publish_capacity``), or None before the first
        step — the symmetric twin of :attr:`slo_signal`."""
        return self.scheduler.capacity_signal

    # -- self-healing --------------------------------------------------------
    def _swap_engine(self):
        """Advance to a fresh engine generation (restart, handoff, the
        degraded drain).  The old engine — and any watchdog thread
        still wedged inside it — is garbage from here: threads touching
        its private cache mutate nothing the new generation reads.  The
        rebuilt pool starts empty, so the stale would-fit signal (and
        stale pool gauges) must not gate admission on the DEAD pool."""
        self.generation += 1
        _tracing.set_context(generation=self.generation)
        self.engine = self._new_engine()
        self.scheduler.capacity_signal = None
        self._cap_published = None

    def _restart(self, err):
        """Classified engine restart: fresh engine + cache, every
        in-flight request requeued (ONE replay prefill re-establishes
        its committed tokens — or a full prompt re-run on the legacy
        arm), black box dumped; budget exhaustion degrades — queued
        requests are failed loudly, never silently lost."""
        self.restarts += 1
        reason = f"{type(err).__name__}: {err}"[:300]
        log.warning("serving: engine fault (%s) — restart %d/%d",
                    reason, self.restarts, self.max_restarts)
        if self.restarts > self.max_restarts:
            self._degrade(err)
            return
        requeued = self.scheduler.requeue_all_running(replay=self.replay)
        self._journal_requeue(requeued)
        if self.journal is not None:
            # tokens the faulted step committed before the fault are
            # real (record_token ran; stream() may yield them) — make
            # them durable with the restart instead of waiting for the
            # next clean step boundary
            self.journal.flush()
        _telemetry.counter("serve.engine_restarts").inc()
        # serve.restart lands under the FAILING step's (step, generation)
        # context — the injection->decision correlation the serve CI tier
        # asserts; only then does the context advance to the new
        # generation, so the fresh engine's serve.decode_path event is
        # stamped with the generation it will actually run as
        _tracing.emit("serve.restart", n=self.restarts, reason=reason,
                      requeued=len(requeued))
        self._swap_engine()
        self._dump_blackbox(f"serving engine restart "
                            f"{self.restarts}/{self.max_restarts}: "
                            f"{reason}")
        _telemetry.flush()
        if self.backoff:
            time.sleep(min(30.0, self.backoff * 2 ** (self.restarts - 1)))

    def _degrade(self, err):
        """Restart budget exhausted: admission closes and QUEUED
        requests fail loudly — but the running batch is not abandoned.
        It migrates (the same replay path a restart uses) onto one
        final engine generation and drains to completion under
        ``step()``'s degraded-drain mode, so budget exhaustion fails
        only queued, never mid-stream, work.  A further fault during
        that drain fails the remainder (``_fail_inflight``)."""
        self.degraded = True
        reason = (f"degraded: restart budget exhausted "
                  f"({type(err).__name__}: {err})")[:300]
        log.error("serving: %s", reason)
        # drain the QUEUE, don't requeue it: these requests are being
        # FAILED, so a requeue would both double-count them as
        # "requeued" and leave each one processed twice
        failed = self.scheduler.drain_pending()
        for req in failed:
            req.fail(reason)
            if self.journal is not None:
                self.journal.end(req, "degraded")
        requeued = self.scheduler.requeue_all_running(replay=self.replay)
        self._journal_requeue(requeued)
        _tracing.emit("serve.drain", kind="degrade",
                      inflight=len(requeued), pending=len(failed))
        if self.journal is not None:
            self.journal.flush()
        if requeued:
            self._swap_engine()
        self._dump_blackbox(reason)
        _telemetry.flush()

    def _fail_inflight(self, reason):
        """Terminal: fail everything still queued or running (a second
        fault inside the degraded drain — no generation left to
        migrate to)."""
        log.error("serving: %s", reason)
        failed = self.scheduler.drain_running()
        failed.extend(self.scheduler.drain_pending())
        for req in failed:
            req.fail(reason)
            if self.journal is not None:
                self.journal.end(req, "failed")
        if self.journal is not None:
            self.journal.flush()
        self._dump_blackbox(reason)
        _telemetry.flush()

    def _dump_blackbox(self, reason):
        if not self.blackbox:
            return None
        if self.slo is not None:
            # capture the fault-time SLO window state in the box's
            # telemetry snapshot (bypassing the refresh rate limit);
            # box-less servers skip it — the per-step refresh keeps the
            # gauges fresh within the rate limit anyway
            try:
                self.scheduler.slo_signal = self.slo.refresh(force=True)
            except Exception as slo_err:  # noqa: BLE001 — best effort
                log.warning("serving: SLO refresh at black-box time "
                            "failed: %s", slo_err)
        try:
            return _tracing.dump_blackbox(self.blackbox, reason=reason)
        except Exception as dump_err:  # noqa: BLE001 — best effort
            log.warning("serving: black-box dump failed: %s", dump_err)
            return None

    # -- planned maintenance: drain / handoff / recover (ISSUE 19) -----------
    def drain(self, max_steps=1_000_000):
        """Graceful drain: admission closes (new submits reject with
        reason ``"draining"``) and the loop runs until every admitted
        request completes — quiescing at decode-step boundaries with
        zero client-visible failures.  Admission stays closed
        afterwards (:meth:`resume_admission` reopens it); returns the
        number of steps the drain took.  :meth:`handoff` is the
        live-migration sibling that never stops serving."""
        self._draining = True
        _tracing.emit("serve.drain", kind="drain",
                      inflight=self.scheduler.running_count(),
                      pending=self.scheduler.queue_depth())
        return self.run_until_idle(max_steps)

    def resume_admission(self):
        """Reopen admission after :meth:`drain`."""
        self._draining = False

    def handoff(self):
        """Hot engine handoff: quiesce at the current decode-step
        boundary (the single driver thread owns it — call between
        ``step()``s) and migrate every live session onto a fresh engine
        generation via ONE replay prefill each.  A planned upgrade: no
        restart budget spent, no backoff, no black box, nothing
        re-yielded — greedy/journaled streams continue bit-identically.
        Returns the number of migrated sessions."""
        requeued = self.scheduler.requeue_all_running(replay=self.replay)
        self._journal_requeue(requeued)
        if self.journal is not None:
            self.journal.flush()
        _tracing.emit("serve.drain", kind="handoff",
                      inflight=len(requeued),
                      pending=self.scheduler.queue_depth())
        self._swap_engine()
        log.info("serving: handoff to generation %d (%d live sessions "
                 "migrated)", self.generation, len(requeued))
        return len(requeued)

    def recover(self):
        """Resume every unfinished stream from the journal — the
        cross-process half of zero-regeneration recovery (a kill −9'd
        server's successor calls this once before stepping).  Each live
        journal entry becomes a Request with its committed tokens
        pre-loaded as the in-memory ledger and its sampler restored
        from the last per-token RNG capsule; the next step re-
        establishes it with ONE ``prefill(prompt + committed)`` and the
        stream continues exactly where the dead process left it.  A
        torn/corrupt entry degrades LOUDLY to prompt replay (tokens
        dropped, ``serve.replay_fallbacks`` counted) — never guesses.
        Returns ``{request_id: Request}``."""
        if self.journal is None:
            raise MXNetError("serving: recover() needs Server("
                             "journal=...) — there is no journal to "
                             "recover from")
        out = {}
        for rid, entry in _journal_load(self.journal.path).items():
            if entry["ended"]:
                continue
            req = Request(entry["prompt"], entry["max_new"],
                          request_id=rid, tenant=entry["tenant"])
            req.tenant_weight = \
                self.scheduler.tenants.get(req.tenant).weight
            req.sampler = self._sampler_for(req)
            if entry["fallback"]:
                _telemetry.counter("serve.replay_fallbacks").inc()
                log.error("serving: journal entry for %s was torn/"
                          "corrupt — recovering from the prompt "
                          "(committed tokens dropped, stream restarts "
                          "from scratch)", rid)
            if req.sampler is not None:
                # the RNG capsule after the LAST committed token (or
                # the admission-time state when none committed yet)
                state = (entry["rngs"][-1] if entry["rngs"]
                         else entry["sampler"])
                if state is not None:
                    req.sampler.load_state_dict(state)
            if entry["tokens"]:
                req.tokens = [int(t) for t in entry["tokens"]]
            if len(req.tokens) >= req.max_new_tokens:
                # the stream finished but its end record died with the
                # process: retire it here — re-admitting would decode
                # past the length budget
                self.journal.end(req, "length")
                req.finish("length")
                out[rid] = req
                continue
            # gate-bypassing re-admission (scheduler.restore, the same
            # cap bypass requeue/defer use): the dead process already
            # admitted this request — its journaled begin is the
            # admission receipt — and a server killed at full load
            # journals up to max_pending + max_batch unfinished
            # streams, so routing recovery back through submit() would
            # queue_full-reject the overflow, abort the remaining
            # streams, and break the zero-lost-streams guarantee.
            # server.submit would also journal a fresh begin and
            # rebuild a fresh sampler — this request CONTINUES its
            # existing journal entry (token indices stay contiguous
            # with what is already on disk).
            self.scheduler.restore(req)
            out[rid] = req
        if self.journal is not None:
            self.journal.flush()
        return out

    # -- drivers -------------------------------------------------------------
    def run_until_idle(self, max_steps=1_000_000):
        """Drive ``step()`` until no request is pending or running;
        returns the number of steps taken."""
        from ..contrib import chaos as _chaos
        _chaos.configure_from_env()   # arm TPUMX_CHAOS faults, like run()
        n = 0
        while not self.scheduler.idle():
            if n >= max_steps:
                raise MXNetError(
                    f"serving: run_until_idle exceeded {max_steps} steps "
                    "with work still queued — wedged scheduler?")
            self.step()
            n += 1
        _telemetry.flush()
        return n

    def stream(self, prompt, max_new_tokens=16, request_id=None):
        """Submit and yield tokens as they are generated (drives the
        engine loop from the consuming thread)."""
        req = self.submit(prompt, max_new_tokens, request_id=request_id)
        seen = 0
        guard = 0
        while True:
            # on the prefill-replay arm an engine restart KEEPS
            # req.tokens (the ledger survives; nothing to re-yield).
            # On the legacy arm a restart resets req.tokens and re-runs
            # from the prompt; greedy decode is deterministic, so the
            # regenerated prefix matches what was already yielded —
            # wait for the length to catch back up to `seen` instead of
            # re-yielding.  Either way the cursor only moves forward.
            while seen < len(req.tokens):
                yield req.tokens[seen]
                seen += 1
            if req.done:
                if req.state == "failed":
                    raise MXNetError(
                        f"serving: request {req.id} failed: "
                        f"{req.finish_reason}")
                return
            guard += 1
            if guard > 1_000_000:
                raise MXNetError("serving: stream wedged — no progress")
            self.step()
