"""The request front-end: streams of requests in, supervised decode out.

:class:`Server` turns a stream of generation requests into scheduler and
engine work (docs/serving.md):

- ``submit(prompt, max_new_tokens)`` admits a request (any thread) and
  returns its :class:`~tpu_mx.serving.scheduler.Request` handle, or
  raises :class:`~tpu_mx.serving.scheduler.AdmissionReject` with a
  reason — the bounded-queue backpressure contract.
- ``step()`` runs ONE engine iteration: admit + prefill newly admissible
  requests, decode the running batch one token, evict finished sequences
  immediately.  The caller drives the loop (``run_until_idle()``), which
  keeps the data plane single-threaded and deterministic under a fixed
  seed — the property every serving test and the bench A/B lean on.
- ``stream(prompt, ...)`` submits and yields tokens as they are
  generated, driving ``step()`` underneath.

**Self-healing** (the supervisor's patterns, reused — tpu_mx/supervisor
.py): every engine compute call runs under ``run_with_deadline`` (a hung
decode — chaos ``slow_decode_step``, a wedged dispatch — becomes a
catchable ``WatchdogTimeout``); non-finite logits raise
``NumericDivergence`` exactly like the training sentinel; both are
sorted by ``supervisor.classify`` and anything transient/numeric
triggers a **classified engine restart**: the engine (cache included) is
rebuilt from scratch, every in-flight request is requeued and re-runs
from its prompt, a black box is dumped (``blackbox=`` prefix, same
flight-recorder format the training supervisor writes), and a bounded
restart budget degrades gracefully — queued requests are failed with a
reason, never silently lost.  Abandoned watchdog threads only ever touch
the DISCARDED engine's private cache (the zombie-step discipline:
scheduler and request handles are mutated exclusively by the caller's
step thread).

Trace context: each step stamps ``step``/``generation`` (engine
generation = restart count) and per-request work stamps ``request`` —
the serving analog of the training step context, so a slow request's
black box reconstructs its admit → prefill → decode → evict timeline
(docs/observability.md).
"""
from __future__ import annotations

import logging
import time

import numpy as np

from ..base import MXNetError
from .. import telemetry as _telemetry
from .. import tracing as _tracing
from ..supervisor import classify, run_with_deadline
from .engine import EngineCore
from .kv_cache import CacheExhausted
from .scheduler import ContinuousBatchingScheduler, Request
from .slo import SLOMonitor
from .tenancy import label_for

__all__ = ["Server"]

log = logging.getLogger(__name__)


class Server:
    """See module docstring.

    ``model`` implements the decode protocol (tpu_mx/serving/model.py);
    ``scheduler`` defaults to a :class:`ContinuousBatchingScheduler`
    built from ``max_pending``/``max_batch``/``max_tokens``;
    ``block_size``/``num_blocks`` size the paged cache; ``deadline``
    arms the hung-step watchdog (seconds, None = off); ``max_restarts``
    bounds the self-healing budget; ``blackbox`` (a path prefix) arms
    the crash black box; ``eos_id`` optionally ends generation early."""

    def __init__(self, model, *, scheduler=None, max_pending=64,
                 max_batch=8, max_tokens=8192, block_size=16,
                 num_blocks=256, deadline=None, max_restarts=3,
                 backoff=0.05, blackbox=None, eos_id=None, slo=None,
                 tenants=None, prefix_sharing=None, dtype=np.float32):
        self.model = model
        # the live SLO monitor (tpu_mx/serving/slo.py): True arms the
        # default targets, a list/tuple of spec strings builds a monitor
        # from them, or pass a configured SLOMonitor.  Refreshed every
        # step; its signal is published to scheduler.slo_signal (the
        # fairness hook) and force-refreshed before every black-box dump
        # so a restart's box carries the fault-time SLO window state.
        if slo is True:
            slo = SLOMonitor()
        elif not slo:
            slo = None   # False/()/[] all mean unarmed, same as None
        elif isinstance(slo, str):
            slo = SLOMonitor((slo,))
        elif isinstance(slo, (list, tuple)):
            slo = SLOMonitor(slo)
        elif not isinstance(slo, SLOMonitor):
            raise TypeError(f"slo= takes True, spec string(s), or an "
                            f"SLOMonitor — got {type(slo).__name__}")
        self.slo = slo
        # multi-tenant policy (ISSUE 12): `tenants` is anything
        # TenantTable.coerce accepts; `prefix_sharing` pins the shared-
        # prefix KV reuse knob (None = the TPUMX_PREFIX_SHARING env
        # resolution).  Both thread through engine restarts — a rebuilt
        # engine keeps the data-plane contract it degraded under.
        self.scheduler = scheduler if scheduler is not None else \
            ContinuousBatchingScheduler(max_pending=max_pending,
                                        max_batch=max_batch,
                                        max_tokens=max_tokens,
                                        tenants=tenants)
        self._block_size = int(block_size)
        self._num_blocks = int(num_blocks)
        self._dtype = dtype
        self._prefix_sharing = prefix_sharing
        self.deadline = deadline
        self.max_restarts = int(max_restarts)
        self.backoff = float(backoff)
        self.blackbox = blackbox
        self.eos_id = eos_id
        self.engine = EngineCore(model, block_size=block_size,
                                 num_blocks=num_blocks, dtype=dtype,
                                 share_prefix=prefix_sharing,
                                 forensics=blackbox,
                                 warm_batch=getattr(self.scheduler,
                                                    "max_batch", None))
        self.generation = 0        # engine generation (restart count)
        self.restarts = 0
        self.degraded = False
        self._steps = 0
        self._tokens_generated = 0
        self._t_first_work = None
        # tenant labels the pool-bytes gauges have published: a tenant
        # whose bytes drop to zero must be zeroed, not left frozen at
        # its last nonzero attribution (ISSUE 14)
        self._pool_tenants_seen = set()
        # capacity-publish throttle: (used_blocks at last publish,
        # monotonic time of it) — the full ledger walk runs only when
        # the pool actually moved or the refresh interval elapsed,
        # mirroring the SLO monitor's own rate limit
        self._cap_published = None

    # -- admission (any thread) ----------------------------------------------
    def submit(self, prompt, max_new_tokens=16, request_id=None,
               tenant=None):
        """Admit one request; returns its handle or raises
        :class:`AdmissionReject` (reason on the exception — resubmit
        later; ``tenant_quota`` means THIS tenant is over its caps).
        ``tenant`` names the submitting tenant (fairness/quota identity
        + bounded telemetry label; None = the default tenant).  A
        degraded server rejects everything."""
        req = Request(prompt, max_new_tokens, request_id=request_id,
                      tenant=tenant)
        req.tenant_weight = self.scheduler.tenants.get(req.tenant).weight
        # both server-side gates route through the scheduler's ONE
        # reject implementation, so a degraded-window or oversized
        # submit is counted and lands on the timeline like any other
        if self.degraded:
            self.scheduler.reject(req, "degraded",
                                  "restart budget exhausted; server is "
                                  "in degraded shutdown")
        # a request whose WORST CASE can never fit the block pool would
        # preempt-loop forever — reject it at the door with the reason
        need = self.engine.cache.blocks_for(req.budget_tokens)
        if need > self._num_blocks:
            self.scheduler.reject(
                req, "request_too_large",
                f"prompt+max_new needs {need} cache blocks > pool of "
                f"{self._num_blocks}")
        return self.scheduler.submit(req)

    # -- the engine loop (one driver thread) ---------------------------------
    def step(self):
        """One engine iteration (admit → prefill → decode → evict).
        Returns True when any work was done.  Transient/numeric faults
        restart the engine in place; fatal ones propagate."""
        if self.degraded:
            raise MXNetError("serving: server is degraded — no further "
                             "steps will run")
        self._steps += 1
        _tracing.set_context(step=self._steps, generation=self.generation)
        try:
            return self._step_guarded()
        except BaseException as e:  # noqa: BLE001 — classified below
            kind = classify(e)
            if kind == "fatal":
                raise
            self._restart(e)
            return True

    def _step_guarded(self):
        worked = False
        # --- admit + prefill (split prefill queue) -------------------------
        admits = self.scheduler.take_prefills()
        for i, req in enumerate(admits):
            if self._t_first_work is None:
                self._t_first_work = time.perf_counter()
            _tracing.set_context(request=req.id)
            req.timeline.mark_prefill_start()
            try:
                first, cached = run_with_deadline(
                    lambda r=req: self.engine.prefill(r),
                    self.deadline, name=f"serve-prefill-{req.id}")
            except CacheExhausted:
                # backpressure: this request (and the rest of this
                # step's admissions) goes back to the queue front — a
                # DEFER, not a requeue: none of them started, so nothing
                # is reset or counted — and the step FALLS THROUGH to
                # decode, whose progress (and evictions) is what will
                # free the blocks; an early return here would starve
                # decode and livelock.  Attribution: the bounced attempt
                # (and the wait until its retry) is a defer_stall; the
                # admissions behind it never started — their wait keeps
                # its label until the stall begins
                req.timeline.mark_prefill_failed()
                for later in admits[i + 1:]:
                    later.timeline.mark_defer()
                self.scheduler.defer(admits[i:])
                _tracing.set_context(request=None)
                break
            except BaseException:
                # engine fault mid-prefill (numeric divergence, wedged
                # deadline): take_prefills() already popped this step's
                # admissions and the restart path only requeues RUNNING
                # requests — put them back before the classified
                # restart or they are silently lost (state "queued" in
                # neither queue; wait() hangs forever).  The faulting
                # request pays a requeue (its destroyed attempt is
                # restart_penalty); the ones behind it never started
                # and keep accruing queue wait.
                self.scheduler.defer(admits[i + 1:])
                self.scheduler.requeue(req, front=True)
                raise
            finally:
                _tracing.set_context(request=None)
            req.timeline.mark_prefill_end(cached_tokens=cached)
            self.scheduler.mark_running(req)
            self._commit_token(req, first)
            worked = True
        # --- decode (one step across the running batch: one token per
        # sequence, or an accepted speculative window) -----------------------
        batch = self.scheduler.decode_batch()
        if batch:
            if self._t_first_work is None:
                self._t_first_work = time.perf_counter()
            items = [(r, r.tokens[-1] if r.tokens else r.prompt[-1])
                     for r in batch]
            t0 = time.perf_counter()
            results, preempted = run_with_deadline(
                lambda: self.engine.decode(items), self.deadline,
                name=f"serve-decode-step{self._steps}")
            fresh = 0
            for req in batch:
                tokens = results.get(req.id)
                if tokens is None or req.done:
                    continue   # preempted, or a static-padding slot
                # a step yields a LIST (one token, or an accepted
                # speculative window); commit in stream order and stop
                # at the first finisher — tokens past an EOS or the
                # length budget were never part of the stream (the
                # sequence's cache is evicted with it either way)
                for token in tokens:
                    fresh += 1
                    self._commit_token(req, token)
                    if req.done:
                        break
            for req in preempted:
                # a FINISHED victim was a static-batching padding slot:
                # its tokens were already delivered, so it is simply
                # dropped from the books — requeueing it would corrupt a
                # done handle and re-decode a completed request
                done_padding = req.done
                _tracing.set_context(request=req.id)
                _tracing.emit("serve.evict", request=req.id,
                              reason="padding" if done_padding
                              else "preempted",
                              generated=len(req.tokens))
                _tracing.set_context(request=None)
                if done_padding:
                    self.scheduler.discard(req)
                else:
                    self.scheduler.requeue(req, front=True)
            _telemetry.counter("serve.decode_steps").inc()
            _tracing.emit("serve.decode", batch=len(items), tokens=fresh,
                          t0=t0, t1=time.perf_counter())
            worked = True
        self._update_gauges()
        return worked

    def _commit_token(self, req, token):
        """Record one generated token and finish/evict when done."""
        req.record_token(token)
        self._tokens_generated += 1
        _telemetry.counter("serve.generated_tokens").inc()
        done_len = len(req.tokens) >= req.max_new_tokens
        done_eos = self.eos_id is not None and int(token) == self.eos_id
        if done_len or done_eos:
            reason = "eos" if done_eos else "length"
            for ev in self.scheduler.finish(req, reason):
                self._evict(ev)

    def _evict(self, req):
        """Free a finished sequence's cache immediately (continuous
        batching's whole point) and close out its telemetry."""
        self.engine.evict(req)
        _telemetry.counter("serve.requests", state="completed").inc()
        _tracing.set_context(request=req.id)
        _tracing.emit("serve.evict", request=req.id,
                      reason=req.finish_reason or "length",
                      generated=len(req.tokens))
        _tracing.set_context(request=None)

    def _update_gauges(self):
        _telemetry.gauge("serve.cache_utilization").set(
            self.engine.cache.utilization())
        _telemetry.gauge("serve.pool_device_resident").set(
            float(self.engine.cache.device_resident))
        _telemetry.gauge("serve.queue_depth").set(
            self.scheduler.queue_depth())
        if self._t_first_work is not None:
            dt = time.perf_counter() - self._t_first_work
            if dt > 0:
                _telemetry.gauge("serve.tokens_per_sec").set(
                    self._tokens_generated / dt)
        self._publish_capacity()
        if self.slo is not None:
            # rate-limited inside the monitor; the signal lands on the
            # scheduler for admission policies that weigh it
            self.scheduler.slo_signal = self.slo.refresh()

    def _publish_capacity(self):
        """Publish the capacity ledger live (ISSUE 14): the pool-state
        gauges, the per-tenant amortized/exclusive byte attribution
        (bounded labels — tenancy.label_for; two tenants collapsed into
        the overflow label are SUMMED, preserving the accounting
        identity), and the scheduler's ``capacity_signal`` hook — the
        would-fit data admission consults before popping a prefill that
        can only bounce (the symmetric twin of ``slo_signal``).

        Throttled like the SLO monitor's refresh: the full ledger walk
        (holders + tenants + trie reclaimable + free-list sort) runs
        only when the pool's used-block count moved since the last
        publish or 0.25 s elapsed — a steady decode loop pays one O(1)
        counter read per step, not an O(pool + trie) scan."""
        used_now = self.engine.cache.allocator.used
        now = time.monotonic()
        if self._cap_published is not None:
            last_used, last_t = self._cap_published
            if used_now == last_used and now - last_t < 0.25:
                return
        self._cap_published = (used_now, now)
        cap = self.engine.cache.capacity_stats()
        _telemetry.gauge("serve.pool_used_bytes").set(
            float(cap["used_bytes"]))
        _telemetry.gauge("serve.pool_fragmentation").set(
            cap["fragmentation"])
        _telemetry.gauge("serve.pool_high_watermark_bytes").set(
            float(cap["high_watermark_bytes"]))
        _telemetry.gauge("serve.prefix_index_bytes").set(
            float(cap["index_bytes"]))
        _telemetry.gauge("serve.pool_pinned_blocks").set(
            float(cap["pinned_blocks"]))
        by_label = {}
        for tenant, d in cap["tenants"].items():
            # ledger pseudo-tenants (_index and friends) are bounded by
            # construction and keep their names; client-controlled ids
            # go through the cardinality cap
            label = tenant if tenant.startswith("_") else label_for(tenant)
            acc = by_label.setdefault(label, [0.0, 0.0])
            acc[0] += d["bytes_amortized"]
            acc[1] += float(d["bytes_exclusive"])
        for label, (amortized, exclusive) in by_label.items():
            _telemetry.gauge("serve.pool_bytes", tenant=label,
                             kind="amortized").set(amortized)
            _telemetry.gauge("serve.pool_bytes", tenant=label,
                             kind="exclusive").set(exclusive)
        for label in self._pool_tenants_seen - set(by_label):
            _telemetry.gauge("serve.pool_bytes", tenant=label,
                             kind="amortized").set(0.0)
            _telemetry.gauge("serve.pool_bytes", tenant=label,
                             kind="exclusive").set(0.0)
        self._pool_tenants_seen |= set(by_label)
        self.scheduler.capacity_signal = {
            "num_blocks": cap["num_blocks"],
            "block_size": cap["block_size"],
            "block_bytes": cap["block_bytes"],
            "used_blocks": cap["used_blocks"],
            "free_blocks": cap["free_blocks"],
            "free_bytes": cap["free_blocks"] * cap["block_bytes"],
            "reclaimable_blocks": cap["reclaimable_blocks"],
        }

    @property
    def slo_signal(self):
        """The SLO monitor's latest signal dict, or None when no
        monitor is armed (the hook the fleet-scale fairness item
        consumes — see tpu_mx/serving/slo.py).  A property, matching
        ``scheduler.slo_signal``'s attribute access — one name, one
        access style on both surfaces."""
        return self.slo.signal() if self.slo is not None else None

    @property
    def capacity_signal(self):
        """The latest capacity ledger signal published to the
        scheduler (``_publish_capacity``), or None before the first
        step — the symmetric twin of :attr:`slo_signal`."""
        return self.scheduler.capacity_signal

    # -- self-healing --------------------------------------------------------
    def _restart(self, err):
        """Classified engine restart: fresh engine + cache, every
        in-flight request requeued (re-runs from its prompt), black box
        dumped; budget exhaustion degrades — queued requests are failed
        loudly, never silently lost."""
        self.restarts += 1
        reason = f"{type(err).__name__}: {err}"[:300]
        log.warning("serving: engine fault (%s) — restart %d/%d",
                    reason, self.restarts, self.max_restarts)
        if self.restarts > self.max_restarts:
            self._degrade(err)
            return
        requeued = self.scheduler.requeue_all_running()
        _telemetry.counter("serve.engine_restarts").inc()
        # serve.restart lands under the FAILING step's (step, generation)
        # context — the injection->decision correlation the serve CI tier
        # asserts; only then does the context advance to the new
        # generation, so the fresh engine's serve.decode_path event is
        # stamped with the generation it will actually run as
        _tracing.emit("serve.restart", n=self.restarts, reason=reason,
                      requeued=len(requeued))
        self.generation += 1
        _tracing.set_context(generation=self.generation)
        # the old engine (and any watchdog thread still wedged inside
        # it) is garbage from here: threads touching its private cache
        # mutate nothing the new generation reads
        self.engine = EngineCore(self.model, block_size=self._block_size,
                                 num_blocks=self._num_blocks,
                                 dtype=self._dtype,
                                 share_prefix=self._prefix_sharing,
                                 forensics=self.blackbox,
                                 warm_batch=getattr(self.scheduler,
                                                    "max_batch", None))
        # the rebuilt engine's pool starts empty: the stale would-fit
        # signal (and the stale pool gauges) refresh on the next step,
        # but the scheduler must not gate admission on the DEAD pool
        self.scheduler.capacity_signal = None
        self._cap_published = None
        self._dump_blackbox(f"serving engine restart "
                            f"{self.restarts}/{self.max_restarts}: "
                            f"{reason}")
        _telemetry.flush()
        if self.backoff:
            time.sleep(min(30.0, self.backoff * 2 ** (self.restarts - 1)))

    def _degrade(self, err):
        """Restart budget exhausted: fail every queued + running request
        with a reason (the client sees it; nothing hangs forever)."""
        self.degraded = True
        reason = (f"degraded: restart budget exhausted "
                  f"({type(err).__name__}: {err})")[:300]
        log.error("serving: %s", reason)
        # drain, don't requeue: these requests are being FAILED, so a
        # requeue would both double-count them as "requeued" and leave
        # each one processed twice
        failed = self.scheduler.drain_running()
        failed.extend(self.scheduler.drain_pending())
        for req in failed:
            req.fail(reason)
        self._dump_blackbox(reason)
        _telemetry.flush()

    def _dump_blackbox(self, reason):
        if not self.blackbox:
            return None
        if self.slo is not None:
            # capture the fault-time SLO window state in the box's
            # telemetry snapshot (bypassing the refresh rate limit);
            # box-less servers skip it — the per-step refresh keeps the
            # gauges fresh within the rate limit anyway
            try:
                self.scheduler.slo_signal = self.slo.refresh(force=True)
            except Exception as slo_err:  # noqa: BLE001 — best effort
                log.warning("serving: SLO refresh at black-box time "
                            "failed: %s", slo_err)
        try:
            return _tracing.dump_blackbox(self.blackbox, reason=reason)
        except Exception as dump_err:  # noqa: BLE001 — best effort
            log.warning("serving: black-box dump failed: %s", dump_err)
            return None

    # -- drivers -------------------------------------------------------------
    def run_until_idle(self, max_steps=1_000_000):
        """Drive ``step()`` until no request is pending or running;
        returns the number of steps taken."""
        from ..contrib import chaos as _chaos
        _chaos.configure_from_env()   # arm TPUMX_CHAOS faults, like run()
        n = 0
        while not self.scheduler.idle():
            if n >= max_steps:
                raise MXNetError(
                    f"serving: run_until_idle exceeded {max_steps} steps "
                    "with work still queued — wedged scheduler?")
            self.step()
            n += 1
        _telemetry.flush()
        return n

    def stream(self, prompt, max_new_tokens=16, request_id=None):
        """Submit and yield tokens as they are generated (drives the
        engine loop from the consuming thread)."""
        req = self.submit(prompt, max_new_tokens, request_id=request_id)
        seen = 0
        guard = 0
        while True:
            # an engine restart resets req.tokens and re-runs from the
            # prompt; greedy decode is deterministic, so the regenerated
            # prefix matches what was already yielded — wait for the
            # length to catch back up to `seen` instead of re-yielding
            while seen < len(req.tokens):
                yield req.tokens[seen]
                seen += 1
            if req.done:
                if req.state == "failed":
                    raise MXNetError(
                        f"serving: request {req.id} failed: "
                        f"{req.finish_reason}")
                return
            guard += 1
            if guard > 1_000_000:
                raise MXNetError("serving: stream wedged — no progress")
            self.step()
