"""EngineCore: model + paged cache = prefill/decode compute (no policy).

One engine generation owns one :class:`~tpu_mx.serving.kv_cache.
PagedKVCache` and runs two operations for the server:

- :meth:`prefill` — one sequence's whole prompt: the model computes every
  layer's K/V (flash kernel on supported TPU shapes), the cache is
  bulk-filled in one call, and the first generated token comes back.
- :meth:`decode` — ONE token for a whole batch of sequences: reserve the
  O(1) cache slot per sequence, then interleave the model's layer loop
  with per-layer batched cache writes and block-table attention
  (``decode_attention`` — the paged kernel or the dense-gather reference
  arm, picked ONCE per engine generation from ``TPUMX_PAGED_DECODE`` so
  a restarted engine's black box records which path it was on via the
  ``serve.decode_path`` event; docs/DIVERGENCES.md #27).  A paged engine
  builds its cache with ``storage="device"`` — the pool lives on the
  accelerator and decode never round-trips it through the host.
  Sequences whose slot reservation hits :class:`CacheExhausted` are
  returned as *preempted* — the scheduler requeues them; the rest of
  the batch proceeds.  Never OOM.

Fault surface (what the server's watchdog/sentinel wrap): the chaos
``slow_decode_step`` injection fires at the top of :meth:`decode` —
INSIDE the server's watchdog thread, like ``hang_step`` does for the
training supervisor — and the logits-health scalar routes through
``chaos.poison_loss`` so ``nan_after`` can poison a decode step
deterministically.  Non-finite logits raise
:class:`~tpu_mx.supervisor.NumericDivergence`, the same exception class
the training sentinel escalates with, so ``supervisor.classify`` sorts
serving faults with the training rules unchanged.

The engine is DISPOSABLE: an engine restart builds a fresh EngineCore
(new cache, same model weights) and the old one — possibly still being
mutated by an abandoned watchdog thread — is garbage.  That is the whole
zombie-step story for serving: hung threads only ever touch a dead
engine's private state, never the scheduler or the request handles
(tpu_mx/serving/server.py).
"""
from __future__ import annotations

import math
import time

import numpy as np

from .. import tracing as _tracing
from ..contrib import chaos as _chaos
from ..supervisor import NumericDivergence
from .attention import decode_attention, resolve_decode_path
from .kv_cache import CacheExhausted, PagedKVCache, prefix_sharing_enabled

__all__ = ["EngineCore"]


class EngineCore:
    """See module docstring.  ``model`` implements the decode protocol
    (tpu_mx/serving/model.py); cache geometry comes from it."""

    def __init__(self, model, block_size=16, num_blocks=256,
                 dtype=np.float32, share_prefix=None, forensics=None):
        self.model = model
        # the decode arm is resolved ONCE per engine generation: a knob
        # flip mid-flight cannot leave half a batch on each path, and
        # the serve.decode_path event below is the black box's record of
        # which arm a (possibly restarted) engine was on.  The sharing
        # knob resolves the same way (TPUMX_PREFIX_SHARING unless pinned
        # by the caller) and rides the same event for the same reason.
        self.decode_kind = resolve_decode_path()
        if share_prefix is None:
            share_prefix = prefix_sharing_enabled()
        self.share_prefix = bool(share_prefix)
        storage = "device" if self.decode_kind != "dense" else "host"
        self.cache = PagedKVCache(
            model.num_layers, model.num_heads, model.head_dim,
            block_size=block_size, num_blocks=num_blocks, dtype=dtype,
            storage=storage, share_prefix=self.share_prefix,
            forensics=forensics)
        _tracing.emit("serve.decode_path", path=self.decode_kind,
                      storage=storage, sharing=self.share_prefix)

    # -- prefill -------------------------------------------------------------
    def prefill(self, req):
        """Run ``req``'s prompt, fill its cache blocks, return ``(first
        generated token, cached_tokens)``.

        With sharing on, the longest indexed full-block prefix of the
        prompt is served from the cache (``cached_tokens`` of them):
        only the suffix's K/V is computed (``model.prefill_suffix``
        attending over the cached prefix) and written — bit-identical
        logits to a full prefill, one prefill's compute shared by every
        request carrying the template.  :class:`CacheExhausted`
        propagates with the cache unchanged and no pinned references
        left behind (the scheduler's backpressure path); NaN/Inf logits
        raise :class:`NumericDivergence`."""
        t0 = time.perf_counter()
        tokens = req.prompt
        # the capacity ledger's attribution key (ISSUE 14): requests
        # without a tenant (bare tests) fall to the single-tenant default
        tenant = getattr(req, "tenant", None)
        plan = self.cache.match_prefix(tokens, tenant=tenant)
        if plan is not None:
            cached = plan.tokens_matched
            try:
                kp, vp = self.cache.gather_plan(plan)
                k, v, logits = self.model.prefill_suffix(
                    tokens[cached:], cached, kp, vp)
            except BaseException:
                # model/gather fault between match and commit: the pins
                # must not outlive the attempt (the audit counts them)
                self.cache.abandon_plan(plan)
                raise
            self.cache.commit_prefill(req.id, plan, k, v, tokens,
                                      tenant=tenant)
        else:
            cached = 0
            k, v, logits = self.model.prefill(tokens)
            self.cache.prefill(req.id, k, v,
                               tokens=tokens if self.share_prefix
                               else None, tenant=tenant)
        health = float(np.max(np.abs(logits)))
        if not math.isfinite(health):
            raise NumericDivergence(
                f"serving: non-finite logits in prefill of {req.id} "
                f"(health={health}) — restarting the engine")
        _tracing.emit("serve.prefill", request=req.id,
                      tokens=len(req.prompt), cached=cached, t0=t0,
                      t1=time.perf_counter())
        return int(np.argmax(logits)), cached

    # -- decode --------------------------------------------------------------
    def decode(self, items):
        """One token for each ``(req, last_token)`` in ``items``.

        Returns ``(results, preempted)``: ``results`` maps request id →
        next token for every sequence that decoded; ``preempted`` lists
        the requests evicted to make room — the scheduler requeues them
        (re-run), the rest of the batch proceeds.  Raises
        :class:`NumericDivergence` on non-finite logits (real or
        chaos-poisoned).

        Preemption picks FINISHED batch members first (static-batching
        padding slots — their cache is pure waste and their handles are
        already done), then the unfinished not-yet-reserved member
        scoring worst on (tenant weight ascending, exclusively-held
        blocks descending, youngest): a low-weight tenant's sequence is
        sacrificed before a high-weight one's, and between peers the
        victim whose eviction actually RETURNS the most blocks goes
        first — freeing a sequence whose blocks are shared releases
        references, not memory (refcounts: the survivors keep reading
        the same bits, so preemption can never evict a block another
        live sequence shares).  The reservation is retried after each
        eviction, so the oldest live sequence always makes progress and
        an over-admitted batch drains instead of livelocking on mutual
        preemption (``items`` arrive in admission order from the
        scheduler)."""
        _chaos.maybe_slow_decode()
        live, preempted = [], []
        remaining = [(req, int(last)) for req, last in items]
        while remaining:
            req, last = remaining.pop(0)
            while True:
                try:
                    self.cache.reserve(req.id)
                    live.append((req, last))
                    break
                except CacheExhausted:
                    # backpressure, never OOM: free a victim's blocks
                    # (an unfinished victim re-runs from its prompt
                    # later) and retry
                    victim = None
                    for j in range(len(remaining) - 1, -1, -1):
                        if remaining[j][0].done:
                            victim = remaining.pop(j)[0]
                            break
                    if victim is None and remaining:
                        victim = remaining.pop(
                            self._pick_victim(remaining))[0]
                    if victim is None:
                        victim = req
                    self.cache.free_sequence(victim.id)
                    preempted.append(victim)
                    if victim is req:
                        break
        if not live:
            return {}, preempted
        tokens = np.array([t for _, t in live], np.int64)
        # the reserved slot IS the new token's position (length - 1)
        positions = np.array(
            [self.cache.length(r.id) - 1 for r, _ in live], np.int64)
        seq_ids = [r.id for r, _ in live]
        h = self.model.embed(tokens, positions)
        # block tables are layer-invariant within a step (the slots were
        # reserved above): build them once, not once per layer
        batch = (self.cache.batch_tables(seq_ids)
                 if self.decode_kind != "dense" else None)
        for i in range(self.model.num_layers):
            q, k, v = self.model.layer_qkv(i, h)
            self.cache.write_batch(seq_ids, i, k, v)
            attn = decode_attention(q, self.cache, seq_ids, i,
                                    kind=self.decode_kind, batch=batch)
            h = self.model.layer_combine(i, h, attn)
        logits = self.model.logits(h)
        health = _chaos.poison_loss(float(np.max(np.abs(logits))))
        if not math.isfinite(health):
            raise NumericDivergence(
                f"serving: non-finite logits in decode batch of "
                f"{len(live)} (health={health}) — restarting the engine")
        out = np.argmax(logits, axis=-1)
        return ({req.id: int(out[b]) for b, (req, _) in enumerate(live)},
                preempted)

    def _pick_victim(self, remaining):
        """Index into ``remaining`` of the preemption victim: lowest
        tenant weight first (SLO-weighted fairness extends to who gets
        sacrificed under memory pressure), then the sequence whose
        eviction returns the MOST exclusively-held blocks (evicting a
        fully shared prefix frees nothing), youngest breaking ties
        (matching the pre-tenancy youngest-first drain guarantee).
        Requests without a tenant weight (bare tests) count as 1.0."""
        best_j, best_key = len(remaining) - 1, None
        for j in range(len(remaining) - 1, -1, -1):
            req = remaining[j][0]
            excl = (self.cache.exclusive_blocks(req.id)
                    if self.cache.has_sequence(req.id) else 0)
            key = (-float(getattr(req, "tenant_weight", 1.0)), excl, j)
            if best_key is None or key > best_key:
                best_j, best_key = j, key
        return best_j

    def evict(self, req):
        """Free a sequence's blocks (idempotent)."""
        return self.cache.free_sequence(req.id)
