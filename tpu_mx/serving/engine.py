"""EngineCore: model + paged cache = prefill/decode compute (no policy).

One engine generation owns one :class:`~tpu_mx.serving.kv_cache.
PagedKVCache` and runs two operations for the server:

- :meth:`prefill` — one sequence's whole prompt: the model computes every
  layer's K/V (flash kernel on supported TPU shapes), the cache is
  bulk-filled in one call, and the first generated token comes back.
- :meth:`decode` — ONE decode step for a whole batch of sequences:
  reserve each sequence's draft-window slots (``reserve_window`` — the
  O(1) append, window width from ``TPUMX_SPECULATIVE``), then run the
  window forward through one of two arms picked ONCE per engine
  generation (recorded on the ``serve.decode_path`` event so a
  restarted engine's black box says which path it was on):

  * **host-resident** (default): the model's numpy layer loop
    interleaved with per-layer batched cache writes and block-table
    attention (``decode_attention`` — the paged kernel or the
    dense-gather reference arm per ``TPUMX_PAGED_DECODE``;
    docs/DIVERGENCES.md #27).
  * **fused** (``TPUMX_FUSED_DECODE=1`` on a paged engine): the ENTIRE
    step — embed, every layer, paged attention, logits, sampling — is
    one jitted device program with donated pool buffers
    (serving/jax_model.py); only sampled token ids cross back.

  With speculation on, the proposer drafts ``K-1`` tokens, the step
  verifies the whole window in one batched call, and each row's
  agreeing prefix is accepted (rejected tail slots truncated) — greedy
  streams bit-identical speculative on/off (serving/speculative.py).
  A paged engine builds its cache with ``storage="device"`` — the pool
  lives on the accelerator and decode never round-trips it through the
  host.  Sequences whose slot reservation hits :class:`CacheExhausted`
  are returned as *preempted* — the scheduler requeues them; the rest
  of the batch proceeds.  Never OOM.

Fault surface (what the server's watchdog/sentinel wrap): the chaos
``slow_decode_step`` injection fires at the top of :meth:`decode` —
INSIDE the server's watchdog thread, like ``hang_step`` does for the
training supervisor — and the logits-health scalar routes through
``chaos.poison_loss`` so ``nan_after`` can poison a decode step
deterministically.  Non-finite logits raise
:class:`~tpu_mx.supervisor.NumericDivergence`, the same exception class
the training sentinel escalates with, so ``supervisor.classify`` sorts
serving faults with the training rules unchanged.

The engine is DISPOSABLE: an engine restart builds a fresh EngineCore
(new cache, same model weights) and the old one — possibly still being
mutated by an abandoned watchdog thread — is garbage.  That is the whole
zombie-step story for serving: hung threads only ever touch a dead
engine's private state, never the scheduler or the request handles
(tpu_mx/serving/server.py).
"""
from __future__ import annotations

import math
import os
import time

import numpy as np

from .. import telemetry as _telemetry
from .. import tracing as _tracing
from ..contrib import chaos as _chaos
from ..supervisor import NumericDivergence
from .attention import decode_attention, resolve_decode_path
from .jax_model import JaxTinyLM, resolve_fused
from .kv_cache import (CacheExhausted, PagedKVCache, _next_pow2,
                       prefix_sharing_enabled)
from .speculative import SiblingProposer, accept_prefix, resolve_spec_window

__all__ = ["EngineCore"]


class EngineCore:
    """See module docstring.  ``model`` implements the decode protocol
    (tpu_mx/serving/model.py); cache geometry comes from it."""

    def __init__(self, model, block_size=16, num_blocks=256,
                 dtype=np.float32, share_prefix=None, forensics=None,
                 warm_batch=None, greedy=True):
        self.model = model
        # non-greedy sampling (ISSUE 19) is a HOST sampler whose RNG
        # state is journaled per token (serving/sampling.py): the fused
        # arm samples on-device and speculation verifies greedily, so
        # both are pinned off for the stream to stay replayable — a
        # knob conflict resolves loudly here, once per generation, and
        # is recorded on serve.decode_path below
        self.greedy = bool(greedy)
        # the decode arm is resolved ONCE per engine generation: a knob
        # flip mid-flight cannot leave half a batch on each path, and
        # the serve.decode_path event below is the black box's record of
        # which arm a (possibly restarted) engine was on.  The sharing,
        # fused-step and speculative knobs resolve the same way
        # (TPUMX_PREFIX_SHARING / TPUMX_FUSED_DECODE / TPUMX_SPECULATIVE
        # unless pinned by the caller) and ride the same event for the
        # same reason.
        self.decode_kind = resolve_decode_path()
        if share_prefix is None:
            share_prefix = prefix_sharing_enabled()
        self.share_prefix = bool(share_prefix)
        self.spec_window = resolve_spec_window() if self.greedy else 1
        self.fused = (resolve_fused(self.decode_kind, model)
                      if self.greedy else False)
        storage = "device" if self.decode_kind != "dense" else "host"
        self.cache = PagedKVCache(
            model.num_layers, model.num_heads, model.head_dim,
            block_size=block_size, num_blocks=num_blocks, dtype=dtype,
            storage=storage, share_prefix=self.share_prefix,
            forensics=forensics)
        if self.fused:
            import jax
            from ..kernels import paged_attention as _pk
            use_kernel = self.decode_kind == "paged-kernel" or (
                jax.default_backend() == "tpu"
                and _pk.supported(model.head_dim, dtype,
                                  self.cache.block_size))
            self.jax_model = JaxTinyLM(model, use_kernel=use_kernel)
            if warm_batch:
                # compile the batch buckets NOW, outside the server's
                # watchdog deadline: a first-bucket compile mid-serving
                # (~0.6s even for the test model) reads as a wedged
                # dispatch and can cascade into a spurious restart
                self.jax_model.warm(self.cache, int(warm_batch),
                                    self.spec_window)
        else:
            self.jax_model = None
        self.proposer = (SiblingProposer(model) if self.spec_window > 1
                         else None)
        _tracing.emit("serve.decode_path", path=self.decode_kind,
                      storage=storage, sharing=self.share_prefix,
                      fused=self.fused, spec_window=self.spec_window,
                      sampling="greedy" if self.greedy else "sampled")
        # sampled decode self-check (ISSUE 20, parallel/integrity.py):
        # serving has no dp peer to vote with, so its SDC detector is
        # the shadow audit — on a seeded sampled cadence, re-execute the
        # identical decode step and compare the emitted tokens
        # bit-exactly.  TPUMX_SELF_CHECK is the sample rate (0 = off,
        # the default: the rerun costs one extra forward on audited
        # steps).  A mismatch is DataCorruption → the server's restart
        # ladder, like every other classified engine fault.
        rate = float(os.environ.get("TPUMX_SELF_CHECK", "0") or 0)
        if rate > 0:
            from ..parallel.integrity import ShadowAuditor
            seed = int(os.environ.get("TPUMX_SELF_CHECK_SEED", "0") or 0)
            self._self_check = ShadowAuditor(rate=rate, seed=seed,
                                             surface="decode")
        else:
            self._self_check = None
        self._decode_step_idx = 0
        # cumulative speculative accounting for the accept-ratio gauge
        self._spec_drafted = 0
        self._spec_accepted = 0

    # -- prefill -------------------------------------------------------------
    def prefill(self, req):
        """Run ``req``'s prompt — PLUS any committed tokens it already
        delivered (the prefill-replay recovery path, ISSUE 19) — fill
        its cache blocks, and return ``(next, cached_tokens)``.  For a
        greedy request ``next`` is the argmax token; for a request
        carrying a sampler it is the final-position LOGITS vector — the
        caller samples on the driver thread after the watchdog join, so
        a zombie deadline thread can never touch the journaled RNG.

        A requeued request that kept its tokens is rebuilt in THIS one
        call: K/V at every position is a pure function of the tokens
        before it (the PR-12 purity proof), so prefilling
        ``prompt + committed`` recreates exactly the cache state the
        interrupted decode had and the returned token is the next one
        of the same stream — recovery cost is one prefill, flat in how
        many tokens were already generated.  ``serve.replay_tokens`` /
        ``serve.replay_requests`` receipt it; the ``serve.prefill``
        event carries ``replayed``.

        With sharing on, the longest indexed full-block prefix of the
        prompt is served from the cache (``cached_tokens`` of them):
        only the suffix's K/V is computed (``model.prefill_suffix``
        attending over the cached prefix) and written — bit-identical
        logits to a full prefill, one prefill's compute shared by every
        request carrying the template.  Replayed requests ride it too:
        N restarted requests sharing a template re-prefill the shared
        prefix once, not N times.  :class:`CacheExhausted` propagates
        with the cache unchanged and no pinned references left behind
        (the scheduler's backpressure path); NaN/Inf logits raise
        :class:`NumericDivergence`."""
        t0 = time.perf_counter()
        committed = [int(t) for t in getattr(req, "tokens", ())]
        tokens = req.prompt + committed if committed else req.prompt
        # the capacity ledger's attribution key (ISSUE 14): requests
        # without a tenant (bare tests) fall to the single-tenant default
        tenant = getattr(req, "tenant", None)
        plan = self.cache.match_prefix(tokens, tenant=tenant)
        if plan is not None:
            cached = plan.tokens_matched
            try:
                kp, vp = self.cache.gather_plan(plan)
                k, v, logits = self.model.prefill_suffix(
                    tokens[cached:], cached, kp, vp)
            except BaseException:
                # model/gather fault between match and commit: the pins
                # must not outlive the attempt (the audit counts them)
                self.cache.abandon_plan(plan)
                raise
            self.cache.commit_prefill(req.id, plan, k, v, tokens,
                                      tenant=tenant)
        else:
            cached = 0
            k, v, logits = self.model.prefill(tokens)
            self.cache.prefill(req.id, k, v,
                               tokens=tokens if self.share_prefix
                               else None, tenant=tenant)
        health = float(np.max(np.abs(logits)))
        if not math.isfinite(health):
            raise NumericDivergence(
                f"serving: non-finite logits in prefill of {req.id} "
                f"(health={health}) — restarting the engine")
        if committed:
            _telemetry.counter("serve.replay_requests").inc()
            _telemetry.counter("serve.replay_tokens").inc(len(committed))
        _tracing.emit("serve.prefill", request=req.id,
                      tokens=len(req.prompt), cached=cached,
                      replayed=len(committed), t0=t0,
                      t1=time.perf_counter())
        # non-greedy requests get the LOGITS back, not a token: the
        # caller samples on its own (driver) thread once the watchdog
        # join returns, so an abandoned deadline thread parked in here
        # can never advance a journaled RNG — the zombie-step discipline
        # covers sampler state, not just the discarded engine's cache.
        # The health gate above still guards the RNG: a poisoned or
        # faulting step raises before any logits are handed back.
        if getattr(req, "sampler", None) is not None:
            return np.asarray(logits).reshape(-1), cached
        return int(np.argmax(logits)), cached

    # -- decode --------------------------------------------------------------
    def decode(self, items):
        """One decode STEP for each ``(req, last_token)`` in ``items`` —
        one to ``spec_window`` tokens per request.

        Returns ``(results, preempted)``: ``results`` maps request id →
        the LIST of tokens this step produced, in stream order, for
        every sequence that decoded (always at least one; up to
        ``spec_window`` when speculation accepts drafted tokens) — for
        a request carrying a sampler the value is instead its
        final-position LOGITS vector (ndarray): the caller samples the
        one token on the driver thread, never this (possibly watchdog)
        thread.  ``preempted`` lists the requests evicted to make room
        — the scheduler requeues them (re-run), the rest of the batch
        proceeds.  Raises :class:`NumericDivergence` on non-finite
        logits (real or chaos-poisoned).

        The step reserves each sequence's whole draft window up front
        (``reserve_window`` — all-or-nothing, so preemption semantics
        are unchanged), runs ONE batched forward over the ``(B, K)``
        window through either the fused device program
        (serving/jax_model.py) or the host-resident layer loop, then
        accepts each row's agreeing draft prefix and truncates the
        rejected tail's cache slots.  Greedy verification makes the
        emitted stream bit-identical to one-token-at-a-time decode
        (serving/speculative.py).

        Preemption picks FINISHED batch members first (static-batching
        padding slots — their cache is pure waste and their handles are
        already done), then the unfinished not-yet-reserved member
        scoring worst on (tenant weight ascending, exclusively-held
        blocks descending, youngest): a low-weight tenant's sequence is
        sacrificed before a high-weight one's, and between peers the
        victim whose eviction actually RETURNS the most blocks goes
        first — freeing a sequence whose blocks are shared releases
        references, not memory (refcounts: the survivors keep reading
        the same bits, so preemption can never evict a block another
        live sequence shares).  The reservation is retried after each
        eviction, so the oldest live sequence always makes progress and
        an over-admitted batch drains instead of livelocking on mutual
        preemption (``items`` arrive in admission order from the
        scheduler)."""
        _chaos.maybe_slow_decode()
        _chaos.maybe_kill9_decode()   # real os._exit(137), cross-process
        _chaos.storm_restart()        # K back-to-back classified restarts
        k = self.spec_window
        live, preempted = [], []
        remaining = [(req, int(last)) for req, last in items]
        while remaining:
            req, last = remaining.pop(0)
            while True:
                try:
                    self.cache.reserve_window(req.id, k)
                    live.append((req, last))
                    break
                except CacheExhausted:
                    # backpressure, never OOM: free a victim's blocks
                    # (an unfinished victim re-runs from its prompt
                    # later) and retry
                    victim = None
                    for j in range(len(remaining) - 1, -1, -1):
                        if remaining[j][0].done:
                            victim = remaining.pop(j)[0]
                            break
                    if victim is None and remaining:
                        victim = remaining.pop(
                            self._pick_victim(remaining))[0]
                    if victim is None:
                        victim = req
                    self.cache.free_sequence(victim.id)
                    preempted.append(victim)
                    if victim is req:
                        break
        if not live:
            return {}, preempted
        b = len(live)
        seq_ids = [r.id for r, _ in live]
        # the reserved window's slots ARE positions length-K .. length-1
        lengths_now = np.array(
            [self.cache.length(s) for s in seq_ids], np.int64)
        base_pos = lengths_now - k
        draft = np.empty((b, k), np.int64)
        draft[:, 0] = [t for _, t in live]
        if k > 1:
            draft[:, 1:] = self.proposer.draft(draft[:, 0], base_pos,
                                               k - 1)
        positions = base_pos[:, None] + np.arange(k)
        samplers = [getattr(r, "sampler", None) for r, _ in live]
        want_logits = any(s is not None for s in samplers)
        if self.fused:
            out, logits1, health, crossings = self._fused_step(
                seq_ids, draft, positions)
        else:
            out, logits1, health, crossings = self._host_step(
                seq_ids, draft, positions, want_logits=want_logits)
        health = _chaos.poison_loss(health)
        if not math.isfinite(health):
            raise NumericDivergence(
                f"serving: non-finite logits in decode batch of "
                f"{len(live)} (health={health}) — restarting the engine")
        # sampled decode self-check (ISSUE 20): BEFORE the acceptance
        # loop truncates any rejected tail, re-run the identical step —
        # same operands, same program; the window's cache writes land the
        # same values in the same reserved slots (idempotent), so the
        # re-execution is bit-deterministic and a token mismatch is flaky
        # hardware by construction.  DataCorruption → classified
        # "corruption" → the server's restart ladder.
        idx = self._decode_step_idx
        self._decode_step_idx += 1
        if self._self_check is not None \
                and self._self_check.should_audit(idx):
            _telemetry.counter("integrity.self_checks").inc()

            def _recompute():
                if self.fused:
                    o2, _l2, _h2, _c2 = self._fused_step(
                        seq_ids, draft, positions)
                else:
                    o2, _l2, _h2, _c2 = self._host_step(
                        seq_ids, draft, positions,
                        want_logits=want_logits)
                return np.asarray(o2)

            try:
                self._self_check.audit(np.asarray(out), _recompute,
                                       step=idx)
            except Exception:
                _telemetry.counter(
                    "integrity.self_check_mismatches").inc()
                raise
        results = {}
        emitted_total = 0
        accepted_total = 0
        for bi, (req, _) in enumerate(live):
            a = accept_prefix(draft[bi], out[bi])
            if a + 1 < k:
                # rejected tail: the bookkeeping must match the
                # accepted stream NOW (the next window overwrites the
                # pool slots either way)
                self.cache.truncate(req.id,
                                    int(lengths_now[bi]) - (k - 1 - a))
            if samplers[bi] is not None and logits1 is not None:
                # non-greedy row (k pinned to 1): hand the last-position
                # logits back — the CALLER samples on the driver thread
                # after the watchdog join, so an abandoned zombie step
                # can never advance the journaled RNG (the health gate
                # above already ran; see prefill)
                results[req.id] = np.asarray(logits1[bi]).reshape(-1)
            else:
                results[req.id] = [int(t) for t in out[bi, :a + 1]]
            accepted_total += a
            emitted_total += a + 1
        if k > 1:
            self._spec_drafted += (k - 1) * b
            self._spec_accepted += accepted_total
            _telemetry.counter("serve.spec_drafted").inc((k - 1) * b)
            if accepted_total:
                _telemetry.counter("serve.spec_accepted").inc(
                    accepted_total)
            _telemetry.gauge("serve.spec_accept_ratio").set(
                self._spec_accepted / self._spec_drafted)
        # the O(1)-vs-O(layers) receipt (ISSUE 16): fused decode crosses
        # the host<->device boundary a CONSTANT 3 times per step
        # (operand commit, sampled tokens, health scalar); the
        # host-resident paged arm pays 4 per layer (two pool-write
        # commits, the query commit, the attention readback); dense is
        # pure host compute
        if crossings:
            _telemetry.counter("serve.host_crossings").inc(crossings)
        _telemetry.gauge("serve.host_crossings_per_token").set(
            crossings / emitted_total)
        return results, preempted

    def _host_step(self, seq_ids, draft, positions, want_logits=False):
        """The host-resident forward: numpy embed/QKV/combine
        interleaved with per-layer batched cache writes and decode
        attention.  ``K == 1`` is byte-for-byte the pre-speculative
        decode step; a wider window runs the same layer loop over the
        flattened ``(B*K, E)`` hidden batch with window writes and the
        per-row-causal widened attention.  Returns ``(out tokens
        (B, K), last-position logits (B, V) when ``want_logits`` else
        None, health, host crossings)`` — the logits hand-back is the
        non-greedy sampling seam (the caller samples after the health
        gate)."""
        b, k = draft.shape
        model = self.model
        # block tables are layer-invariant within a step (the slots were
        # reserved above): build them once, not once per layer
        batch = (self.cache.batch_tables(seq_ids)
                 if self.decode_kind != "dense" else None)
        if k == 1:
            h = model.embed(draft[:, 0], positions[:, 0])
            for i in range(model.num_layers):
                q, kk, vv = model.layer_qkv(i, h)
                self.cache.write_batch(seq_ids, i, kk, vv)
                attn = decode_attention(q, self.cache, seq_ids, i,
                                        kind=self.decode_kind,
                                        batch=batch)
                h = model.layer_combine(i, h, attn)
            logits = model.logits(h)
            out = np.argmax(logits, axis=-1)[:, None]
            if want_logits:
                crossings = (0 if self.decode_kind == "dense"
                             else 4 * model.num_layers)
                return (out, logits, float(np.max(np.abs(logits))),
                        crossings)
        else:
            h = model.embed(draft.reshape(-1), positions.reshape(-1))
            hd = (model.num_heads, model.head_dim)
            for i in range(model.num_layers):
                q, kk, vv = model.layer_qkv(i, h)
                self.cache.write_window(seq_ids, i,
                                        kk.reshape(b, k, *hd),
                                        vv.reshape(b, k, *hd))
                attn = decode_attention(q.reshape(b, k, *hd),
                                        self.cache, seq_ids, i,
                                        kind=self.decode_kind,
                                        batch=batch)
                h = model.layer_combine(i, h, attn.reshape(b * k, *hd))
            logits = model.logits(h).reshape(b, k, -1)
            out = np.argmax(logits, axis=-1)
        crossings = (0 if self.decode_kind == "dense"
                     else 4 * model.num_layers)
        return out, None, float(np.max(np.abs(logits))), crossings

    def _fused_step(self, seq_ids, draft, positions):
        """The fused arm: pad the batch to a power of two (dummy rows:
        zero tables, length 1, scatter coordinates at ``num_blocks`` so
        ``mode="drop"`` discards their pool writes — the jax_model
        padding contract) and run the whole window through ONE jitted
        device program with donated pools.  Returns ``(out tokens
        (B, K), health, host crossings)`` — crossings is the constant
        3 however many layers the model has."""
        b, k = draft.shape
        tables, lengths = self.cache.batch_tables(seq_ids)
        bids, offs = self.cache.window_slots(seq_ids, k)
        bpad = _next_pow2(b)
        if bpad != b:
            pad = bpad - b
            draft = np.concatenate(
                [draft, np.zeros((pad, k), draft.dtype)])
            positions = np.concatenate(
                [positions, np.zeros((pad, k), positions.dtype)])
            tables = np.concatenate(
                [tables, np.zeros((pad, tables.shape[1]), tables.dtype)])
            lengths = np.concatenate(
                [lengths, np.ones(pad, lengths.dtype)])
            bids = np.concatenate(
                [bids, np.full((pad, k), self.cache.allocator.num_blocks,
                               np.int32)])
            offs = np.concatenate([offs, np.zeros((pad, k), np.int32)])
        toks, health = self.jax_model.decode_step(
            self.cache, draft, positions, tables, lengths, bids, offs)
        _telemetry.counter("serve.fused_steps").inc()
        return toks[:b], None, health, 3

    def _pick_victim(self, remaining):
        """Index into ``remaining`` of the preemption victim: lowest
        tenant weight first (SLO-weighted fairness extends to who gets
        sacrificed under memory pressure), then the sequence whose
        eviction returns the MOST exclusively-held blocks (evicting a
        fully shared prefix frees nothing), youngest breaking ties
        (matching the pre-tenancy youngest-first drain guarantee).
        Requests without a tenant weight (bare tests) count as 1.0."""
        best_j, best_key = len(remaining) - 1, None
        for j in range(len(remaining) - 1, -1, -1):
            req = remaining[j][0]
            excl = (self.cache.exclusive_blocks(req.id)
                    if self.cache.has_sequence(req.id) else 0)
            key = (-float(getattr(req, "tenant_weight", 1.0)), excl, j)
            if best_key is None or key > best_key:
                best_j, best_key = j, key
        return best_j

    def evict(self, req):
        """Free a sequence's blocks (idempotent)."""
        return self.cache.free_sequence(req.id)
