"""Samplers whose RNG state is data (the serving half of resume.py).

Greedy decode needs no state: ``argmax`` replays bit-identically from
the tokens alone, which is why the recovery ladder (docs/robustness.md)
can rebuild a greedy stream from nothing but the committed-token
journal.  Non-greedy decode is only replayable if the sampler's RNG
state is treated exactly like the training RNG in ``tpu_mx/resume.py``:
captured as an exact capsule (``encode_state`` — base64 of the raw
MT19937 words, never a repr) next to every committed token, and restored
before the next sample.  With that discipline a journaled top-k stream
is bit-identical across an engine restart, a kill −9, or a planned
handoff — the sampler continues mid-stream instead of re-rolling.

- :class:`GreedySampler` exists only for symmetry in tests; the engine's
  fast path keeps its batched ``argmax`` and never constructs one.
- :class:`TopKSampler` draws from the softmax over the ``k`` highest
  logits with a private ``np.random.RandomState`` (process-global numpy
  RNG is never touched — the determinism rule every subsystem here
  follows).  ``state_dict()``/``load_state_dict()`` round-trip the exact
  generator state; ``reset()`` restores the construction-time state for
  the legacy prompt-replay arm, which re-rolls the whole stream from the
  start and therefore must reproduce it from the initial seed.

The engine resolves sampling ONCE per server (like every data-plane
knob): a non-greedy server pins the fused whole-step arm off and the
speculative window to 1 — both sample on-device/greedily and would fork
the stream from the host sampler (recorded on ``serve.decode_path`` so a
black box says which sampling mode the engine was on).
"""
from __future__ import annotations

import zlib

import numpy as np

from ..base import MXNetError
from ..resume import decode_state, encode_state

__all__ = ["GreedySampler", "TopKSampler", "fold_seed", "make_sampler",
           "parse_sampling"]


def parse_sampling(spec):
    """``"greedy"`` or ``"top_k:K"`` → ``("greedy", None)`` /
    ``("top_k", K)``.  The one spec parser, used by the server at
    construction so a typo fails the constructor, not request N."""
    spec = str(spec or "greedy").strip()
    if spec == "greedy":
        return "greedy", None
    kind, _, arg = spec.partition(":")
    if kind == "top_k":
        try:
            k = int(arg)
        except ValueError:
            k = 0
        if k >= 1:
            return "top_k", k
    raise MXNetError(
        f"serving: unknown sampling spec {spec!r} — expected 'greedy' "
        f"or 'top_k:K' with K >= 1")


def fold_seed(base_seed, request_id):
    """One deterministic 32-bit seed per request: the server's
    ``sampling_seed`` folded with the request id, so a recovered process
    (which re-derives samplers only when the journal carried no state)
    rolls the same stream the dead process would have."""
    return (int(base_seed) * 1000003
            + zlib.crc32(str(request_id).encode("utf-8"))) & 0xFFFFFFFF


class GreedySampler:
    """Stateless argmax — the trivial member of the sampler protocol."""

    kind = "greedy"

    def sample(self, logits):
        return int(np.argmax(np.asarray(logits).reshape(-1)))

    def state_dict(self):
        return {"kind": self.kind}

    def load_state_dict(self, state):
        if state.get("kind") != self.kind:
            raise MXNetError(f"sampler state kind {state.get('kind')!r} "
                             f"!= {self.kind!r}")

    def reset(self):
        pass


class TopKSampler:
    """Softmax over the top ``k`` logits, drawn from a private
    MT19937 — see module docstring for the RNG-is-data contract."""

    kind = "top_k"

    def __init__(self, k, seed=0):
        self.k = int(k)
        if self.k < 1:
            raise MXNetError(f"TopKSampler: k must be >= 1, got {k}")
        self._rng = np.random.RandomState(int(seed) & 0xFFFFFFFF)
        # the construction-time state, kept so reset() (the legacy
        # prompt-replay arm) re-rolls the stream from the beginning
        self._initial = self._rng.get_state()

    def sample(self, logits):
        logits = np.asarray(logits, np.float64).reshape(-1)
        k = min(self.k, logits.size)
        idx = np.argpartition(logits, -k)[-k:]
        # deterministic candidate order whatever argpartition returned:
        # logit descending, index ascending on ties
        idx = idx[np.lexsort((idx, -logits[idx]))]
        z = logits[idx] - logits[idx][0]
        p = np.exp(z)
        p /= p.sum()
        return int(idx[self._rng.choice(k, p=p)])

    def state_dict(self):
        """Exact JSON-safe capsule of the generator (resume.py's
        encode_state — the MT19937 key array rides as base64 bytes)."""
        return {"kind": self.kind, "k": self.k,
                "state": encode_state(list(self._rng.get_state()))}

    def load_state_dict(self, state):
        if state.get("kind") != self.kind:
            raise MXNetError(f"sampler state kind {state.get('kind')!r} "
                             f"!= {self.kind!r}")
        if int(state.get("k", self.k)) != self.k:
            raise MXNetError(
                f"sampler state k={state.get('k')} != configured "
                f"k={self.k} — the journaled stream was rolled under a "
                f"different distribution")
        st = decode_state(state["state"])
        self._rng.set_state((str(st[0]), np.asarray(st[1], np.uint32),
                             int(st[2]), int(st[3]), float(st[4])))

    def reset(self):
        self._rng.set_state(self._initial)


def make_sampler(kind, k, seed):
    """Instantiate a per-request sampler, or None for greedy (the
    engine's batched argmax fast path needs no object)."""
    if kind == "greedy":
        return None
    if kind == "top_k":
        return TopKSampler(k, seed=seed)
    raise MXNetError(f"serving: unknown sampler kind {kind!r}")
