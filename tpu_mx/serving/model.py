"""TinyLM: a deterministic attention decoder for the serving runtime.

The serving engine (docs/serving.md) is model-agnostic — it drives any
object implementing the small decode protocol below — but the tests, the
``serve`` CI tier and the bench leg need a REAL autoregressive attention
model whose correctness is checkable bit-for-bit: same tokens in, same
logits out, on any host, with no trained weights to ship.  TinyLM is
that: a multi-layer pre-activation attention decoder with
seed-deterministic random weights (numpy ``RandomState``), positional
embeddings, residual connections and a bounded ``tanh`` nonlinearity so
hundreds of autoregressive steps stay finite.  It is NOT a trained
language model; it is the workload that makes the cache/scheduler/server
claims falsifiable (block-table gather must reproduce the dense cache's
logits exactly — tests/test_serving.py).

Decode protocol (what the engine calls; any model serving real traffic
implements the same surface):

- attributes ``num_layers``, ``num_heads``, ``head_dim``, ``vocab_size``
- ``prefill(tokens) -> (k, v, logits_last)`` — the whole prompt in one
  call: per-layer K/V ``(num_layers, L, H, D)`` for the cache bulk-fill
  and the last position's logits ``(V,)``
- ``embed(tokens, positions) -> (B, E)`` — batched decode entry
- ``layer_qkv(i, h) -> (q, k, v)`` each ``(B, H, D)``
- ``layer_combine(i, h, attn) -> (B, E)`` — residual + output proj
- ``logits(h) -> (B, V)``

The per-layer split exists because layer i's K/V projection is a
function of layer i-1's attention output: the engine must interleave
cache writes with the forward, which is exactly what the
``reserve``/``write`` cache API models.
"""
from __future__ import annotations

import numpy as np

from . import attention as _attn

__all__ = ["TinyLM"]


class TinyLM:
    """Seed-deterministic attention decoder (see module docstring)."""

    def __init__(self, vocab_size=128, embed_dim=64, num_heads=4,
                 num_layers=2, max_positions=4096, seed=0):
        if embed_dim % num_heads:
            raise ValueError(f"embed_dim {embed_dim} must divide by "
                             f"num_heads {num_heads}")
        self.vocab_size = int(vocab_size)
        self.embed_dim = int(embed_dim)
        self.num_heads = int(num_heads)
        self.head_dim = self.embed_dim // self.num_heads
        self.num_layers = int(num_layers)
        self.max_positions = int(max_positions)
        rng = np.random.RandomState(seed)
        scale = 1.0 / np.sqrt(self.embed_dim)

        def mat(*shape):
            return (rng.standard_normal(shape) * scale).astype(np.float32)

        self.tok_emb = mat(self.vocab_size, self.embed_dim)
        self.pos_emb = mat(self.max_positions, self.embed_dim)
        self.layers = [
            {"wq": mat(self.embed_dim, self.embed_dim),
             "wk": mat(self.embed_dim, self.embed_dim),
             "wv": mat(self.embed_dim, self.embed_dim),
             "wo": mat(self.embed_dim, self.embed_dim)}
            for _ in range(self.num_layers)]
        self.w_out = mat(self.embed_dim, self.vocab_size)

    # -- shared projections ---------------------------------------------------
    def _split_heads(self, x):
        # (..., E) -> (..., H, D)
        return x.reshape(x.shape[:-1] + (self.num_heads, self.head_dim))

    def embed(self, tokens, positions):
        """(B,) int tokens at (B,) int absolute positions -> (B, E)."""
        tokens = np.asarray(tokens, np.int64)
        positions = np.asarray(positions, np.int64)
        if np.any(positions >= self.max_positions):
            raise ValueError(
                f"position {int(positions.max())} >= max_positions="
                f"{self.max_positions} — raise max_positions or cap "
                "prompt+generation length at admission")
        return self.tok_emb[tokens % self.vocab_size] + self.pos_emb[positions]

    def layer_qkv(self, i, h):
        """(B, E) -> q, k, v each (B, H, D)."""
        lay = self.layers[i]
        return (self._split_heads(h @ lay["wq"]),
                self._split_heads(h @ lay["wk"]),
                self._split_heads(h @ lay["wv"]))

    def layer_combine(self, i, h, attn):
        """Residual + output projection + bounded nonlinearity.

        ``tanh`` keeps hidden magnitudes in [-1, 1] so arbitrarily long
        untrained-weight generations never overflow — the engine's NaN
        sentinel must fire on *injected* faults, not on the toy model's
        own drift."""
        flat = attn.reshape(attn.shape[0], self.embed_dim)
        return np.tanh(h + flat @ self.layers[i]["wo"])

    def logits(self, h):
        """(B, E) -> (B, V)."""
        return h @ self.w_out

    # -- prefill --------------------------------------------------------------
    def prefill(self, tokens):
        """The whole prompt in one call.

        Returns ``(k, v, logits_last)`` with ``k``/``v`` shaped
        ``(num_layers, L, H, D)`` — the cache bulk-fill payload — and the
        last position's ``(V,)`` logits (the first generated token's
        distribution).  Attention routes through
        :func:`serving.attention.prefill_attention` (flash on supported
        TPU shapes, dense reference elsewhere)."""
        tokens = np.asarray(tokens, np.int64)
        length = tokens.shape[0]
        h = self.embed(tokens, np.arange(length))          # (L, E)
        ks = np.empty((self.num_layers, length, self.num_heads,
                       self.head_dim), np.float32)
        vs = np.empty_like(ks)
        for i in range(self.num_layers):
            q, k, v = self.layer_qkv(i, h)                 # (L, H, D)
            ks[i] = k
            vs[i] = v
            attn = _attn.prefill_attention(q, k, v)
            h = self.layer_combine(i, h, attn)
        return ks, vs, self.logits(h[-1:])[0]

    def prefill_suffix(self, tokens, pos_offset, k_prefix, v_prefix):
        """The tail of a prompt whose leading ``pos_offset`` positions'
        K/V are already cached (shared-prefix reuse, ISSUE 12).

        ``tokens``: the SUFFIX token ids, absolute positions
        ``pos_offset .. pos_offset+S-1``; ``k_prefix``/``v_prefix``:
        ``(num_layers, pos_offset, H, D)`` — the cached prefix K/V
        (``PagedKVCache.gather_plan``).  Returns ``(k, v, logits_last)``
        with ``k``/``v`` shaped ``(num_layers, S, H, D)`` — only the
        suffix positions, the cache-write payload — and the last
        position's ``(V,)`` logits.

        Soundness: position p's K/V is a pure function of tokens 0..p,
        so the cached prefix is bit-identical to what a full prefill
        would recompute; each suffix query attends causally over
        [prefix K/V ++ suffix K/V] — the same score rows, reduced in the
        same order, as the full prefill's last S rows
        (tests/test_multitenant.py pins the greedy streams)."""
        tokens = np.asarray(tokens, np.int64)
        s = tokens.shape[0]
        if s < 1:
            raise ValueError("prefill_suffix: empty suffix — at least the "
                             "final prompt position must be computed for "
                             "its logits")
        m = int(pos_offset)
        want = (self.num_layers, m, self.num_heads, self.head_dim)
        k_prefix = np.asarray(k_prefix, np.float32)
        v_prefix = np.asarray(v_prefix, np.float32)
        if k_prefix.shape != want or v_prefix.shape != want:
            raise ValueError(
                f"prefill_suffix: prefix K/V must be {want}, got "
                f"{k_prefix.shape} / {v_prefix.shape}")
        h = self.embed(tokens, m + np.arange(s))           # (S, E)
        ks = np.empty((self.num_layers, s, self.num_heads,
                       self.head_dim), np.float32)
        vs = np.empty_like(ks)
        for i in range(self.num_layers):
            q, k, v = self.layer_qkv(i, h)                 # (S, H, D)
            ks[i] = k
            vs[i] = v
            attn = _attn.prefill_attention(
                q, np.concatenate([k_prefix[i], k], axis=0),
                np.concatenate([v_prefix[i], v], axis=0))
            h = self.layer_combine(i, h, attn)
        return ks, vs, self.logits(h[-1:])[0]
