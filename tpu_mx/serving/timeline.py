"""Per-request latency attribution: every request's wall clock, typed.

The SLO pair (TTFT/ITL histograms) says *how slow*; this module says
*where the time went*.  Each :class:`~tpu_mx.serving.scheduler.Request`
owns a :class:`RequestTimeline` that decomposes its whole lifetime —
submit to finish/fail — into typed phases stamped at the existing
scheduler/engine/server seams:

- ``queue_wait``   — pending-queue time before a prefill attempt starts
- ``prefill``      — the prompt's engine prefill (watchdog wait included:
  this is the *request's* wall clock, not the device's)
- ``decode_gap``   — per-token: from the previous committed token (or
  prefill end) to this commit — scheduler share and decode compute both
- ``restart_penalty`` — everything an engine restart / cache preemption
  cost this request: the in-flight interval at fault time, the
  rebuild/backoff/queue wait until its recovery starts, and the
  recovery work itself — the replay prefill on the prefill-replay arm
  (ISSUE 19), or, on the legacy prompt-replay arm, the re-run prefill
  PLUS every re-decoded catch-up token (tokens the client already had
  deliver nothing; charging them to ``decode_gap`` would hide exactly
  the O(n) cost the replay arm removes — the CI A/B gate compares the
  two arms on this phase)
- ``defer_stall``  — cache-backpressure deferrals: the wait after a
  prefill admission bounced on ``CacheExhausted``
- ``reject``       — the (tiny) interval a rejected admission consumed

The accounting is **interval-complete by construction**: a single
``_mark`` cursor advances monotonically from ``submitted_at``, and every
seam closes ``[mark, now]`` into exactly one phase — so the phases sum
to the measured wall clock and any clock-mixing or double-count bug
breaks the 5% invariant the serve CI tier asserts.  At first-token time
the cumulative sums are snapshotted as ``ttft_breakdown`` (which
therefore sums to the measured TTFT, restarts included — the snapshot
resets when a requeue discards the generation).

One ``serve.request_timeline`` event per request is emitted at
finish/fail (never per transition — 512 ring slots are for *whole*
lifecycles) carrying the request id in its payload (``data.request``;
the process-global trace context is NOT written here — finalize can run
on the submitting thread), and each phase total lands in
the ``serve.phase_seconds{phase=...}`` histogram, windowed like every
histogram, so "which phase is eating the fleet's budget *right now*" is
an O(buckets) read (docs/observability.md "SLO engine").

Thread-safety: a timeline is mutated only by the thread driving its
request — the server's step thread after admission, the submitting
thread for a synchronous reject — matching the Request handle's own
discipline (docs/serving.md).
"""
from __future__ import annotations

import time

from .. import telemetry as _telemetry
from .. import tracing as _tracing

__all__ = ["PHASES", "RequestTimeline"]

# the closed set of phase names (docs/observability.md documents each);
# serve.phase_seconds{phase=...} and the serve.request_timeline payload
# carry exactly these
PHASES = ("queue_wait", "prefill", "decode_gap", "restart_penalty",
          "defer_stall", "reject")


class RequestTimeline:
    """See module docstring.  ``t0`` is the request's ``submitted_at``
    (the same ``perf_counter`` reading, so the attribution and the SLO
    bookkeeping share one clock)."""

    __slots__ = ("t0", "_mark", "_wait_kind", "_in_flight", "phases",
                 "defers", "requeues", "tokens", "ttft_breakdown",
                 "_first_token_pending", "ended_at", "outcome",
                 "cached_tokens", "_replay_pending", "_catchup")

    def __init__(self, t0=None):
        self.t0 = time.perf_counter() if t0 is None else float(t0)
        self._mark = self.t0
        self._wait_kind = "queue_wait"   # what the NEXT wait interval is
        self._in_flight = False          # prefill done, decoding
        self.phases = {}
        self.defers = 0
        self.requeues = 0
        self.tokens = 0                  # delivered by the final attempt
        self.cached_tokens = 0           # prompt tokens served from the
        #                                  shared-prefix cache (final
        #                                  attempt — a short `prefill`
        #                                  phase is attributed honestly)
        self.ttft_breakdown = None
        self._first_token_pending = True
        self.ended_at = None
        self.outcome = None
        self._replay_pending = False     # next prefill is a restart replay
        self._catchup = 0                # legacy re-decodes still owed

    # -- the one accounting primitive ----------------------------------------
    def _close(self, phase, now=None):
        """Close ``[mark, now]`` into ``phase`` and advance the mark."""
        now = time.perf_counter() if now is None else now
        if now > self._mark:
            self.phases[phase] = (self.phases.get(phase, 0.0)
                                  + (now - self._mark))
            self._mark = now
        return self._mark

    # -- seams (server/scheduler call these) ---------------------------------
    def mark_prefill_start(self):
        """The server picked this request's prefill: the wait so far was
        queue_wait (or restart_penalty/defer_stall after a requeue or
        deferral)."""
        self._close(self._wait_kind)
        self._wait_kind = "queue_wait"

    def mark_prefill_end(self, cached_tokens=0):
        """``cached_tokens``: how many leading prompt tokens this
        attempt served from the shared-prefix cache — recorded so a
        suspiciously fast ``prefill`` phase reads as a cache hit, not a
        measurement bug (ISSUE 12).  A restart-replay prefill (ISSUE 19)
        is recovery work, not first-time prompt work: it closes into
        ``restart_penalty``, keeping ``prefill`` comparable across
        restarted and clean requests."""
        self._close("restart_penalty" if self._replay_pending
                    else "prefill")
        self._replay_pending = False
        self._in_flight = True
        self.cached_tokens = int(cached_tokens)

    def mark_prefill_failed(self):
        """The prefill attempt bounced on cache backpressure: the
        attempt itself, and the wait until the retry starts, are a
        defer stall."""
        self._close("defer_stall")
        self._wait_kind = "defer_stall"
        self._in_flight = False
        self.defers += 1

    def mark_defer(self):
        """Deferred before starting (an earlier admission in the same
        step exhausted the cache): the wait so far keeps its label, the
        wait from here to the retried prefill is a defer stall."""
        self._close(self._wait_kind)
        self._wait_kind = "defer_stall"
        self.defers += 1

    def mark_token(self, now=None):
        """A token committed: the gap since the previous commit (or the
        prefill end) is decode_gap.  The first token of an attempt
        snapshots the cumulative phase sums — the TTFT breakdown.  On
        the legacy prompt-replay arm, the first ``committed`` tokens
        after a requeue are CATCH-UP re-decodes — the client already
        had them, so their gaps are restart penalty, not decode_gap
        (and each one counts ``serve.redecode_tokens``)."""
        if self._catchup > 0:
            self._catchup -= 1
            self._close("restart_penalty", now)
            _telemetry.counter("serve.redecode_tokens").inc()
        else:
            self._close("decode_gap", now)
        self.tokens += 1
        if self._first_token_pending:
            self._first_token_pending = False
            self.ttft_breakdown = dict(self.phases)

    def mark_requeue(self, committed=0):
        """An engine restart / cache preemption DISCARDED this request's
        generation (the legacy prompt-replay arm): the in-flight
        interval, and everything until the re-run's prefill starts, is
        restart penalty.  ``committed`` is how many tokens the discarded
        attempt had delivered — the re-run's first ``committed`` decodes
        are catch-up and stay in restart_penalty (:meth:`mark_token`).
        The first-token snapshot resets with the generation (TTFT is
        measured to the final attempt's first token)."""
        self._close("restart_penalty")
        self._wait_kind = "restart_penalty"
        self._in_flight = False
        self.requeues += 1
        self.tokens = 0
        self.cached_tokens = 0   # the re-run re-resolves its own hit
        self._catchup += int(committed)
        self._first_token_pending = True
        self.ttft_breakdown = None

    def mark_replay_requeue(self):
        """The prefill-replay arm's requeue (ISSUE 19): the generation
        SURVIVES — committed tokens, their delivery times, and the TTFT
        already measured all stand, because the recovery re-establishes
        the stream without re-yielding anything.  Everything from the
        fault to the end of the ONE replay prefill is restart penalty
        (the wait here, the prefill via ``_replay_pending``)."""
        self._close("restart_penalty")
        self._wait_kind = "restart_penalty"
        self._in_flight = False
        self.requeues += 1
        self._replay_pending = True

    # -- terminal ------------------------------------------------------------
    def finalize(self, request_id, outcome, ttft=None, now=None,
                 tenant=None):
        """Close the books (idempotent) and emit the one-per-request
        ``serve.request_timeline`` event + phase histograms.  ``outcome``
        is ``done``/``failed``/``rejected``; ``ttft`` the request's
        measured submit→first-token seconds when a token was produced;
        ``tenant`` the submitting tenant (rides the event payload — the
        per-tenant grouping key tools/slo_report.py uses)."""
        if self.ended_at is not None:
            return
        if outcome == "rejected":
            self._close("reject", now)
        elif outcome != "done":
            # failed mid-decode (degraded drain of RUNNING requests):
            # the residual interval was in-flight, not queued — it is
            # the decode gap that never committed.  A request failed
            # while genuinely waiting keeps the wait label it was
            # accruing under.
            self._close("decode_gap" if self._in_flight
                        else self._wait_kind, now)
        # a "done" request's mark already sits at its last token commit
        self.ended_at = self._mark
        self.outcome = outcome
        for phase, seconds in self.phases.items():
            _telemetry.histogram("serve.phase_seconds",
                                 phase=phase).observe(seconds)
        payload = {p: self.phases.get(p, 0.0) for p in PHASES}
        if ttft is not None:
            payload["ttft"] = float(ttft)
        if tenant is not None:
            payload["tenant"] = str(tenant)
        # the request id travels in the PAYLOAD, not the trace context:
        # finalize can run on the submitting thread (synchronous
        # reject), and the context is process-global — writing it here
        # would race the step thread's request scope.  Join timeline
        # events on data.request.
        _tracing.emit("serve.request_timeline", request=request_id,
                      outcome=outcome, latency=self.ended_at - self.t0,
                      tokens=self.tokens, requeues=self.requeues,
                      defers=self.defers,
                      cached_tokens=self.cached_tokens, **payload)

    @property
    def total(self):
        """Sum of all attributed phases (== ended_at - t0 once
        finalized; the CI invariant compares this against the request's
        independently stamped wall clock)."""
        return sum(self.phases.values())
