"""Serving attention: flash prefill on TPU, dense-gather decode fallback.

Two shapes of attention exist in a serving engine and they want different
kernels:

- **Prefill** — the whole prompt at once: a (L, H, D) causal
  self-attention, exactly the shape ``tpu_mx/kernels/flash_attention.py``
  was built for.  :func:`prefill_attention` routes through the Pallas
  kernel whenever the backend is a real TPU and the shape passes
  ``flash_attention.supported()`` (head_dim % 64, L % 128); everything
  else — including the CPU tier-1 suite — runs the dense reference.
- **Decode** — one new token per sequence against the paged cache: a
  (B, 1, H, D) query over block-scattered K/V.  The flash kernel's grid
  assumes contiguous (BH, T, D) operands and T % 128; a single-token
  query is the wrong shape for it, and a true paged-attention kernel
  (block-table indexing inside the kernel) is future TPU work recorded
  as docs/DIVERGENCES.md #27.  :func:`decode_attention` therefore runs
  the **dense-gather fallback** everywhere: the cache gathers each
  sequence's blocks into a padded dense batch
  (``PagedKVCache.gather_batch``) and the scores are masked by the true
  lengths — bit-identical to a contiguous cache, O(total context) per
  step on the host.

Both paths keep softmax statistics in f32 (same discipline as the
kernel); the dense reference is pure numpy so the serving data plane
stays importable and testable without jax.
"""
from __future__ import annotations

import math

import numpy as np

__all__ = ["dense_attention", "prefill_attention", "decode_attention"]

# mask value for padded/causal-excluded score entries; matches the
# kernel's NEG_INF discipline (finite: exp() underflows to exactly 0
# without generating inf-inf=nan corners in the f32 stats)
_NEG_INF = -1e30


def dense_attention(q, k, v, lengths=None, causal=False):
    """Reference attention: ``softmax(q·kᵀ/√D  [+masks]) · v``.

    ``q``: (B, Tq, H, D); ``k``/``v``: (B, Tk, H, D); ``lengths``:
    optional int (B,) — key positions >= length are masked out (the
    padded dense-gather batch).  ``causal`` aligns the LAST query to the
    LAST valid key (prefill: Tq == Tk; decode: Tq == 1 attending to all
    cached keys).  f32 scores/softmax, output cast back to q.dtype."""
    q = np.asarray(q)
    k = np.asarray(k)
    v = np.asarray(v)
    b, tq, h, d = q.shape
    tk = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    # asarray, not astype: the hot path is already f32 and astype would
    # COPY the O(context) operands every decode step
    s = np.einsum("bqhd,bkhd->bhqk", np.asarray(q, np.float32),
                  np.asarray(k, np.float32)) * scale
    kpos = np.arange(tk)
    if lengths is not None:
        # mask by slice-assigning ONLY the padding tail per row: O(pad)
        # instead of a full O(B·Tk) where-pass — the decode hot path
        # calls this every token (bit-identical result: the same
        # entries end up _NEG_INF)
        lens_arr = np.asarray(lengths, np.int64).reshape(b)
        for i in range(b):
            if lens_arr[i] < tk:
                s[i, :, :, lens_arr[i]:] = _NEG_INF
    if causal:
        # query i sits at absolute position (valid_len - Tq + i)
        lens = (np.asarray(lengths, np.int64).reshape(b, 1, 1, 1)
                if lengths is not None else
                np.full((b, 1, 1, 1), tk, np.int64))
        qpos = lens - tq + np.arange(tq).reshape(1, 1, tq, 1)
        s = np.where(kpos.reshape(1, 1, 1, tk) <= qpos, s, _NEG_INF)
    m = np.max(s, axis=-1, keepdims=True)
    p = np.exp(s - m, out=s)
    p /= np.sum(p, axis=-1, keepdims=True)
    out = np.einsum("bhqk,bkhd->bqhd", p, np.asarray(v, np.float32))
    return np.asarray(out, q.dtype)


def _tpu_flash_ok(length, head_dim, dtype):
    """Whether the Pallas flash kernel should take this prefill: a real
    TPU backend (interpret mode is correctness-only — orders of magnitude
    slower than numpy for a single prompt) and a supported shape."""
    try:
        import jax
        from ..kernels import flash_attention as _fa
    except ImportError:  # serving data plane must run without jax
        return False
    if jax.default_backend() != "tpu":
        return False
    return _fa.supported((length, head_dim), dtype)


def prefill_attention(q, k, v):
    """Causal self-attention over one prompt: ``q``/``k``/``v`` are
    (L, H, D); returns (L, H, D).

    TPU + supported shape → the Pallas flash kernel ((H, L, D) folded
    layout, O(L) memory); otherwise the dense numpy reference (the CPU
    fallback tier-1 tests, docs/DIVERGENCES.md #27)."""
    q = np.asarray(q)
    length, heads, dim = q.shape
    if _tpu_flash_ok(length, dim, q.dtype):
        import jax.numpy as jnp
        from ..kernels.flash_attention import flash_attention as _flash
        fold = lambda x: jnp.asarray(x).transpose(1, 0, 2)  # (H, L, D)
        out = _flash(fold(q), fold(k), fold(v), causal=True)
        return np.asarray(out).transpose(1, 0, 2)
    return dense_attention(q[None], np.asarray(k)[None],
                           np.asarray(v)[None], causal=True)[0]


def decode_attention(q, keys, values, lengths):
    """One decode step's attention for a batch of sequences.

    ``q``: (B, H, D) — each sequence's single new-token query; ``keys``/
    ``values``: (B, Lmax, H, D) — the padded dense gather of each
    sequence's block table (``PagedKVCache.gather_batch``, new token's
    K/V already written at position length-1); ``lengths``: (B,) true
    context lengths.  Returns (B, H, D)."""
    out = dense_attention(np.asarray(q)[:, None], keys, values,
                          lengths=lengths)
    return out[:, 0]
