"""Serving attention: flash prefill, paged-kernel or dense-gather decode.

Two shapes of attention exist in a serving engine and they want different
kernels:

- **Prefill** — the whole prompt at once: a (L, H, D) causal
  self-attention, exactly the shape ``tpu_mx/kernels/flash_attention.py``
  was built for.  :func:`prefill_attention` routes through the Pallas
  kernel whenever the backend is a real TPU and the shape passes
  ``flash_attention.supported()`` (head_dim % 64, L % 128); everything
  else — including the CPU tier-1 suite — runs the dense reference.
- **Decode** — one new token per sequence against the paged cache: a
  (B, 1, H, D) query over block-scattered K/V.  :func:`decode_attention`
  dispatches between two arms behind the ``TPUMX_PAGED_DECODE`` knob:

  * **dense-gather** (default, the always-available reference arm): the
    cache resolves each sequence's block table into a padded dense
    batch (``PagedKVCache.gather_batch``) and the scores are masked by
    the true lengths — bit-identical to a contiguous cache, O(total
    context) of host memcpy per step (docs/DIVERGENCES.md #27).
  * **paged** (``TPUMX_PAGED_DECODE=1``): the raw block tables go to
    ``tpu_mx/kernels/paged_attention.py`` — the Pallas kernel on a real
    TPU (pool resident in HBM, tables scalar-prefetched into the
    BlockSpec index maps), the same algorithm as one jitted XLA program
    off-TPU.  ``TPUMX_PAGED_DECODE=kernel`` forces the Pallas kernel
    everywhere (interpret mode off-TPU) — the parity-test/CI arm that
    exercises the real kernel code path on CPU.

Both paths keep softmax statistics in f32 (same discipline as the
kernels); the dense reference is pure numpy so the serving data plane
stays importable and testable without jax — a paged request on a
jax-less host resolves to the dense arm, never an ImportError.
"""
from __future__ import annotations

import math
import os

import numpy as np

from .. import telemetry as _telemetry

__all__ = ["dense_attention", "prefill_attention", "decode_attention",
           "dense_decode_attention", "decode_path", "resolve_decode_path"]

# mask value for padded/causal-excluded score entries; matches the
# kernel's NEG_INF discipline (finite: exp() underflows to exactly 0
# without generating inf-inf=nan corners in the f32 stats)
_NEG_INF = -1e30


def dense_attention(q, k, v, lengths=None, causal=False):
    """Reference attention: ``softmax(q·kᵀ/√D  [+masks]) · v``.

    ``q``: (B, Tq, H, D); ``k``/``v``: (B, Tk, H, D); ``lengths``:
    optional int (B,) — key positions >= length are masked out (the
    padded dense-gather batch).  ``causal`` aligns the LAST query to the
    LAST valid key (prefill: Tq == Tk; decode: Tq == 1 attending to all
    cached keys).  f32 scores/softmax, output cast back to q.dtype."""
    q = np.asarray(q)
    k = np.asarray(k)
    v = np.asarray(v)
    b, tq, h, d = q.shape
    tk = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    # asarray, not astype: the hot path is already f32 and astype would
    # COPY the O(context) operands every decode step
    s = np.einsum("bqhd,bkhd->bhqk", np.asarray(q, np.float32),
                  np.asarray(k, np.float32)) * scale
    kpos = np.arange(tk)
    if lengths is not None:
        # mask by slice-assigning ONLY the padding tail per row: O(pad)
        # instead of a full O(B·Tk) where-pass — the decode hot path
        # calls this every token (bit-identical result: the same
        # entries end up _NEG_INF)
        lens_arr = np.asarray(lengths, np.int64).reshape(b)
        for i in range(b):
            if lens_arr[i] < tk:
                s[i, :, :, lens_arr[i]:] = _NEG_INF
    if causal:
        # query i sits at absolute position (valid_len - Tq + i)
        lens = (np.asarray(lengths, np.int64).reshape(b, 1, 1, 1)
                if lengths is not None else
                np.full((b, 1, 1, 1), tk, np.int64))
        qpos = lens - tq + np.arange(tq).reshape(1, 1, tq, 1)
        s = np.where(kpos.reshape(1, 1, 1, tk) <= qpos, s, _NEG_INF)
    m = np.max(s, axis=-1, keepdims=True)
    p = np.exp(s - m, out=s)
    p /= np.sum(p, axis=-1, keepdims=True)
    out = np.einsum("bhqk,bkhd->bqhd", p, np.asarray(v, np.float32))
    return np.asarray(out, q.dtype)


def _tpu_flash_ok(length, head_dim, dtype):
    """Whether the Pallas flash kernel should take this prefill: a real
    TPU backend (interpret mode is correctness-only — orders of magnitude
    slower than numpy for a single prompt) and a supported shape."""
    try:
        import jax
        from ..kernels import flash_attention as _fa
    except ImportError:  # serving data plane must run without jax
        return False
    if jax.default_backend() != "tpu":
        return False
    return _fa.supported((length, head_dim), dtype)


def prefill_attention(q, k, v):
    """Causal self-attention over one prompt: ``q`` is (S, H, D) and
    ``k``/``v`` are (L, H, D) with L >= S; returns (S, H, D).

    L == S is the classic whole-prompt prefill; L > S is the
    shared-prefix SUFFIX prefill (ISSUE 12): the queries are the last S
    prompt positions and the leading L-S keys/values came from the
    prefix cache — causal alignment puts query i at absolute position
    L - S + i, which is exactly ``dense_attention``'s convention, so
    the suffix rows see the same score rows (same reduction order) a
    full prefill would compute.

    TPU + supported whole-prompt shape → the Pallas flash kernel
    ((H, L, D) folded layout, O(L) memory); suffix prefills and
    everything off-TPU run the dense numpy reference (the CPU fallback
    tier-1 tests, docs/DIVERGENCES.md #27)."""
    q = np.asarray(q)
    k = np.asarray(k)
    length, heads, dim = q.shape
    if k.shape[0] == length and _tpu_flash_ok(length, dim, q.dtype):
        import jax.numpy as jnp
        from ..kernels.flash_attention import flash_attention as _flash
        fold = lambda x: jnp.asarray(x).transpose(1, 0, 2)  # (H, L, D)
        out = _flash(fold(q), fold(k), fold(v), causal=True)
        return np.asarray(out).transpose(1, 0, 2)
    return dense_attention(q[None], k[None],
                           np.asarray(v)[None], causal=True)[0]


def dense_decode_attention(q, keys, values, lengths):
    """The dense-gather decode arm (and the paged arms' parity oracle).

    ``q``: (B, H, D) — each sequence's single new-token query; ``keys``/
    ``values``: (B, Lmax, H, D) — the padded dense gather of each
    sequence's block table (``PagedKVCache.gather_batch``, new token's
    K/V already written at position length-1); ``lengths``: (B,) true
    context lengths.  Returns (B, H, D)."""
    out = dense_attention(np.asarray(q)[:, None], keys, values,
                          lengths=lengths)
    return out[:, 0]


# -- decode dispatch ---------------------------------------------------------
_PAGED_ENV = "TPUMX_PAGED_DECODE"


def decode_path():
    """The decode arm ``TPUMX_PAGED_DECODE`` requests (no availability
    check): ``"dense"`` (unset/``0``), ``"paged"`` (``1``/``auto`` —
    Pallas kernel on a supported TPU shape, the jitted XLA same-algorithm
    arm otherwise) or ``"paged-kernel"`` (``kernel`` — force the Pallas
    kernel, interpret mode off-TPU; the parity/CI arm).  Unknown values
    raise: a typo'd ``kernel`` silently falling back to another arm
    would let a "kernel parity" run pass without ever executing the
    kernel (same loud-config discipline as ``PagedKVCache(storage=)``
    and ``TPUMX_ATTENTION``)."""
    v = os.environ.get(_PAGED_ENV, "0").strip().lower()
    if v in ("", "0", "dense", "off"):
        return "dense"
    if v in ("kernel", "interpret"):
        return "paged-kernel"
    if v in ("1", "auto", "paged", "xla", "on"):
        return "paged"
    raise ValueError(
        f"{_PAGED_ENV}={v!r} is not a recognized decode arm — use 0 "
        "(dense-gather reference), 1 (paged: kernel on TPU / XLA twin "
        "off-TPU) or kernel (force the Pallas kernel, interpret off-TPU)")


def resolve_decode_path():
    """:func:`decode_path`, downgraded to ``"dense"`` when jax is not
    importable — the paged arms need it, the reference arm must not."""
    kind = decode_path()
    if kind != "dense":
        try:
            import jax  # noqa: F401 — availability probe only
        except ImportError:
            return "dense"
    return kind


def _paged_decode(q, cache, seq_ids, layer, kind, batch=None):
    """Run one decode step's attention through the paged kernel (or its
    jitted XLA twin): raw block tables + the resident pool, no host
    gather.  The batch axis is padded to a power of two (dummy rows:
    block-0 table, length 1 — finite real pool contents sliced away
    below) so jitted consumers see log2-many shapes, not one per batch
    composition.  ``batch`` is an optional precomputed ``(tables,
    lengths)`` pair — tables cannot change between the layers of one
    decode step, so the engine builds them once per step instead of
    once per layer."""
    import jax
    from ..kernels import paged_attention as _pk
    from .kv_cache import _next_pow2

    tables, lengths = (cache.batch_tables(seq_ids) if batch is None
                       else batch)
    kp, vp = cache.pool(layer)
    b = q.shape[0]
    bpad = _next_pow2(b)
    if bpad != b:
        q_in = np.concatenate(
            [np.asarray(q), np.zeros((bpad - b,) + q.shape[1:], q.dtype)])
        tables = np.concatenate(
            [tables, np.zeros((bpad - b, tables.shape[1]), tables.dtype)])
        lengths = np.concatenate(
            [lengths, np.ones(bpad - b, lengths.dtype)])
    else:
        q_in = q
    use_kernel = kind == "paged-kernel" or (
        jax.default_backend() == "tpu"
        and _pk.supported(q.shape[-1], q_in.dtype, cache.block_size))
    fn = _pk.paged_attention if use_kernel else _pk.paged_attention_reference
    # the host-resident arm's one readback per layer (the numpy
    # reference model needs the kernel's output home for layer_combine)
    # sits behind the guarded-fallback idiom — ISSUE 16 retired the
    # justified suppression that used to live here, and the FUSED arm
    # (serving/jax_model.py) removes the readback entirely: the whole
    # step is one device program and only sampled tokens come home
    out = fn(q_in, kp, vp, tables, lengths)
    if not isinstance(out, np.ndarray):
        out = np.asarray(out)
    return out[:b]


def decode_attention(q, cache, seq_ids, layer, kind=None, batch=None):
    """One decode step's attention for a batch of sequences, against the
    paged cache.

    ``q``: (B, H, D) — each sequence's single new-token query, the new
    token's K/V already written at position length-1 — or (B, Tq, H, D),
    a speculative draft WINDOW (ISSUE 16): the last Tq positions'
    queries, every drafted slot's K/V already written, per-row causal
    masking (query t sits at absolute position length - Tq + t).
    ``cache``: the :class:`~tpu_mx.serving.kv_cache.PagedKVCache`;
    ``seq_ids``: the batch's sequence ids in row order; ``layer``: the
    layer whose pool to read.  ``kind`` pins the arm (an engine resolves
    the env knob once per generation so a black box records one truth);
    defaults to :func:`resolve_decode_path`.  ``batch``: optional
    precomputed ``cache.batch_tables(seq_ids)`` result for the paged
    arms — the tables are layer-invariant within a step, so per-layer
    callers build them once.  Returns q's shape.

    Every call counts ``serve.decode_attention{kind=...}`` — the
    observable that says which arm a production decode actually took."""
    kind = resolve_decode_path() if kind is None else kind
    q = np.asarray(q)
    if kind == "dense":
        kd, vd, lens = cache.gather_batch(seq_ids, layer)
        if q.ndim == 4:
            # the window arm: dense_attention's causal alignment (last
            # query at the last valid key) IS the draft window's per-row
            # mask; the Tq == 1 call below stays byte-for-byte the
            # pre-speculative path
            out = dense_attention(q, kd, vd, lengths=lens, causal=True)
        else:
            out = dense_decode_attention(q, kd, vd, lens)
    else:
        out = _paged_decode(q, cache, seq_ids, layer, kind, batch=batch)
    _telemetry.counter("serve.decode_attention", kind=kind).inc()
    return out
