"""Multi-tenant policy: per-tenant weights, quotas, and bounded labels.

The fleet-scale serving story (ROADMAP; ISSUE 12): "millions of users"
are not one queue — they are tenants with different priorities, traffic
shapes, and blast radii.  This module is the small, declarative policy
surface the scheduler and server consume:

- :class:`TenantConfig` — one tenant's knobs: ``weight`` (its share of
  the admission bandwidth under the SLO-weighted fair policy —
  tpu_mx/serving/scheduler.py), ``max_inflight`` (admitted-and-
  unfinished request cap) and ``token_quota`` (worst-case in-flight
  token cap, the same ``budget_tokens`` unit the scheduler's global
  ``max_tokens`` budget uses).  Exceeding either gets
  ``AdmissionReject(reason="tenant_quota")`` — backpressure per tenant,
  so one tenant's burst can never starve the fleet.
- :class:`TenantTable` — the registry.  Unknown tenants resolve to a
  permissive default (weight 1, no caps): single-tenant callers never
  have to know tenancy exists, and the pre-tenancy behavior is exactly
  the one-tenant special case.
- :func:`label_for` — the bounded-cardinality telemetry label.  Tenant
  ids are client-controlled strings; using them raw as metric labels
  would let one misbehaving client mint unbounded series.  The first
  ``TENANT_LABEL_CAP`` distinct tenants seen by the process keep their
  name; every later one collapses into the ``_other`` overflow label
  (documented in docs/observability.md — the per-tenant SLO breakdown
  is exact for the configured/early tenants, aggregated for the long
  tail).
"""
from __future__ import annotations

import threading

__all__ = ["DEFAULT_TENANT", "TENANT_LABEL_CAP", "OVERFLOW_LABEL",
           "TenantConfig", "TenantTable", "label_for",
           "reset_label_registry"]

DEFAULT_TENANT = "default"

# telemetry-label cardinality cap: first N distinct tenant ids keep
# their own label, the rest share the overflow label (docs/observability
# .md).  Configured tenants are pre-registered by TenantTable, so a
# declared tenant can never be squeezed out by anonymous traffic.
TENANT_LABEL_CAP = 16
OVERFLOW_LABEL = "_other"

_label_lock = threading.Lock()
_labels: dict = {}


def label_for(tenant):
    """The bounded telemetry label for ``tenant`` (see module
    docstring): its own name for the first :data:`TENANT_LABEL_CAP`
    distinct tenants, :data:`OVERFLOW_LABEL` afterwards.  Stable within
    a process.  Past-cap ids are NOT remembered: tenant ids are
    client-controlled strings, so the registry itself must stay
    bounded — an id-per-request stream maps to the overflow label
    without growing process memory."""
    tenant = str(tenant)
    with _label_lock:
        got = _labels.get(tenant)
        if got is not None:
            return got
        if len(_labels) >= TENANT_LABEL_CAP:
            return OVERFLOW_LABEL
        _labels[tenant] = tenant
        return tenant


def reset_label_registry():
    """Drop the process-global label assignments (test hook — the cap
    is first-come-first-named, so isolation between tests needs it)."""
    with _label_lock:
        _labels.clear()


class TenantConfig:
    """One tenant's declarative policy — see module docstring.
    ``weight`` must be positive; ``max_inflight``/``token_quota`` are
    caps on admitted-and-unfinished work (None = uncapped)."""

    __slots__ = ("tenant", "weight", "max_inflight", "token_quota")

    def __init__(self, tenant, weight=1.0, max_inflight=None,
                 token_quota=None):
        self.tenant = str(tenant)
        self.weight = float(weight)
        if self.weight <= 0:
            raise ValueError(f"TenantConfig({tenant!r}): weight must be "
                             f"positive, got {weight}")
        self.max_inflight = None if max_inflight is None \
            else int(max_inflight)
        if self.max_inflight is not None and self.max_inflight < 1:
            raise ValueError(f"TenantConfig({tenant!r}): max_inflight "
                             f"must be >= 1, got {max_inflight}")
        self.token_quota = None if token_quota is None else int(token_quota)
        if self.token_quota is not None and self.token_quota < 1:
            raise ValueError(f"TenantConfig({tenant!r}): token_quota "
                             f"must be >= 1, got {token_quota}")

    def __repr__(self):
        return (f"TenantConfig({self.tenant!r}, weight={self.weight}, "
                f"max_inflight={self.max_inflight}, "
                f"token_quota={self.token_quota})")


class TenantTable:
    """The tenant registry.  ``get`` never fails: unknown tenants
    resolve to a shared permissive default config, so tenancy is purely
    additive — a server with no table behaves exactly as before."""

    def __init__(self, configs=()):
        self._configs = {}
        for cfg in configs:
            if not isinstance(cfg, TenantConfig):
                raise TypeError(f"TenantTable takes TenantConfig entries, "
                                f"got {type(cfg).__name__}")
            if cfg.tenant in self._configs:
                raise ValueError(f"duplicate tenant {cfg.tenant!r}")
            self._configs[cfg.tenant] = cfg
            label_for(cfg.tenant)   # declared tenants get real labels
        self._default = TenantConfig("_default_")

    @classmethod
    def coerce(cls, obj):
        """``None`` → empty table; a table → itself; an iterable of
        :class:`TenantConfig` → a table; a ``{tenant: {knobs...}}``
        mapping → a table (the config-file shape)."""
        if obj is None:
            return cls()
        if isinstance(obj, cls):
            return obj
        if isinstance(obj, dict):
            return cls(TenantConfig(t, **(kw or {}))
                       for t, kw in obj.items())
        return cls(obj)

    def get(self, tenant):
        """``tenant``'s config, or the permissive default."""
        return self._configs.get(str(tenant), self._default)

    def configured(self):
        """The explicitly declared tenant ids."""
        return list(self._configs)

    def __len__(self):
        return len(self._configs)

    def __repr__(self):
        return f"TenantTable({sorted(self._configs)})"
