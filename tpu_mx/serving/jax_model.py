"""JaxTinyLM: the whole decode step as ONE jitted device program.

PR 9 made decode *attention* native (the Pallas paged kernel and its
jitted XLA twin), but the model forward stayed host-resident numpy:
``embed``/``layer_qkv``/``layer_combine``/``logits`` crossed the
host<->device boundary per layer per token (docs/DIVERGENCES.md #27) —
O(layers) eager dispatches fencing in the kernel's win.  This module is
the serving-v3 answer (ISSUE 16): a transformer whose ENTIRE decode
step — embed, every layer's QKV projections, the paged-attention walk
against the device-resident KV pool, residual/combine, logits and
greedy/top-k sampling — is one ``jax.jit`` program with the cache pools
passed in as **donated** buffers and written by in-program scatters.
Only the sampled token ids (and one health scalar) ever cross back to
the host: O(1) crossings per step, however many layers the model has.

Weights import straight from a host :class:`~tpu_mx.serving.model.
TinyLM` — same seed, same matrices — so the fused program's greedy
streams are checkable against the numpy reference bit-for-bit (the CI
serve tier gates fused-vs-host stream equality; tests/test_serving.py
pins it per step).

The query axis is a window: ``tokens`` is ``(B, Tq)``, so the same
program that decodes one token per sequence (``Tq == 1``) verifies a
speculative draft window (``Tq > 1`` — serving/speculative.py) in one
batched call, with the widened kernel applying the per-row causal
mask (kernels/paged_attention.py).

Pool-donation contract: :meth:`JaxTinyLM.decode_step` takes the cache's
pool handles (``PagedKVCache.pools``), CONSUMES them (donation makes
the in-program scatter genuinely in-place), and installs the returned
buffers (``adopt_pools``).  Anything holding a pre-step handle is stale
by the cache's own step-thread-ownership rule.

Batch-padding contract (the engine's job): dummy rows carry
``lengths == 1`` and scatter coordinates ``bids == num_blocks`` — out
of range, so ``mode="drop"`` makes their pool writes no-ops — and the
health scalar only reduces over rows with ``lengths >= 2`` (every real
decode row has at least prompt + reserved slot), so a dummy row's
finite garbage can neither clobber block 0 nor trip the NaN sentinel.
"""
from __future__ import annotations

import functools
import math
import os

import numpy as np

from ..base import MXNetError

__all__ = ["JaxTinyLM", "fused_requested", "resolve_fused"]

_FUSED_ENV = "TPUMX_FUSED_DECODE"


def fused_requested():
    """The fused-step knob's raw request: ``TPUMX_FUSED_DECODE`` unset/
    ``0``/``off`` means the host-resident arm, ``1``/``on``/``auto``
    requests the fused device program.  Unknown values raise — the same
    loud-config discipline as ``TPUMX_PAGED_DECODE`` (a typo silently
    falling back would let a "fused parity" run pass without ever
    executing the fused program)."""
    v = os.environ.get(_FUSED_ENV, "0").strip().lower()
    if v in ("", "0", "off", "no", "host"):
        return False
    if v in ("1", "on", "auto", "yes", "fused"):
        return True
    raise ValueError(
        f"{_FUSED_ENV}={v!r} is not a recognized decode arm — use 0 "
        "(host-resident forward) or 1 (whole-step fused device program)")


def resolve_fused(decode_kind, model):
    """Whether THIS engine generation runs the fused arm: requested via
    the env knob, AND the decode arm is paged (the fused program needs
    the device-resident pool — a dense engine has host pools), AND the
    model's weights are importable (:meth:`JaxTinyLM.compatible`).  The
    downgrades mirror ``resolve_decode_path``'s jax-availability
    downgrade: resolved once per generation, recorded on the
    ``serve.decode_path`` event's ``fused`` field."""
    if not fused_requested():
        return False
    if decode_kind == "dense":
        return False
    return JaxTinyLM.compatible(model)


@functools.lru_cache(maxsize=16)
def _build_step(num_layers, vocab, num_heads, head_dim, use_kernel,
                top_k):
    """Build (once per static geometry) the fused decode-step program.

    Static args are baked into the trace; ``jax.jit`` itself caches one
    executable per operand-shape set on top (batch bucket, table width,
    window width), so the decode hot loop never re-traces.  The pools
    (argnums 1/2) are DONATED: the scatter that writes the window's K/V
    reuses their buffers instead of copying the whole pool per step."""
    import jax
    import jax.numpy as jnp

    from ..kernels import paged_attention as _pk

    scale = 1.0 / math.sqrt(head_dim)

    def step(params, kps, vps, tokens, positions, tables, lengths,
             bids, offs, key):
        b, tq = tokens.shape
        embed_dim = num_heads * head_dim
        h = (params["tok_emb"][tokens % vocab]
             + params["pos_emb"][positions])               # (B, Tq, E)
        new_kps, new_vps = [], []
        for i in range(num_layers):
            q = (h @ params["wq"][i]).reshape(
                b, tq, num_heads, head_dim)
            k = (h @ params["wk"][i]).reshape(
                b, tq, num_heads, head_dim)
            v = (h @ params["wv"][i]).reshape(
                b, tq, num_heads, head_dim)
            # in-program donated index update — the kv_cache write_*
            # jit family's scatter, fused into the step program.  Dummy
            # rows scatter at bids == num_blocks: dropped, never block 0
            kp = kps[i].at[bids, offs].set(
                k.astype(kps[i].dtype), mode="drop")
            vp = vps[i].at[bids, offs].set(
                v.astype(vps[i].dtype), mode="drop")
            new_kps.append(kp)
            new_vps.append(vp)
            if use_kernel:
                fn = _pk._kernel_call(
                    b, tables.shape[1], kp.shape[1], tq, num_heads,
                    head_dim, "float32", scale, _pk._interpret())
                attn = fn(tables, lengths, q, kp, vp)
            else:
                attn = _pk.window_walk(q, kp, vp, tables, lengths,
                                       scale)
            h = jnp.tanh(h + attn.reshape(b, tq, embed_dim)
                         @ params["wo"][i])
        logits = h @ params["w_out"]                       # (B, Tq, V)
        if top_k > 1:
            # Gumbel-max over the top-k slice: one categorical draw per
            # (row, window position) without materializing a host RNG
            vals, idxs = jax.lax.top_k(logits, top_k)
            g = jax.random.gumbel(key, vals.shape)
            pick = jnp.argmax(vals + g, axis=-1)
            toks = jnp.take_along_axis(idxs, pick[..., None],
                                       axis=-1)[..., 0]
        else:
            toks = jnp.argmax(logits, axis=-1)
        # health only over REAL rows (dummy padding rows carry
        # lengths == 1; real rows always have prompt + reserved >= 2):
        # a padded row's finite garbage must not masquerade as the
        # batch's logit magnitude
        valid = lengths >= 2
        health = jnp.max(jnp.where(valid[:, None, None],
                                   jnp.abs(logits), 0.0))
        return new_kps, new_vps, toks.astype(jnp.int32), health

    return jax.jit(step, donate_argnums=(1, 2))


class JaxTinyLM:
    """TinyLM's weights as resident jax arrays + the fused step (see
    module docstring).  Construction imports the host model's matrices
    once; the per-step host traffic is the integer operand batch in and
    the sampled tokens out."""

    _IMPORTED = ("tok_emb", "pos_emb", "layers", "w_out", "vocab_size",
                 "num_layers", "num_heads", "head_dim", "max_positions")

    def __init__(self, model, use_kernel=False):
        if not self.compatible(model):
            raise MXNetError(
                "JaxTinyLM: model does not expose TinyLM's weight "
                f"surface ({', '.join(self._IMPORTED)}) — the fused "
                "decode arm only runs models whose forward it can "
                "reproduce bit-checkably")
        import jax.numpy as jnp

        self.model = model
        self.vocab_size = model.vocab_size
        self.num_layers = model.num_layers
        self.num_heads = model.num_heads
        self.head_dim = model.head_dim
        self.max_positions = model.max_positions
        self.use_kernel = bool(use_kernel)
        self.params = {
            "tok_emb": jnp.asarray(model.tok_emb),
            "pos_emb": jnp.asarray(model.pos_emb),
            "wq": jnp.stack([jnp.asarray(l["wq"]) for l in model.layers]),
            "wk": jnp.stack([jnp.asarray(l["wk"]) for l in model.layers]),
            "wv": jnp.stack([jnp.asarray(l["wv"]) for l in model.layers]),
            "wo": jnp.stack([jnp.asarray(l["wo"]) for l in model.layers]),
            "w_out": jnp.asarray(model.w_out),
        }
        # greedy needs no randomness; the key operand still rides along
        # so top-k sampling shares one trace signature.  Drawn through
        # the framework stream so resume capsules can replay it.
        from .. import random as _random
        self._dummy_key = _random.take_key()

    @staticmethod
    def compatible(model):
        """Whether the fused arm can import this model's weights."""
        return all(hasattr(model, a) for a in JaxTinyLM._IMPORTED)

    def warm(self, cache, max_batch, tq, table_width=4):
        """Pre-compile the fused step for every pow2 batch bucket up to
        ``max_batch`` at window width ``tq``.

        The first call at a new operand-shape set pays the XLA compile
        (~0.6s for even the test model on CPU) — INSIDE the server's
        watchdog deadline if it happens mid-serving, where it is
        indistinguishable from a wedged dispatch and can cascade into a
        spurious engine restart.  Engine construction runs outside the
        watchdog, so the engine warms the buckets here with all-dummy
        batches (the module docstring's padding contract: writes
        dropped, health masked — semantically a no-op).  Restarted
        engines re-warm for free: the executable cache is keyed on the
        lru-cached step callable + shapes, both unchanged.  Wider block
        tables than ``table_width`` still compile lazily — that cost is
        shared with (and was already carried by) the host arm's jitted
        attention twin."""
        nb = cache.allocator.num_blocks
        b = 1
        top = max(1, int(max_batch))
        while True:
            shape = (b, int(tq))
            self.decode_step(
                cache, np.zeros(shape, np.int32),
                np.zeros(shape, np.int32),
                np.zeros((b, int(table_width)), np.int32),
                np.ones((b,), np.int32),
                np.full(shape, nb, np.int32), np.zeros(shape, np.int32))
            if b >= top:
                break
            b *= 2

    def decode_step(self, cache, tokens, positions, tables, lengths,
                    bids, offs, top_k=1, key=None):
        """ONE fused device step for a (padded) decode batch.

        ``tokens``/``positions``/``bids``/``offs``: int ``(B, Tq)``;
        ``tables``: int32 ``(B, NB)``; ``lengths``: int32 ``(B,)`` —
        the engine's padded window batch (dummy rows per the module
        docstring's contract).  Consumes and replaces ``cache``'s pool
        buffers (donation handoff), returns ``(tokens, health)`` with
        ``tokens`` a host int32 ``(B, Tq)`` of sampled ids and
        ``health`` the real rows' max |logit| — the ONLY values that
        cross back to the host."""
        positions = np.asarray(positions)
        if positions.max() >= self.max_positions:
            # the host model's embed() contract, checked before the
            # device program bakes the out-of-range gather in
            raise ValueError(
                f"position {int(positions.max())} >= max_positions="
                f"{self.max_positions} — raise max_positions or cap "
                "prompt+generation length at admission")
        step = _build_step(self.num_layers, self.vocab_size,
                           self.num_heads, self.head_dim,
                           self.use_kernel, int(top_k))
        kps, vps = cache.pools()
        new_kps, new_vps, toks, health = step(
            self.params, kps, vps,
            np.asarray(tokens, np.int32), positions.astype(np.int32),
            np.asarray(tables, np.int32), np.asarray(lengths, np.int32),
            np.asarray(bids, np.int32), np.asarray(offs, np.int32),
            self._dummy_key if key is None else key)
        cache.adopt_pools(new_kps, new_vps)
        # the one sanctioned readback pair: sampled ids + health scalar
        toks = np.asarray(toks)
        return toks, float(health)
