"""Request admission + continuous batching policy (docs/serving.md).

The scheduler is pure policy: it owns the **pending** queue (bounded —
the backpressure boundary) and the **running** set (the live decode
batch), and decides, each engine step, which requests to prefill and
which sequences to decode.  It never touches the model or the cache
contents; the server composes it with the engine.

- **Continuous batching** (:class:`ContinuousBatchingScheduler`): new
  requests are admitted into the running batch on EVERY step as slots
  and token budget allow, and finished sequences leave immediately —
  the batch never waits for its slowest member.  This is the ≥2×
  throughput claim the bench ``serve`` leg measures against the static
  baseline.
- **Admission control**: three reject-with-reason gates *before* any
  memory is committed — ``queue_full`` (bounded pending queue),
  ``request_too_large`` (one request can never fit the token budget),
  and the chaos ``reject_storm`` injection.  A reject is an
  :class:`AdmissionReject` the caller sees with ``.reason``; nothing is
  silently dropped and nothing OOMs.
- **Token budget**: admission stops while the in-flight worst case
  (``sum(len(prompt) + max_new_tokens)`` over running) would exceed
  ``max_tokens`` — the knob that keeps cache demand bounded.
- **Static baseline** (:class:`StaticBatchingScheduler`): the naive
  policy real systems started from — admit a full batch, run it until
  EVERY member finishes (finished sequences keep burning their slot,
  cache and compute as padding), only then admit the next batch.  Kept
  in-tree so the continuous-batching win is measured against a real
  implementation, not a strawman description.

Thread-safety: all public methods take the scheduler lock; ``submit``
may be called from any thread while the server's step thread admits and
evicts (tests/test_serving.py hammers this).
"""
from __future__ import annotations

import itertools
import threading
import time

from ..base import MXNetError
from .. import telemetry as _telemetry
from .. import tracing as _tracing
from ..contrib import chaos as _chaos
from .tenancy import DEFAULT_TENANT, TenantTable, label_for
from .timeline import RequestTimeline

__all__ = ["Request", "AdmissionReject", "ContinuousBatchingScheduler",
           "StaticBatchingScheduler"]

_req_counter = itertools.count()


class AdmissionReject(MXNetError):
    """The front-end refused this request; ``reason`` says why
    (``queue_full`` / ``request_too_large`` / ``reject_storm``).  This is
    backpressure, not failure: the client resubmits later."""

    def __init__(self, reason, detail=""):
        super().__init__(f"request rejected: {reason}"
                         + (f" ({detail})" if detail else ""))
        self.reason = reason
        # reasons: queue_full / request_too_large / reject_storm /
        # degraded / tenant_quota (ISSUE 12 — the submitting tenant is
        # over its max_inflight or token_quota; resubmit after its own
        # in-flight work drains, other tenants are unaffected) /
        # draining (ISSUE 19 — the server is quiescing for a drain or
        # handoff; resubmit once admission reopens)


class Request:
    """One generation request and its lifecycle record (the handle the
    front-end returns).

    States: ``queued`` → ``running`` → ``done`` (or ``failed``).  A
    requeued request (engine restart, cache preemption) goes back to
    ``queued``; on the prefill-replay arm (ISSUE 19, the default) its
    committed tokens SURVIVE — they are the in-memory token ledger the
    recovery prefill replays in one call — while the legacy
    prompt-replay arm discards them and re-runs from the prompt
    (docs/serving.md, docs/robustness.md).  ``requeues`` counts how
    often either happened.  ``sampler`` (serving/sampling.py) is the
    per-request host sampler for non-greedy modes, or None for the
    engine's batched-argmax fast path.  Latency bookkeeping
    (``submitted_at``, ``first_token_at``, ``token_times``) feeds the
    TTFT/ITL telemetry and the bench percentiles."""

    def __init__(self, prompt, max_new_tokens, request_id=None,
                 tenant=None):
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("Request: empty prompt")
        if int(max_new_tokens) < 1:
            raise ValueError("Request: max_new_tokens must be >= 1")
        self.id = request_id or f"req-{next(_req_counter):06d}"
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        # the submitting tenant (ISSUE 12): the fairness/quota identity
        # and the bounded telemetry label.  tenant_weight is resolved by
        # the server from its TenantTable at submit (1.0 bare) — the
        # engine's preemption victim selection reads it without needing
        # the table.
        self.tenant = DEFAULT_TENANT if tenant is None else str(tenant)
        self.tenant_weight = 1.0
        self.sampler = None
        self.state = "queued"
        self.tokens = []
        self.finish_reason = None
        self.requeues = 0
        self.submitted_at = time.perf_counter()
        self.first_token_at = None
        self.finished_at = None
        self.token_times = []
        # the attribution ledger shares the submit timestamp so phases
        # and the TTFT/latency bookkeeping run on one clock
        # (tpu_mx/serving/timeline.py; docs/observability.md)
        self.timeline = RequestTimeline(self.submitted_at)
        self._done = threading.Event()

    @property
    def budget_tokens(self):
        """Worst-case in-flight footprint: prompt + full generation."""
        return len(self.prompt) + self.max_new_tokens

    @property
    def done(self):
        return self._done.is_set()

    @property
    def ttft(self):
        """Submit → first token, seconds (None before the first token)."""
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.submitted_at

    def record_token(self, token):
        now = time.perf_counter()
        if self.first_token_at is None:
            self.first_token_at = now
        else:
            gap = now - self.token_times[-1]
            _telemetry.histogram("serve.itl_seconds").observe(gap)
            # the per-tenant twin (bounded label — tenancy.label_for):
            # the unlabeled series stays the fleet aggregate every
            # existing dashboard and the global SLO monitor read; the
            # labeled one is what the per-tenant burn/boost loop and
            # slo_report's tenant section consume
            _telemetry.histogram("serve.itl_seconds",
                                 tenant=label_for(self.tenant)).observe(gap)
        self.token_times.append(now)
        self.tokens.append(int(token))
        self.timeline.mark_token(now)

    def reset_generation(self, keep_tokens=False):
        """Put the request back in ``queued`` for a re-run
        (restart/preemption).  ``keep_tokens=True`` is the
        prefill-replay arm: committed tokens, delivery times, and the
        measured TTFT all stand — the recovery prefill replays them
        without re-yielding.  ``keep_tokens=False`` is the legacy
        prompt-replay arm: generated state is discarded, and a stateful
        sampler rewinds to its initial capsule so the re-rolled stream
        reproduces the discarded one bit-for-bit."""
        if keep_tokens:
            self.requeues += 1
            self.state = "queued"
            self.timeline.mark_replay_requeue()
            return
        committed = len(self.tokens)
        self.tokens = []
        self.token_times = []
        self.first_token_at = None
        self.requeues += 1
        self.state = "queued"
        if self.sampler is not None:
            self.sampler.reset()
        self.timeline.mark_requeue(committed=committed)

    def _observe_ttft(self):
        # one serve.ttft_seconds sample per REQUEST, stamped at terminal
        # time from the final attempt's first token: a per-attempt
        # observe would let a restart's discarded attempt contribute an
        # extra, optimistic sample (no restart penalty) to exactly the
        # histogram the SLO monitor alerts on during an incident.
        # Deliberate tradeoff: the sample lands when the request ENDS,
        # so TTFT breach detection lags by the decode duration and
        # still-decoding requests are invisible to the window — fine at
        # this runtime's generation lengths; long-generation serving
        # would want an in-flight-aware read (docs/observability.md).
        if self.first_token_at is not None:
            _telemetry.histogram("serve.ttft_seconds").observe(self.ttft)
            _telemetry.histogram(
                "serve.ttft_seconds",
                tenant=label_for(self.tenant)).observe(self.ttft)

    def finish(self, reason="length"):
        self.state = "done"
        self.finish_reason = reason
        self.finished_at = time.perf_counter()
        self._observe_ttft()
        # per-tenant terminal count (the unlabeled completed/rejected
        # totals live at the scheduler/server seams, unchanged)
        _telemetry.counter("serve.requests", state="completed",
                           tenant=label_for(self.tenant)).inc()
        self.timeline.finalize(self.id, "done", ttft=self.ttft,
                               tenant=self.tenant)
        self._done.set()

    def fail(self, reason):
        self.state = "failed"
        self.finish_reason = reason
        self.finished_at = time.perf_counter()
        self._observe_ttft()
        outcome = ("rejected" if str(reason).startswith("rejected")
                   else "failed")
        _telemetry.counter("serve.requests", state=outcome,
                           tenant=label_for(self.tenant)).inc()
        self.timeline.finalize(self.id, outcome, ttft=self.ttft,
                               tenant=self.tenant)
        self._done.set()

    def wait(self, timeout=None):
        """Block until done/failed; returns the terminal state reached."""
        self._done.wait(timeout)
        return self.state

    def __repr__(self):
        return (f"Request({self.id}, state={self.state}, "
                f"prompt={len(self.prompt)}t, "
                f"generated={len(self.tokens)}/{self.max_new_tokens})")


class ContinuousBatchingScheduler:
    """Split prefill/decode queues with per-step continuous admission
    (policy details in the module docstring).

    **Multi-tenant fairness** (ISSUE 12): ``tenants`` (anything
    :meth:`~tpu_mx.serving.tenancy.TenantTable.coerce` accepts) arms
    per-tenant policy.  Admission enforces each tenant's
    ``max_inflight``/``token_quota`` (reject reason ``tenant_quota``),
    and :meth:`take_prefills` becomes **SLO-weighted fair**: candidates
    are the per-tenant QUEUE HEADS (FIFO within a tenant — one tenant's
    oversized head no longer blocks every other tenant's admissible
    work), picked by weighted virtual time — each admission advances its
    tenant's clock by ``budget_tokens / effective_weight``, so admitted
    token bandwidth converges to the weight ratio, deficit-style.  A
    tenant whose per-tenant SLO burn is breaching (``slo_signal``, the
    PR-11 hook — tpu_mx/serving/slo.py publishes per-tenant burn when
    tenant-labeled series exist) gets its weight multiplied by
    ``slo_boost`` until the breach clears.  With a single tenant every
    rule degenerates to exactly the pre-tenancy FIFO behavior."""

    def __init__(self, max_pending=64, max_batch=8, max_tokens=8192,
                 tenants=None, slo_boost=2.0):
        self.max_pending = int(max_pending)
        self.max_batch = int(max_batch)
        self.max_tokens = int(max_tokens)
        self.tenants = TenantTable.coerce(tenants)
        self.slo_boost = float(slo_boost)
        self._lock = threading.RLock()
        self._pending = []
        self._running = []
        # weighted-fairness state: tenant -> virtual time (service
        # received / effective weight), plus the monotone SYSTEM floor:
        # the highest virtual time any pick has been served at.  A new
        # or long-idle tenant enters at max(own, floor) — it competes
        # from "now", not from a stale-low clock that would let it
        # monopolize admission for an unbounded catch-up period.  (For
        # continuously backlogged tenants the floor is provably inert:
        # a candidate with a lower clock would have been picked first.)
        self._vtime = {}
        self._vfloor = 0.0
        # requests popped by take_prefills but not yet running (the
        # mid-prefill window): in neither queue, but still in flight —
        # the tenant quota count must see them or a concurrent submit
        # in that window slips past max_inflight/token_quota.  Removed
        # at mark_running / defer / requeue.
        self._admitting = set()
        # the vtime charge each pending admission paid at pick time, so
        # a DEFERRED admission (cache backpressure, never started) can
        # be refunded — without the refund a tenant under memory
        # pressure is charged once per bounce while receiving zero
        # service, skewing the weight ratio against it
        self._vtime_charges = {}
        # the server publishes its SLO monitor's latest signal here each
        # step (tpu_mx/serving/slo.py) — take_prefills consults it for
        # the per-tenant burn-rate boost
        self.slo_signal = None
        # the capacity ledger's would-fit signal (ISSUE 14; the
        # symmetric twin of slo_signal, published by the server from
        # cache.capacity_stats each step): admission skips a prefill
        # whose blocks cannot fit free + pressure-reclaimable capacity
        # instead of popping it just to bounce on CacheExhausted.  None
        # (no server driving, or right after an engine restart) means
        # no gating — exactly the pre-ledger behavior.
        self.capacity_signal = None
        # per-request gate-skip counts: the would-fit need is computed
        # from the FULL prompt, but a shared-prefix hit may need far
        # fewer fresh blocks — after a bounded number of gated rounds
        # the head is admitted anyway (the pre-ledger pop-and-maybe-
        # defer path), so the gate can delay but never starve
        self._capacity_skips = {}

    # -- admission (any thread) ----------------------------------------------
    def _tenant_inflight(self, tenant):
        """(requests, budget tokens) admitted and unfinished for
        ``tenant`` — pending + running + the mid-prefill window
        (popped by ``take_prefills``, not yet ``mark_running``).
        Called under the lock; O(n) over bounded queues beats a
        drift-prone incremental counter."""
        n = toks = 0
        for bucket in (self._pending, self._running, self._admitting):
            for r in bucket:
                if r.tenant == tenant:
                    n += 1
                    toks += r.budget_tokens
        return n, toks

    def submit(self, req):
        """Enqueue ``req`` or raise :class:`AdmissionReject`."""
        if _chaos.forced_reject():
            self.reject(req, "reject_storm",
                         "chaos reject_storm injection armed")
        if req.budget_tokens > self.max_tokens:
            self.reject(
                req, "request_too_large",
                f"prompt+max_new = {req.budget_tokens} tokens > "
                f"max_tokens = {self.max_tokens}")
        cfg = self.tenants.get(req.tenant)
        with self._lock:
            # the reject itself (handle fail + timeline finalize +
            # telemetry + event) runs OUTSIDE the lock: a client-thread
            # reject burst must not block the step thread's queue ops
            quota = None
            if cfg.max_inflight is not None or cfg.token_quota is not None:
                n, toks = self._tenant_inflight(req.tenant)
                if cfg.max_inflight is not None and n >= cfg.max_inflight:
                    quota = (f"tenant {req.tenant!r} has {n} in-flight "
                             f">= max_inflight = {cfg.max_inflight}")
                elif cfg.token_quota is not None \
                        and toks + req.budget_tokens > cfg.token_quota:
                    quota = (f"tenant {req.tenant!r} in-flight worst case "
                             f"{toks} + {req.budget_tokens} tokens > "
                             f"token_quota = {cfg.token_quota}")
            depth = len(self._pending)
            full = quota is None and depth >= self.max_pending
            if quota is None and not full:
                self._pending.append(req)
        if quota is not None:
            self.reject(req, "tenant_quota", quota)
        if full:
            self.reject(
                req, "queue_full",
                f"{depth} pending >= max_pending = {self.max_pending}")
        _telemetry.counter("serve.requests", state="admitted").inc()
        _telemetry.gauge("serve.queue_depth").set(self.queue_depth())
        _tracing.emit("serve.admit", request=req.id,
                      prompt_tokens=len(req.prompt),
                      max_new_tokens=req.max_new_tokens,
                      tenant=req.tenant)
        return req

    def restore(self, req):
        """Re-admit a RECOVERED request (``server.recover`` — ISSUE 19)
        with every admission gate bypassed: the dead process already
        admitted it, and its journaled ``begin`` IS the admission
        receipt.  A server killed at full load journals up to
        ``max_pending + max_batch`` unfinished streams (pending plus
        the running batch), so routing recovery through :meth:`submit`
        would ``queue_full``-reject the overflow and break the
        zero-lost-streams guarantee — this is the same deliberate cap
        bypass :meth:`requeue`/:meth:`defer` use for in-flight work.
        Appended (not fronted) so journal order is preserved."""
        with self._lock:
            self._pending.append(req)
        _telemetry.counter("serve.requests", state="admitted").inc()
        _telemetry.gauge("serve.queue_depth").set(self.queue_depth())
        _tracing.emit("serve.admit", request=req.id,
                      prompt_tokens=len(req.prompt),
                      max_new_tokens=req.max_new_tokens,
                      tenant=req.tenant, recovered=True)
        return req

    def reject(self, req, reason, detail=""):
        """Refuse ``req`` with full bookkeeping — fail the handle, count
        it, put it on the timeline — then raise :class:`AdmissionReject`.
        The ONE reject implementation; the server's own gates (pool-size,
        degraded) route through it too."""
        req.fail(f"rejected: {reason}")
        _telemetry.counter("serve.requests", state="rejected").inc()
        _tracing.emit("serve.reject", request=req.id, reason=reason)
        raise AdmissionReject(reason, detail)

    # -- per-step policy (the server's step thread) --------------------------
    def budget_used(self):
        with self._lock:
            return sum(r.budget_tokens for r in self._running)

    def _breaching_tenants(self):
        """Tenant LABELS whose per-tenant SLO burn is breaching, read
        off the last published ``slo_signal`` (tpu_mx/serving/slo.py
        adds a ``tenants`` sub-map per target when tenant-labeled
        series exist).  These are telemetry labels, not raw tenant ids:
        measurement happens under the cardinality-capped label, so a
        past-cap tenant breaches — and boosts — as the aggregated
        ``_other`` group.  Called under the lock; empty set when no
        monitor is armed."""
        sig = self.slo_signal
        if not sig:
            return frozenset()
        out = set()
        for st in sig.get("slos", {}).values():
            for tenant, ts in st.get("tenants", {}).items():
                if ts.get("breaching"):
                    out.add(tenant)
        return out

    def _effective_weight(self, tenant, boosted):
        """``boosted`` holds breaching LABELS — compare through
        ``label_for`` so a tenant measured under the overflow label
        still receives the boost its (aggregated) burn earned."""
        w = self.tenants.get(tenant).weight
        return w * self.slo_boost if label_for(tenant) in boosted else w

    # consecutive gated rounds before a head is admitted regardless: the
    # gate's need estimate ignores shared-prefix reuse (a fully cached
    # prompt may need ZERO fresh blocks), so it must be able to delay
    # but never starve — the escape hands the request to the ordinary
    # pop-and-maybe-defer path, which resolves the cached case exactly
    CAPACITY_GATE_MAX_SKIPS = 4

    def _fits_capacity(self, req):
        """Would-fit admission gate (under the lock): with a published
        ``capacity_signal``, a prefill whose block need exceeds free +
        optimistically-reclaimable capacity is left queued this step —
        popping it could only bounce on ``CacheExhausted`` and stall as
        a deferral.  The bound is approximate in BOTH directions
        (reclaimable is optimistic; the need ignores shared-prefix
        hits), so a gated head escapes after
        :data:`CAPACITY_GATE_MAX_SKIPS` rounds and an admitted prefill
        can still bounce into the ordinary defer path — the gate
        removes the common bounce, it never replaces backpressure."""
        sig = self.capacity_signal
        if not sig:
            return True
        bs = max(int(sig.get("block_size", 1)), 1)
        need = -(-len(req.prompt) // bs)
        if need <= (sig.get("free_blocks", 0)
                    + sig.get("reclaimable_blocks", 0)):
            self._capacity_skips.pop(req, None)
            return True
        skips = self._capacity_skips.get(req, 0) + 1
        if skips >= self.CAPACITY_GATE_MAX_SKIPS:
            self._capacity_skips.pop(req, None)   # anti-starvation escape
            return True
        self._capacity_skips[req] = skips
        return False

    def _pick_next(self, used):
        """The weighted-fair admission pick (under the lock): among the
        per-tenant queue heads that fit the remaining token budget AND
        the pool's would-fit capacity, the tenant with the LOWEST
        virtual time goes next (ties: queue order — ``heads`` preserves
        first-seen order, so keeping the earliest on equal vtime is
        FIFO).  Returns the request, or None when nothing admissible."""
        heads = {}
        for r in self._pending:
            if r.tenant not in heads:
                heads[r.tenant] = r
        if not heads:
            return None
        boosted = self._breaching_tenants()
        if len(heads) == 1:
            # single tenant: the pre-tenancy ORDER bit-for-bit,
            # including stop-at-the-head (no in-tenant reordering) —
            # but the clock still runs, so a tenant that served alone
            # does not look idle-cheap the moment a second one appears
            r = self._pending[0]
            if used + r.budget_tokens > self.max_tokens:
                return None
            if not self._fits_capacity(r):
                return None
            self._charge(r, boosted)
            return r
        best, best_vt = None, None
        for r in heads.values():
            if used + r.budget_tokens > self.max_tokens:
                continue
            if not self._fits_capacity(r):
                continue
            vt = max(self._vtime.get(r.tenant, 0.0), self._vfloor)
            if best is None or vt < best_vt:
                best, best_vt = r, vt
        if best is not None:
            self._charge(best, boosted)
            # bound the vtime map: tenant ids are client-controlled
            # strings, so an adversarial id-per-request stream would
            # otherwise grow it forever.  Pruning idle tenants is
            # harmless — the re-entry floor already handles a returning
            # tenant fairly.
            if len(self._vtime) > 4 * max(len(heads), 16):
                live = ({r.tenant for r in self._pending}
                        | {r.tenant for r in self._running}
                        | {r.tenant for r in self._admitting})
                self._vtime = {t: v for t, v in self._vtime.items()
                               if t in live}
        return best

    def _charge(self, req, boosted):
        """Advance the picked tenant's virtual clock and the system
        floor; remember the charge so a deferred (never-started)
        admission can be refunded on its way back to the queue."""
        vt = max(self._vtime.get(req.tenant, 0.0), self._vfloor)
        cost = req.budget_tokens / self._effective_weight(req.tenant,
                                                          boosted)
        self._vtime[req.tenant] = vt + cost
        self._vfloor = max(self._vfloor, vt)
        self._vtime_charges[req] = cost

    def take_prefills(self):
        """Pop the pending requests admissible THIS step: batch slots
        free and the worst-case token budget respected, ordered by the
        SLO-weighted fair policy across tenants (class docstring) —
        plain FIFO when one tenant is present.  Continuous: runs every
        step, so a finishing sequence's slot is refilled on the very
        next iteration."""
        out = []
        with self._lock:
            used = sum(r.budget_tokens for r in self._running)
            while (self._pending
                   and len(self._running) + len(out) < self.max_batch):
                req = self._pick_next(used)
                if req is None:
                    break
                self._pending.remove(req)
                self._admitting.add(req)
                used += req.budget_tokens
                out.append(req)
            if self._capacity_skips:
                # bound the skip ledger to requests still queued (a
                # drained/rejected request must not pin its handle)
                pending = set(self._pending)
                self._capacity_skips = {
                    r: n for r, n in self._capacity_skips.items()
                    if r in pending}
        if out:
            _telemetry.gauge("serve.queue_depth").set(self.queue_depth())
        return out

    def mark_running(self, req):
        with self._lock:
            req.state = "running"
            self._admitting.discard(req)
            self._vtime_charges.pop(req, None)   # service delivered
            self._running.append(req)

    def decode_batch(self):
        """The sequences to decode this step (continuous: every running,
        unfinished request — finished ones were already evicted)."""
        with self._lock:
            return list(self._running)

    def finish(self, req, reason="length"):
        """Mark ``req`` finished; returns the requests whose cache should
        be evicted NOW (continuous: immediately — the block pool is the
        scarce resource and a finished sequence holds it for no one).
        ``req.finish`` (terminal telemetry: TTFT observe, per-phase
        histograms, the timeline event) runs OUTSIDE the lock — only the
        step thread calls this, and holding the lock through it would
        serialize submitting threads against per-request telemetry."""
        req.finish(reason)
        with self._lock:
            if req in self._running:
                self._running.remove(req)
        return [req]

    def requeue(self, req, front=True, replay=False):
        """Bounce a running request back to pending for a re-run
        (engine restart, cache preemption).  ``replay=True`` (the
        server's prefill-replay arm, ISSUE 19) keeps its committed
        tokens — the recovery prefill replays them in one call;
        ``replay=False`` discards them (legacy prompt replay).
        ``front=True`` preserves arrival order fairness.  The vtime
        charge is NOT refunded: a requeued request consumed real
        service (its interrupted attempt) — unlike a deferral."""
        with self._lock:
            if req in self._running:
                self._running.remove(req)
            self._admitting.discard(req)
            self._vtime_charges.pop(req, None)
            req.reset_generation(keep_tokens=replay)
            if front:
                self._pending.insert(0, req)
            else:
                self._pending.append(req)
        _telemetry.counter("serve.requests", state="requeued").inc()
        _telemetry.gauge("serve.queue_depth").set(self.queue_depth())

    def defer(self, reqs):
        """Push admissions that never STARTED back to the queue front
        (prefill hit cache backpressure).  Unlike :meth:`requeue` this
        neither resets generation nor counts a requeue — a deferred
        request was not re-run, merely not admitted yet — and its
        pick-time vtime charge is REFUNDED: a tenant bouncing on memory
        pressure received no service, so charging it per bounce would
        skew the weighted ratio against exactly the tenant being
        starved."""
        with self._lock:
            for req in reqs:
                self._admitting.discard(req)
                charge = self._vtime_charges.pop(req, None)
                if charge is not None and req.tenant in self._vtime:
                    self._vtime[req.tenant] -= charge
            self._pending[0:0] = list(reqs)
        _telemetry.gauge("serve.queue_depth").set(self.queue_depth())

    def requeue_all_running(self, replay=False):
        """Engine restart / handoff: every in-flight sequence survives
        by going back to pending (newest first so fronted order stays
        FIFO).  ``replay`` as in :meth:`requeue`."""
        with self._lock:
            running = list(self._running)
        for req in reversed(running):
            self.requeue(req, front=True, replay=replay)
        return running

    def drain_running(self):
        """Remove and return every UNFINISHED in-flight request without
        requeueing (degraded shutdown: the server fails them — they were
        never re-admitted, so nothing counts as requeued)."""
        with self._lock:
            out = list(self._running)
            self._running = []
        return out

    def discard(self, req):
        """Drop a request from the scheduler's books with NO state
        change on the handle (a finished padding slot whose cache was
        preempted away — it already delivered its tokens)."""
        with self._lock:
            if req in self._running:
                self._running.remove(req)

    def drain_pending(self):
        """Remove and return EVERY pending request (degraded shutdown:
        the server fails them loudly instead of leaving them queued
        forever)."""
        with self._lock:
            out = list(self._pending)
            self._pending = []
        _telemetry.gauge("serve.queue_depth").set(0)
        return out

    # -- observables ---------------------------------------------------------
    def queue_depth(self):
        with self._lock:
            return len(self._pending)

    def running_count(self):
        with self._lock:
            return len(self._running)

    def idle(self):
        with self._lock:
            return not self._pending and not self._running


class StaticBatchingScheduler(ContinuousBatchingScheduler):
    """The naive static-batching baseline (bench A/B arm — module
    docstring).  Admission waits for a FULL drain; finished sequences
    stay in the decode batch as padding (their decode output is
    discarded by the server) and their cache is only freed when the
    whole batch completes."""

    def __init__(self, max_pending=64, max_batch=8, max_tokens=8192,
                 tenants=None, slo_boost=2.0):
        super().__init__(max_pending=max_pending, max_batch=max_batch,
                         max_tokens=max_tokens, tenants=tenants,
                         slo_boost=slo_boost)
        self._finished = []

    def take_prefills(self):
        with self._lock:
            if self._running or self._finished:
                return []   # static: the whole batch must drain first
        return super().take_prefills()

    def decode_batch(self):
        # finished members keep their slot (and their padding decodes)
        # until the batch drains — the waste continuous batching removes
        with self._lock:
            return list(self._running) + list(self._finished)

    def finish(self, req, reason="length"):
        req.finish(reason)   # terminal telemetry outside the lock
        with self._lock:
            if req in self._running:
                self._running.remove(req)
                self._finished.append(req)
            if self._running:
                return []
            drained = list(self._finished)
            self._finished = []
        return drained

    def requeue_all_running(self, replay=False):
        with self._lock:
            # padding members' cache is freed by the server on restart
            # like everyone else's; only unfinished ones re-run
            self._finished = []
        return super().requeue_all_running(replay=replay)

    def drain_running(self):
        with self._lock:
            self._finished = []   # done already — nothing to fail
        return super().drain_running()

    def discard(self, req):
        with self._lock:
            if req in self._finished:
                self._finished.remove(req)
        super().discard(req)

    def idle(self):
        with self._lock:
            return (not self._pending and not self._running
                    and not self._finished)
