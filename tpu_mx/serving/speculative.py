"""Speculative multi-token decode: draft cheap, verify in one window.

Classic speculative decoding (ISSUE 16): a cheap **proposer** guesses
the next ``K-1`` tokens, the real model verifies the whole guessed
window in ONE batched ``(B, K, H, D)`` attention call against the paged
pool (the widened kernels/paged_attention.py query axis), and the
agreeing prefix is accepted.  Greedy verification makes the scheme
lossless BY CONSTRUCTION: every emitted token is the verify model's own
argmax given the accepted prefix — exactly the token one-at-a-time
decode would have produced — so greedy streams are provably
bit-identical speculative on/off (tests/test_serving.py pins it; the
CI serve tier gates it in both decode arms).  Speculation only changes
how many verify-model STEPS a stream costs: an accepted draft token is
a decode step the engine never ran.

The draft window rides the normal cache machinery: ``reserve_window``
grabs the K slots, the verify forward writes every drafted position's
K/V, and rejection truncates the unaccepted tail
(``PagedKVCache.truncate``) — so a restart mid-draft loses nothing the
server's committed-stream replay doesn't already cover.

Knob (resolved once per engine generation, recorded on the
``serve.decode_path`` event's ``spec_window`` field):

- ``TPUMX_SPECULATIVE`` unset/``0``/``off`` — window 1 (speculation
  off: one token per step, the classic decode loop).
- ``1``/``on`` — the default window (:data:`DEFAULT_WINDOW`).
- an integer ``>= 2`` — that window width.  Anything else raises (the
  loud-config discipline every serving knob follows).
"""
from __future__ import annotations

import os

import numpy as np

__all__ = ["DEFAULT_WINDOW", "resolve_spec_window", "SiblingProposer",
           "accept_prefix"]

_SPEC_ENV = "TPUMX_SPECULATIVE"

# Swept on the Tq axis of tools/paged_sweep.py (ROUND11_NOTES.md): the
# widened kernel's per-window cost grows sublinearly in Tq (the block
# walk is shared), so the window wants to be as wide as the accept rate
# sustains; 4 is where the toy proposer's acceptance still pays for the
# extra verify rows.
DEFAULT_WINDOW = 4


def resolve_spec_window():
    """The draft-window width ``TPUMX_SPECULATIVE`` requests; 1 means
    speculation off (see module docstring)."""
    v = os.environ.get(_SPEC_ENV, "0").strip().lower()
    if v in ("", "0", "off", "no"):
        return 1
    if v in ("1", "on", "yes", "auto"):
        return DEFAULT_WINDOW
    try:
        n = int(v)
    except ValueError:
        raise ValueError(
            f"{_SPEC_ENV}={v!r} is not a recognized speculative setting "
            "— use 0 (off), 1 (default window) or an integer window "
            "width >= 2") from None
    if n < 2:
        raise ValueError(
            f"{_SPEC_ENV}={v!r}: an explicit window must be >= 2 "
            "(1-token windows are just decode; use 0/1 to toggle)")
    return n


class SiblingProposer:
    """The verify model's own weights, evaluated context-FREE: each
    draft step embeds only (token, position) and collapses every
    layer's attention to its own value row (a single-key causal softmax
    is the identity on ``v``), so drafting costs a handful of ``(B, E)``
    matmuls — no cache reads, no O(context) anything.  It is exactly
    the verify model minus context, which is what makes it a sibling:
    same embeddings, same projections, deterministic, free to disagree.

    Acceptance is therefore workload-dependent by design — the engine
    REPORTS the measured ratio (``serve.spec_accept_ratio``) rather
    than assuming one; correctness never depends on it (module
    docstring: greedy verification is lossless at any accept rate)."""

    def __init__(self, model):
        self.model = model

    def draft(self, last_tokens, positions, n):
        """``n`` greedy draft tokens per row: ``last_tokens`` ``(B,)``
        are the stream heads, ``positions`` ``(B,)`` their absolute
        positions.  Returns int64 ``(B, n)`` — draft ``j`` chained from
        draft ``j-1`` (the window the verify step will judge)."""
        m = self.model
        cur = np.asarray(last_tokens, np.int64)
        pos = np.asarray(positions, np.int64)
        out = np.empty((cur.shape[0], n), np.int64)
        for j in range(n):
            p = np.minimum(pos + j, m.max_positions - 1)
            h = m.tok_emb[cur % m.vocab_size] + m.pos_emb[p]
            for i in range(m.num_layers):
                _, _, v = m.layer_qkv(i, h)
                h = m.layer_combine(i, h, v)
            cur = np.argmax(m.logits(h), axis=-1)
            out[:, j] = cur
        return out


def accept_prefix(draft_row, out_row):
    """How many DRAFTED tokens the verify step confirmed: the longest
    ``j`` run where ``draft_row[j] == out_row[j-1]`` for ``j = 1..K-1``
    (``draft_row[0]`` is the stream head, never judged; ``out_row[j]``
    is the verify model's argmax after consuming ``draft_row[:j+1]``).
    The emitted tokens are ``out_row[:accepted+1]`` — the confirmed
    drafts plus the verify model's one free next token."""
    a = 0
    for j in range(1, len(draft_row)):
        if int(draft_row[j]) != int(out_row[j - 1]):
            break
        a += 1
    return a
