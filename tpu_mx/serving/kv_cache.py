"""Paged KV cache: fixed-size blocks, a free-list allocator, block tables.

The serving runtime's memory manager (docs/serving.md).  A training step
owns one batch for its whole lifetime; a serving engine juggles thousands
of concurrent sequences whose lengths are unknown at admission.  Naive
per-sequence contiguous KV buffers either over-reserve (max_len for every
request — most of it never used) or reallocate-and-copy as sequences grow.
The paged design (vLLM's PagedAttention insight, applied to this stack's
layout) fixes both:

- **Blocks**: K and V live in ONE preallocated pool per layer, shaped
  ``(num_blocks, block_size, num_heads, head_dim)``.  A sequence's cache
  is a list of block ids — its **block table** — plus a length; logically
  contiguous, physically scattered.
- **Free-list allocator**: :class:`BlockAllocator` hands out block ids
  from a LIFO free list under one lock.  Exhaustion raises
  :class:`CacheExhausted` — the scheduler's backpressure signal (requeue /
  reject), NEVER an allocation attempt that OOMs the process.
- **Refcounts** (ISSUE 12): every held block carries a reference count.
  ``alloc`` hands out blocks at one reference; ``incref`` adds sharers
  (the shared-prefix index, a :meth:`PagedKVCache.fork` sibling);
  ``free`` DECREMENTS and only returns a block to the free list at
  zero.  Freeing a sequence whose blocks another live sequence shares
  therefore releases references, never data — the invariant behind
  "preemption never evicts a block another live sequence shares".
  Double-free (freeing an unheld block) stays loud.
- **O(1) append**: generating one token costs at most one free-list pop
  (amortized ``1/block_size`` pops) and one slot write — independent of
  how long the sequence already is.
- **Copy-free reuse**: finishing a sequence pushes its blocks straight
  back on the free list; the next sequence overwrites them.  No zeroing,
  no compaction, no copies.

Two storage modes share the allocator/table semantics (``storage=``):

- ``"host"`` (default): pools are host numpy — the CPU-testable layout
  tier-1 exercises, read through the dense-gather fallback.
- ``"device"``: pools are per-layer **device-resident** jax arrays
  (HBM on TPU); ``prefill``/``write``/``write_batch`` mutate them with
  jitted in-place index updates (buffer-donated where the backend
  supports donation) and the paged-attention decode kernel indexes them
  by raw block table (``tpu_mx/kernels/paged_attention.py``) — the
  cache never round-trips through the host on the decode path
  (docs/DIVERGENCES.md #27).  Same allocator, same block-table
  bookkeeping, same exhaustion-is-backpressure contract.

All public methods are thread-safe for BOOKKEEPING: the allocator has
its own lock and the table map is guarded by the cache lock, so a
scheduler thread can admit/evict while tests hammer alloc/free
concurrently (tests/test_serving.py).  Device-pool ARRAY access (writes
and :meth:`pool` readers) additionally belongs to the single engine
step thread: donation invalidates the previous buffer, so a reader
holding a stale pool reference across a write would observe a consumed
array — the serving data plane is single-threaded by design
(docs/serving.md), which is exactly this discipline.
"""
from __future__ import annotations

import functools
import itertools
import threading
import time
from collections import deque

import numpy as np

from ..base import MXNetError
from .. import telemetry as _telemetry
from .. import tracing as _tracing
from . import accounting as _accounting
from .accounting import INDEX_TENANT, CapacityLedger
from .prefix_cache import PrefixIndex, prefix_sharing_enabled
from .tenancy import DEFAULT_TENANT

__all__ = ["CacheExhausted", "BlockAllocator", "PagedKVCache",
           "PrefillPlan", "prefix_sharing_enabled"]

# ids for pinned prefill plans' ledger holders — unique per process so a
# forensic record never conflates two concurrently pinned plans
_plan_ids = itertools.count()


def _next_pow2(n):
    return 1 if n <= 1 else 1 << (int(n) - 1).bit_length()


# Jitted device-pool updaters, built on first device-mode cache.  Python
# scalars/arrays trace as arguments, so repeated writes share one
# compilation per operand shape; donating the pool makes the update
# genuinely in-place (measured ~9us vs ~6ms copy-on-write for a 16 MiB
# pool on this host's CPU backend) — which is why pool handles are
# step-thread-owned: the pre-write array object is CONSUMED by every
# write (module docstring).
_DEV_OPS = None


def _dev_ops():
    global _DEV_OPS
    if _DEV_OPS is None:
        import jax

        donate = (0,)

        @functools.partial(jax.jit, donate_argnums=donate)
        def write_slot(pool, bid, off, val):
            return pool.at[bid, off].set(val.astype(pool.dtype))

        @functools.partial(jax.jit, donate_argnums=donate)
        def write_rows(pool, bids, offs, vals):
            return pool.at[bids, offs].set(vals.astype(pool.dtype))

        @functools.partial(jax.jit, donate_argnums=donate)
        def write_blocks(pool, bids, chunk):
            return pool.at[bids].set(chunk.astype(pool.dtype))

        @functools.partial(jax.jit, donate_argnums=donate)
        def copy_block(pool, dst, src):
            # the copy-on-write primitive: one block's slots duplicated
            # on-device (the pool never round-trips through the host)
            return pool.at[dst].set(pool[src])

        _DEV_OPS = (write_slot, write_rows, write_blocks, copy_block)
    return _DEV_OPS


class CacheExhausted(MXNetError):
    """The block pool has no room for this allocation.  This is the
    BACKPRESSURE signal, not an error to crash on: the scheduler catches
    it and requeues (decode append) or defers admission (prefill) —
    docs/serving.md "Backpressure"."""


class BlockAllocator:
    """LIFO free-list allocator over ``num_blocks`` fixed-size blocks.

    ``alloc(n)`` is all-or-nothing: either all ``n`` ids are handed out
    or :class:`CacheExhausted` is raised and the free list is untouched —
    a partial grab would leak blocks on the error path.  ``free`` rejects
    ids the allocator did not hand out (double-free corrupts the pool
    silently; loud is the only acceptable failure mode).

    **Capacity ledger** (ISSUE 14): every reference additionally carries
    an attribution — the ``holder=`` a caller names on
    ``alloc``/``incref``/``free`` (a sequence, the prefix index, a
    pinned plan; ``None`` files under the ``_anon`` holder, so bare
    callers stay ledgered).  The ledger mutates under THIS lock, next to
    the refcount it mirrors, which is what makes ``audit()``'s identity
    — per block, attributed refs == refcount; per tenant, amortized
    bytes sum exactly to pool-used bytes — hold at every instant
    (tpu_mx/serving/accounting.py)."""

    def __init__(self, num_blocks, block_bytes=1):
        if int(num_blocks) < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        self.num_blocks = int(num_blocks)
        self._lock = threading.Lock()
        # LIFO: recently freed blocks are re-handed first (their pages are
        # the warmest — copy-free reuse on sequence completion)
        self._free = list(range(self.num_blocks - 1, -1, -1))
        self._held = set()
        self._refs = {}   # block id -> reference count (held blocks only)
        self.ledger = CapacityLedger(block_bytes)

    def alloc(self, n=1, holder=None):
        """``n`` block ids at one reference each, or raise
        :class:`CacheExhausted` (free list untouched — all-or-nothing).
        ``holder`` attributes the references in the capacity ledger."""
        n = int(n)
        with self._lock:
            if n > len(self._free):
                raise CacheExhausted(
                    f"KV cache exhausted: need {n} block(s), "
                    f"{len(self._free)}/{self.num_blocks} free — "
                    "backpressure, not OOM: requeue or reject")
            ids = [self._free.pop() for _ in range(n)]
            self._held.update(ids)
            for bid in ids:
                self._refs[bid] = 1
            self.ledger.hold(ids, holder)
            self.ledger.note_used(len(self._held))
        return ids

    def incref(self, block_ids, holder=None):
        """Add one reference to each (held) block — a sharer: the
        shared-prefix index, or a :meth:`PagedKVCache.fork` sibling.
        Increfing a block the allocator did not hand out is as loud as
        double-freeing one (a stale id would resurrect a freed block)."""
        with self._lock:
            for bid in block_ids:
                if bid not in self._held:
                    raise MXNetError(
                        f"BlockAllocator.incref: block {bid} is not held "
                        "(stale or foreign id) — sharing it would "
                        "resurrect freed storage")
            for bid in block_ids:
                self._refs[bid] += 1
            self.ledger.hold(block_ids, holder)

    def free(self, block_ids, holder=None):
        """Drop one reference per block; a block reaching ZERO
        references returns to the free list (copy-free: contents are
        left in place for the next owner to overwrite).  A block another
        holder still references survives — which is why freeing a
        preempted sequence can never corrupt a sequence sharing its
        prefix.  Freeing an unheld block (double free) stays loud, and
        so does naming a ``holder`` that does not hold the reference
        (the ledger's attribution would silently drift otherwise)."""
        with self._lock:
            for bid in block_ids:
                if bid not in self._held:
                    raise MXNetError(
                        f"BlockAllocator.free: block {bid} is not held "
                        "(double free or foreign id) — the pool would be "
                        "silently corrupted")
            # the ledger validates the holder's attribution BEFORE any
            # refcount moves, so a mis-attributed free changes nothing
            self.ledger.release(block_ids, holder)
            for bid in block_ids:
                self._refs[bid] -= 1
                if self._refs[bid] == 0:
                    del self._refs[bid]
                    self._held.discard(bid)
                    self._free.append(bid)

    def reassign(self, block_ids, src, dst):
        """Move the attributed ownership of one reference per block from
        holder ``src`` to ``dst`` WITHOUT touching refcounts — the
        commit-prefill handoff (a plan's pins become the registered
        sequence's references)."""
        with self._lock:
            for bid in block_ids:
                if bid not in self._held:
                    raise MXNetError(
                        f"BlockAllocator.reassign: block {bid} is not "
                        "held — cannot move attribution of a freed block")
            self.ledger.transfer(block_ids, src, dst)

    def describe(self, holder, kind=None, tenant=None, pinned=None):
        """Attach attribution metadata to a ledger holder (under the
        allocator lock, like every ledger mutation)."""
        with self._lock:
            self.ledger.describe(holder, kind=kind, tenant=tenant,
                                 pinned=pinned)

    def _fragmentation_locked(self):
        """1 - (largest contiguous free-id run / free blocks); 0 when
        the free list is empty.  Any block satisfies any allocation, so
        this is a locality signal (how scattered reuse has become), not
        an allocation-failure predictor."""
        if not self._free:
            return 0.0
        free = sorted(self._free)
        best = run = 1
        for a, b in zip(free, free[1:]):
            run = run + 1 if b == a + 1 else 1
            if run > best:
                best = run
        return 1.0 - best / len(free)

    def fragmentation(self):
        """Free-list fragmentation in [0, 1] (see the locked helper)."""
        with self._lock:
            return self._fragmentation_locked()

    def capacity_snapshot(self):
        """One consistent read of the pool's capacity state: counts,
        fragmentation, high watermark, every ledger holder row and the
        per-tenant attribution — the forensic record's raw material
        (holders and tenants share one totals pass — ledger.views)."""
        with self._lock:
            holders, tenants = self.ledger.views()
            return {
                "num_blocks": self.num_blocks,
                "block_bytes": self.ledger.block_bytes,
                "used_blocks": len(self._held),
                "free_blocks": len(self._free),
                "total_refs": sum(self._refs.values()),
                "high_watermark_blocks": self.ledger.high_watermark,
                "fragmentation": self._fragmentation_locked(),
                "holders": holders,
                "tenants": tenants,
            }

    def audit(self):
        """Verify the accounting identity (ledger vs refcounts, exact
        per-tenant byte sums — accounting.CapacityLedger.audit) and
        return the audit report; raises on any violation.  The serve CI
        tier runs this after every chaos storm."""
        with self._lock:
            report = self.ledger.audit(dict(self._refs))
            report["free_blocks"] = len(self._free)
            report["num_blocks"] = self.num_blocks
            report["fragmentation"] = self._fragmentation_locked()
            return report

    def refcount(self, block_id):
        """The block's live reference count (0 when not held)."""
        with self._lock:
            return self._refs.get(block_id, 0)

    def refcounts(self):
        """``{block_id: refcount}`` for every held block — the audit
        surface: after every sequence is freed and the prefix index
        dropped, this must be empty (CI's post-storm allocator audit)."""
        with self._lock:
            return dict(self._refs)

    @property
    def available(self):
        """Blocks currently on the free list."""
        with self._lock:
            return len(self._free)

    @property
    def used(self):
        with self._lock:
            return len(self._held)

    def utilization(self):
        """Used fraction of the pool, in [0, 1]."""
        with self._lock:
            return len(self._held) / self.num_blocks


class _Sequence:
    __slots__ = ("blocks", "length", "holder", "tenant")

    def __init__(self, holder=None, tenant=DEFAULT_TENANT):
        self.blocks = []
        self.length = 0
        self.holder = holder    # the sequence's ledger holder id
        self.tenant = tenant


class PrefillPlan:
    """A pinned prefix match (:meth:`PagedKVCache.match_prefix`):
    ``blocks`` are increfed physical ids covering the leading
    ``tokens_matched`` prompt tokens.  A plan MUST flow into exactly one
    of :meth:`PagedKVCache.commit_prefill` (which takes ownership of the
    pins) or :meth:`PagedKVCache.abandon_plan` (which releases them) —
    dropping it on the floor leaks references until the audit catches
    it."""

    __slots__ = ("blocks", "tokens_matched", "holder", "_consumed")

    def __init__(self, blocks, tokens_matched, holder=None):
        self.blocks = list(blocks)
        self.tokens_matched = int(tokens_matched)
        # the plan's capacity-ledger holder id (pinned attribution):
        # commit reassigns it to the sequence, abandon releases it
        self.holder = holder
        # a plan's pins are released exactly once (by commit_prefill or
        # abandon_plan).  Without this flag a double abandon — or an
        # abandon after commit — would free() blocks the plan no longer
        # owns, silently stealing ANOTHER holder's reference (the index
        # or a live sequence) and eventually serving a recycled block's
        # K/V as someone's cached prefix.  The allocator cannot catch
        # that (the block is legitimately held); the plan must.
        self._consumed = False

    def consume(self):
        """Mark the pins as spent; raises on a second consumption —
        the refcount analog of 'double-free stays loud'."""
        if self._consumed:
            raise MXNetError(
                "PrefillPlan already consumed (committed or abandoned) — "
                "releasing its pins again would steal another holder's "
                "reference and corrupt served K/V")
        self._consumed = True

    def __repr__(self):
        return (f"PrefillPlan({len(self.blocks)} shared blocks, "
                f"{self.tokens_matched} tokens"
                + (", consumed)" if self._consumed else ")"))


class PagedKVCache:
    """Block-pooled K/V storage for many concurrent sequences.

    One pool pair per call site::

        cache = PagedKVCache(num_layers=2, num_heads=4, head_dim=16,
                             block_size=16, num_blocks=256)
        cache.prefill("req-1", k, v)        # bulk-fill: k/v (N, L, H, D)
        pos = cache.reserve("req-1")        # O(1) append: one slot
        cache.write("req-1", layer, k1, v1) # fill the reserved slot
        kd, vd, lens = cache.gather_batch(["req-1", ...], layer)
        cache.free_sequence("req-1")        # blocks back to the free list

    ``reserve`` + per-layer ``write`` split the append because a decoder
    computes layer i's K/V only after layer i-1's attention — the slot is
    reserved once per token (the O(1) step), then each layer writes its
    projection into it as the forward proceeds.

    ``gather_batch`` is the dense-gather decode fallback: it materializes
    a padded ``(B, Lmax, H, D)`` view by copying block slices — O(total
    context) per call, the documented cost of serving attention without
    the paged kernel (docs/DIVERGENCES.md #27).  The paged decode path
    instead reads :meth:`batch_tables` + :meth:`pool` and indexes the
    pool in-kernel.
    """

    def __init__(self, num_layers, num_heads, head_dim, block_size=16,
                 num_blocks=256, dtype=np.float32, storage="host",
                 share_prefix=None, forensics=None):
        if storage not in ("host", "device"):
            raise ValueError(f"storage must be 'host' or 'device', "
                             f"got {storage!r}")
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.block_size = int(block_size)
        if self.block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        # per-token K/V footprint across all layers, both pools — the
        # unit of the prefill-bytes accounting, and (× block_size) the
        # capacity ledger's block-bytes denomination
        self._token_bytes = (self.num_layers * self.num_heads
                             * self.head_dim * 2 * np.dtype(dtype).itemsize)
        self.allocator = BlockAllocator(
            num_blocks, block_bytes=self._token_bytes * self.block_size)
        self.storage = storage
        # exhaustion forensics (ISSUE 14): a bounded ring of capacity
        # records — one per genuine CacheExhausted and per prefix-index
        # pressure eviction — persisted (rolling, atomic) as
        # <forensics>-capacity.json when a path prefix is armed
        self._forensics = deque(maxlen=256)
        self._forensics_path = (f"{forensics}-capacity.json"
                                if forensics else None)
        self._forensics_dumped = None   # monotonic time of last disk dump
        layer_shape = (self.allocator.num_blocks, self.block_size,
                       self.num_heads, self.head_dim)
        if storage == "device":
            try:
                import jax.numpy as jnp
            except ImportError:
                raise MXNetError(
                    "PagedKVCache: storage='device' needs jax — use the "
                    "default host storage (dense-gather decode) without "
                    "it") from None
            # per-layer pools (not one (L, N, ...) array): layer reads on
            # the decode hot path must be O(1) handle lookups, never a
            # per-step slice copy of the whole pool
            self._k_dev = [jnp.zeros(layer_shape, dtype)
                           for _ in range(self.num_layers)]
            self._v_dev = [jnp.zeros(layer_shape, dtype)
                           for _ in range(self.num_layers)]
            self.k_blocks = self.v_blocks = None
        else:
            shape = (self.num_layers,) + layer_shape
            self.k_blocks = np.zeros(shape, dtype)
            self.v_blocks = np.zeros(shape, dtype)
        self._lock = threading.RLock()
        self._seqs = {}
        # shared-prefix index (ISSUE 12): None = every prefill is
        # private (the pre-sharing behavior, bit-for-bit).  The knob
        # defaults to the TPUMX_PREFIX_SHARING env resolution so an
        # engine, the bench arms, and a bare test cache all agree.
        if share_prefix is None:
            share_prefix = prefix_sharing_enabled()
        if share_prefix and np.dtype(dtype) != np.float32:
            # the suffix prefill attends over PREFIX K/V read back from
            # the pool; a quantized pool (f16/bf16) would feed it
            # pool-rounded values where the sharing-off arm recomputes
            # the prefix at model precision — silently different logits
            # is the one failure mode sharing must never have, so a
            # lossy pool refuses loudly instead (docs/DIVERGENCES.md
            # #28; widen by writing the index's compute-precision copy
            # if a quantized shared pool is ever needed)
            raise ValueError(
                f"share_prefix requires a float32 pool (got "
                f"{np.dtype(dtype).name}): a lossy pool dtype would "
                "break the sharing-on/off bit-equality guarantee")
        self.prefix = PrefixIndex(self.block_size) if share_prefix else None
        self._prompt_tokens = 0     # tokens requested across prefills
        self._cached_tokens = 0     # of those, served from the index
        self._cow_copies = 0

    @property
    def device_resident(self):
        """True when the block pools live on the accelerator (jax
        arrays) rather than in host numpy — the `serve.
        pool_device_resident` gauge's source of truth."""
        return self.storage == "device"

    # -- bookkeeping ---------------------------------------------------------
    def _entry(self, seq_id):
        try:
            return self._seqs[seq_id]
        except KeyError:
            raise MXNetError(f"PagedKVCache: unknown sequence {seq_id!r} "
                             "(never prefilled, or already freed)") from None

    def has_sequence(self, seq_id):
        with self._lock:
            return seq_id in self._seqs

    def length(self, seq_id):
        """Tokens currently cached for ``seq_id`` (reserved slots count)."""
        with self._lock:
            return self._entry(seq_id).length

    def block_table(self, seq_id):
        """The sequence's block-id table (a copy), in position order."""
        with self._lock:
            return list(self._entry(seq_id).blocks)

    def num_sequences(self):
        with self._lock:
            return len(self._seqs)

    def utilization(self):
        return self.allocator.utilization()

    def blocks_for(self, num_tokens):
        """Blocks a ``num_tokens``-long prefill needs (admission math)."""
        return -(-int(num_tokens) // self.block_size)

    # -- writes --------------------------------------------------------------
    def _alloc(self, n, holder=None):
        """``allocator.alloc`` with prefix-cache pressure relief: on
        exhaustion, least-recently-matched index-only prefixes are
        released and the allocation retried ONCE.  When the pool is
        genuinely full of live sequence data, :class:`CacheExhausted`
        propagates — the backpressure contract is unchanged, the index
        merely never stands between a live request and free memory.
        Both the pressure eviction and the genuine exhaustion leave a
        capacity forensic record naming every live holder (ISSUE 14).
        Called under the cache lock."""
        try:
            return self.allocator.alloc(n, holder=holder)
        except CacheExhausted:
            if self.prefix is None:
                self._record_forensic("exhaustion", need=n)
                raise
            released = self.prefix.release(self.allocator, n)
            if released:
                _telemetry.counter("serve.prefix_evictions").inc(released)
                _tracing.emit("serve.prefix_evict", released=released,
                              need=int(n))
                self._record_forensic("pressure_evict", need=n,
                                      released=released)
            try:
                return self.allocator.alloc(n, holder=holder)
            except CacheExhausted:
                self._record_forensic("exhaustion", need=n)
                raise

    def _record_forensic(self, kind, need, released=0):
        """Snapshot WHO holds the pool at a capacity event — every
        holder (sequence/index/plan) with its tenant, block counts,
        pinned/shared state and age — into the bounded forensic ring,
        and persist the ring (rolling, atomic) when a path is armed.
        A ``CacheExhausted`` additionally lands on the flight-recorder
        timeline so a backpressure incident's black box names the
        forensic file.  Best-effort: forensics must never turn
        backpressure into a crash.  Called under the cache lock."""
        snap = self.allocator.capacity_snapshot()
        rec = {"kind": kind, "ts": time.time(), "need": int(need),
               "free": snap["free_blocks"], "released": int(released),
               "pool": {k: snap[k] for k in
                        ("num_blocks", "block_bytes", "used_blocks",
                         "total_refs", "high_watermark_blocks",
                         "fragmentation")},
               "holders": snap["holders"], "tenants": snap["tenants"]}
        self._forensics.append(rec)
        if kind == "exhaustion":
            _tracing.emit("serve.capacity_exhausted", need=int(need),
                          free=int(snap["free_blocks"]),
                          holders=len(snap["holders"]),
                          forensic=self._forensics_path or "")
        # disk dumps are rate-limited (>= 1 s apart, first record
        # always): the RING holds every record regardless, but a
        # sustained overload storm raises CacheExhausted per bounced
        # prefill and an O(ring) atomic rewrite under the cache lock
        # per event would stall the data plane exactly when it is
        # already exhausted.  flush_forensics() force-syncs at
        # teardown/audit time.
        now = time.monotonic()
        if self._forensics_path and (self._forensics_dumped is None
                                     or now - self._forensics_dumped
                                     >= 1.0):
            self._forensics_dumped = now
            try:
                _accounting.dump_forensics(self._forensics_path,
                                           self._forensics)
            except Exception:  # noqa: BLE001 — forensics are best-effort
                pass

    def forensic_records(self):
        """The in-memory capacity forensic ring (newest last)."""
        with self._lock:
            return list(self._forensics)

    def flush_forensics(self):
        """Force-sync the forensic ring to disk (bypassing the dump
        rate limit) — teardown and post-storm audit call this so the
        on-disk record set matches the ring exactly.  Returns the path
        written, or None (unarmed / empty ring)."""
        with self._lock:
            if not self._forensics_path or not self._forensics:
                return None
            self._forensics_dumped = time.monotonic()
            return _accounting.dump_forensics(self._forensics_path,
                                              self._forensics)

    def _fill(self, blocks, k, v, offset=0):
        """Write ``k``/``v`` (``(num_layers, T, H, D)``) into ``blocks``
        starting at slot ``offset`` of the first block (``offset`` is
        the in-block remainder of a block-aligned prefix — 0 everywhere
        today because only full blocks are shared).  Called under the
        cache lock, blocks privately owned by the caller."""
        length = k.shape[1]
        bs = self.block_size
        if self.storage == "device":
            _, _, write_blocks, _ = _dev_ops()
            nb = len(blocks)
            pad = nb * bs - length - offset
            bids = np.asarray(blocks, np.int32)
            for layer in range(self.num_layers):
                # one scatter per pool per layer: the prompt's K/V
                # crosses to the device once, zero-padded to whole
                # blocks (the tail slots are this sequence's own
                # future append slots)
                ck = np.pad(k[layer], ((offset, pad), (0, 0), (0, 0)))
                cv = np.pad(v[layer], ((offset, pad), (0, 0), (0, 0)))
                self._k_dev[layer] = write_blocks(
                    self._k_dev[layer], bids,
                    ck.reshape(nb, bs, *ck.shape[1:]))
                self._v_dev[layer] = write_blocks(
                    self._v_dev[layer], bids,
                    cv.reshape(nb, bs, *cv.shape[1:]))
        else:
            for i, bid in enumerate(blocks):
                lo = max(i * bs - offset, 0)
                hi = min((i + 1) * bs - offset, length)
                s0 = offset if i == 0 else 0
                self.k_blocks[:, bid, s0:s0 + hi - lo] = k[:, lo:hi]
                self.v_blocks[:, bid, s0:s0 + hi - lo] = v[:, lo:hi]

    def _account_prefill(self, computed_tokens, cached_tokens):
        """Prefill byte accounting + the hit-ratio gauge (under the
        cache lock; telemetry's registry lock is a leaf)."""
        self._prompt_tokens += computed_tokens + cached_tokens
        self._cached_tokens += cached_tokens
        _telemetry.counter("serve.prefill_bytes").inc(
            computed_tokens * self._token_bytes)
        if cached_tokens:
            _telemetry.counter("serve.prefix_hits").inc()
            _telemetry.counter("serve.prefill_bytes_saved").inc(
                cached_tokens * self._token_bytes)
        if self._prompt_tokens:
            _telemetry.gauge("serve.prefix_hit_ratio").set(
                self._cached_tokens / self._prompt_tokens)

    def prefill(self, seq_id, k, v, tokens=None, tenant=None):
        """Bulk-fill a new sequence's blocks in one call.

        ``k``/``v``: ``(num_layers, L, num_heads, head_dim)``.  Allocates
        exactly ``ceil(L / block_size)`` blocks all-or-nothing — on
        :class:`CacheExhausted` nothing is registered, so the scheduler
        can requeue the request and retry after an eviction.  ``tokens``
        (the prompt's token ids, optional) lets the shared-prefix index
        learn this sequence's full blocks for future reuse — omitted,
        the prefill stays private (the pre-sharing behavior).
        ``tenant`` is the capacity ledger's attribution key (defaults
        to the single-tenant default)."""
        k = np.asarray(k)
        v = np.asarray(v)
        want = (self.num_layers, k.shape[1], self.num_heads, self.head_dim)
        if k.shape != want or v.shape != want:
            raise ValueError(
                f"prefill: k/v must be (num_layers={self.num_layers}, L, "
                f"H={self.num_heads}, D={self.head_dim}); got {k.shape} / "
                f"{v.shape}")
        length = k.shape[1]
        if length < 1:
            raise ValueError("prefill: empty prompt")
        if tokens is not None and len(tokens) != length:
            raise ValueError(f"prefill: {len(tokens)} tokens for {length} "
                             "K/V positions")
        tenant = DEFAULT_TENANT if tenant is None else str(tenant)
        holder = f"seq:{seq_id}"
        with self._lock:
            if seq_id in self._seqs:
                raise MXNetError(f"prefill: sequence {seq_id!r} already "
                                 "cached (free it first)")
            blocks = self._alloc(self.blocks_for(length), holder=holder)
            self.allocator.describe(holder, kind="sequence", tenant=tenant)
            # fill BEFORE publishing in _seqs: a concurrent gather must
            # never see a registered-but-empty sequence (all-zero K/V
            # would be silently wrong logits, not an error)
            self._fill(blocks, k, v)
            entry = _Sequence(holder=holder, tenant=tenant)
            entry.blocks = blocks
            entry.length = length
            self._seqs[seq_id] = entry
            if self.prefix is not None and tokens is not None:
                self.prefix.insert(tokens, blocks, self.allocator)
            self._account_prefill(length, 0)

    # -- shared-prefix prefill (ISSUE 12) ------------------------------------
    def match_prefix(self, tokens, tenant=None):
        """The longest indexed full-block prefix of ``tokens``, PINNED:
        the matched blocks are increfed under the lock so pressure
        eviction can never reuse them between the match and the commit.
        Returns a :class:`PrefillPlan` or None (sharing off, or no
        match).  Every plan must reach :meth:`commit_prefill` or
        :meth:`abandon_plan`.  The pins are ledgered as a ``plan``
        holder under ``tenant`` — a backpressure forensic taken
        mid-plan attributes the pinned blocks to the tenant whose
        prefill pinned them."""
        if self.prefix is None:
            return None
        with self._lock:
            blocks, m = self.prefix.match(tokens)
            if not m:
                return None
            holder = f"plan:{next(_plan_ids)}"
            self.allocator.incref(blocks, holder=holder)
            self.allocator.describe(
                holder, kind="plan",
                tenant=DEFAULT_TENANT if tenant is None else str(tenant),
                pinned=True)
            return PrefillPlan(blocks, m, holder=holder)

    def gather_plan(self, plan):
        """The pinned prefix's K/V as host ``(num_layers, m, H, D)``
        arrays — the suffix prefill's attention operands.  A device pool
        pays one fetch here; acceptable because prefill is host-resident
        anyway (docs/DIVERGENCES.md #27) and the fetch replaces the
        whole prefix's projection matmuls."""
        m = plan.tokens_matched
        ks = np.empty((self.num_layers, m, self.num_heads, self.head_dim),
                      np.float32)
        vs = np.empty_like(ks)
        for layer in range(self.num_layers):
            kp, vp = self.pool(layer)
            if self.storage == "device":
                import jax.numpy as jnp
                # tpumx-lint: disable=hot-path-purity -- prefill-path
                # fetch of the shared prefix (one gather per layer per
                # SHARED prefill, replacing the prefix's full projection
                # compute); decode never takes this path
                idx = jnp.asarray(plan.blocks, jnp.int32)
                kp, vp = np.asarray(kp[idx]), np.asarray(vp[idx])
            else:
                kp, vp = kp[plan.blocks], vp[plan.blocks]
            ks[layer] = kp.reshape(-1, self.num_heads, self.head_dim)[:m]
            vs[layer] = vp.reshape(-1, self.num_heads, self.head_dim)[:m]
        return ks, vs

    def commit_prefill(self, seq_id, plan, k, v, tokens, tenant=None):
        """Register ``seq_id`` as the pinned prefix plus the computed
        suffix: ``k``/``v`` are ``(num_layers, S, H, D)`` projections
        for ``tokens[plan.tokens_matched:]``.  All-or-nothing like
        :meth:`prefill`: on ANY failure (suffix allocation hitting
        genuine exhaustion included) the plan's pins are released and
        nothing is registered — the scheduler defers and the retry
        re-plans from scratch.  On success the plan's pinned ledger
        attribution is reassigned to the sequence's holder."""
        k = np.asarray(k)
        v = np.asarray(v)
        m = plan.tokens_matched
        length = m + k.shape[1]
        tenant = DEFAULT_TENANT if tenant is None else str(tenant)
        holder = f"seq:{seq_id}"
        with self._lock:
            plan.consume()   # pins spent here, succeed or fail
            fresh = []
            published = False
            try:
                if seq_id in self._seqs:
                    raise MXNetError(f"commit_prefill: sequence {seq_id!r} "
                                     "already cached (free it first)")
                if length != len(tokens):
                    raise ValueError(
                        f"commit_prefill: {len(tokens)} tokens vs "
                        f"{m} matched + {k.shape[1]} suffix positions")
                if m % self.block_size != 0 or k.shape[1] < 1:
                    raise ValueError(
                        f"commit_prefill: matched prefix ({m}) must be "
                        f"block-aligned with a non-empty suffix")
                want = (self.num_layers, k.shape[1], self.num_heads,
                        self.head_dim)
                if k.shape != want or v.shape != want:
                    raise ValueError(
                        f"commit_prefill: suffix k/v must be {want}, got "
                        f"{k.shape} / {v.shape}")
                fresh = self._alloc(self.blocks_for(length)
                                    - len(plan.blocks), holder=holder)
                self.allocator.describe(holder, kind="sequence",
                                        tenant=tenant)
                self._fill(fresh, k, v)
                entry = _Sequence(holder=holder, tenant=tenant)
                entry.blocks = plan.blocks + fresh
                entry.length = length
                self._seqs[seq_id] = entry
                published = True
                self.prefix.insert(tokens, entry.blocks, self.allocator)
                # LAST, so the except arm below can still release the
                # pins under the plan's holder: the pinned attribution
                # becomes the sequence's (refcounts untouched)
                self.allocator.reassign(plan.blocks, plan.holder, holder)
            except BaseException:
                # ALL-or-nothing: unregister (only what THIS call
                # published — the already-cached guard's failure must
                # not destroy the pre-existing live sequence), release
                # the plan's pins AND any fresh blocks allocated above —
                # a fill/insert fault must not leak held refcounts (the
                # post-storm audit would catch it, after the pool had
                # already shrunk) or publish a half-built sequence
                if published:
                    self._seqs.pop(seq_id, None)
                if fresh:
                    self.allocator.free(fresh, holder=holder)
                self.allocator.free(plan.blocks, holder=plan.holder)
                raise
            self._account_prefill(k.shape[1], m)

    def abandon_plan(self, plan):
        """Release a plan's pins without committing (the model faulted
        between match and commit).  Like :meth:`commit_prefill` this
        consumes the plan — a second release raises instead of stealing
        another holder's reference."""
        with self._lock:
            plan.consume()
            self.allocator.free(plan.blocks, holder=plan.holder)

    def fork(self, parent_id, child_id, tenant=None):
        """Register ``child_id`` sharing ALL of ``parent_id``'s blocks
        (one incref per block) — the parallel-sampling shape: N
        generations from one prompt pay one prefill and one copy of the
        prompt's KV.  Both siblings copy-on-write their shared tail
        block on their next divergent append (:meth:`reserve`).
        ``tenant`` defaults to the parent's ledger attribution."""
        with self._lock:
            if child_id in self._seqs:
                raise MXNetError(f"fork: sequence {child_id!r} already "
                                 "cached (free it first)")
            parent = self._entry(parent_id)
            holder = f"seq:{child_id}"
            self.allocator.incref(parent.blocks, holder=holder)
            self.allocator.describe(
                holder, kind="sequence",
                tenant=parent.tenant if tenant is None else str(tenant))
            entry = _Sequence(holder=holder,
                              tenant=parent.tenant if tenant is None
                              else str(tenant))
            entry.blocks = list(parent.blocks)
            entry.length = parent.length
            self._seqs[child_id] = entry

    def _cow_tail(self, entry):
        """Copy-on-write the entry's (shared) tail block: allocate a
        private block, duplicate the tail's slots into it, drop one
        reference on the original.  The sharers keep reading the
        original bits; this sequence appends into its own copy — the
        write is invisible to them by construction."""
        old = entry.blocks[-1]
        new = self._alloc(1, holder=entry.holder)[0]
        if self.storage == "device":
            _, _, _, copy_block = _dev_ops()
            for layer in range(self.num_layers):
                self._k_dev[layer] = copy_block(self._k_dev[layer], new, old)
                self._v_dev[layer] = copy_block(self._v_dev[layer], new, old)
        else:
            self.k_blocks[:, new] = self.k_blocks[:, old]
            self.v_blocks[:, new] = self.v_blocks[:, old]
        entry.blocks[-1] = new
        self.allocator.free([old], holder=entry.holder)
        self._cow_copies += 1
        _telemetry.counter("serve.cow_copies").inc()

    def reserve(self, seq_id):
        """Reserve the next token's slot: the O(1) append.  At most one
        free-list pop (when the tail block is full); returns the position
        index the per-layer :meth:`write` calls will fill.  A partially
        filled tail block that is SHARED (refcount > 1 — a fork sibling
        or the prefix index holds it) is copy-on-written first: appends
        must never mutate bits another reader sees.  On
        :class:`CacheExhausted` the sequence is unchanged — the caller
        preempts it (free + requeue), never crashes."""
        with self._lock:
            entry = self._entry(seq_id)
            if entry.length % self.block_size == 0:
                entry.blocks.extend(self._alloc(1, holder=entry.holder))
            elif self.allocator.refcount(entry.blocks[-1]) > 1:
                self._cow_tail(entry)
            pos = entry.length
            entry.length = pos + 1
            return pos

    def reserve_window(self, seq_id, k):
        """Reserve ``k`` consecutive slots in one call — the speculative
        draft window's append (ISSUE 16).  All-or-nothing like every
        allocation on this class: on :class:`CacheExhausted` midway the
        freshly grabbed blocks are released and the length restored, so
        the caller preempts exactly as it would for a single-slot
        :meth:`reserve` (a completed copy-on-write of the shared tail is
        kept — it is semantically invisible: same bits, private copy).
        Returns the reserved positions ``[length, ..., length+k-1]``."""
        k = int(k)
        if k < 1:
            raise ValueError(f"reserve_window: k must be >= 1, got {k}")
        with self._lock:
            entry = self._entry(seq_id)
            base_nblocks = len(entry.blocks)
            base_length = entry.length
            try:
                if (entry.length % self.block_size != 0
                        and self.allocator.refcount(entry.blocks[-1]) > 1):
                    self._cow_tail(entry)
                need = (-(-(entry.length + k) // self.block_size)
                        - len(entry.blocks))
                if need > 0:
                    entry.blocks.extend(self._alloc(need,
                                                    holder=entry.holder))
            except CacheExhausted:
                fresh = entry.blocks[base_nblocks:]
                if fresh:
                    self.allocator.free(fresh, holder=entry.holder)
                    del entry.blocks[base_nblocks:]
                entry.length = base_length
                raise
            entry.length = base_length + k
            return list(range(base_length, base_length + k))

    def truncate(self, seq_id, length):
        """Shrink ``seq_id`` to ``length`` cached tokens — speculative
        decode's rejection path: the verify step reserved a whole draft
        window, the model accepted a prefix of it, and the unaccepted
        tail slots must stop being part of the sequence (the NEXT window
        overwrites those pool slots, but the length/table bookkeeping
        must agree with the accepted stream NOW).  Whole blocks past the
        new tail drop one reference each (shared blocks survive, as
        everywhere).  No-op when ``length`` already matches."""
        length = int(length)
        if length < 1:
            raise ValueError(f"truncate: length must be >= 1, got {length}")
        with self._lock:
            entry = self._entry(seq_id)
            if length > entry.length:
                raise MXNetError(
                    f"truncate: sequence {seq_id!r} holds {entry.length} "
                    f"tokens — cannot grow to {length} (use reserve)")
            keep = self.blocks_for(length)
            tail = entry.blocks[keep:]
            if tail:
                self.allocator.free(tail, holder=entry.holder)
                del entry.blocks[keep:]
            entry.length = length

    def window_slots(self, seq_ids, k):
        """The (block id, in-block offset) address of each sequence's
        last ``k`` reserved slots, as int32 ``(B, k)`` arrays — the
        fused decode step's in-program scatter coordinates (the device
        program writes the draft window's K/V straight into the donated
        pool at these addresses; no host-side write call happens at
        all)."""
        with self._lock:
            bids = np.empty((len(seq_ids), k), np.int32)
            offs = np.empty((len(seq_ids), k), np.int32)
            for i, s in enumerate(seq_ids):
                entry = self._entry(s)
                for j in range(k):
                    pos = entry.length - k + j
                    bids[i, j] = entry.blocks[pos // self.block_size]
                    offs[i, j] = pos % self.block_size
        return bids, offs

    def write(self, seq_id, layer, k, v):
        """Write one layer's K/V projection into the newest reserved slot
        (``k``/``v``: ``(num_heads, head_dim)``)."""
        with self._lock:
            entry = self._entry(seq_id)
            pos = entry.length - 1
            bid = entry.blocks[pos // self.block_size]
            off = pos % self.block_size
            if self.storage == "device":
                # numpy operands cross the jit boundary on the C++ fast
                # path; an eager jnp.asarray per operand costs ~73us of
                # dispatch each and dominated the per-token write cost
                write_slot, _, _, _ = _dev_ops()
                self._k_dev[layer] = write_slot(
                    self._k_dev[layer], bid, off, np.asarray(k))
                self._v_dev[layer] = write_slot(
                    self._v_dev[layer], bid, off, np.asarray(v))
            else:
                self.k_blocks[layer, bid, off] = k
                self.v_blocks[layer, bid, off] = v

    def write_batch(self, seq_ids, layer, k, v):
        """Write one layer's K/V for a whole decode batch into each
        sequence's newest reserved slot (``k``/``v``: ``(B, num_heads,
        head_dim)``).  On device storage this is ONE scatter per pool —
        the decode hot path's per-step write cost — instead of B
        round-trips; host storage loops the per-sequence slot writes."""
        with self._lock:
            slots = []
            for s in seq_ids:
                entry = self._entry(s)
                pos = entry.length - 1
                slots.append((entry.blocks[pos // self.block_size],
                              pos % self.block_size))
            if self.storage == "device":
                _, write_rows, _, _ = _dev_ops()
                bids = np.asarray([b for b, _ in slots], np.int32)
                offs = np.asarray([o for _, o in slots], np.int32)
                self._k_dev[layer] = write_rows(
                    self._k_dev[layer], bids, offs, np.asarray(k))
                self._v_dev[layer] = write_rows(
                    self._v_dev[layer], bids, offs, np.asarray(v))
            else:
                for i, (bid, off) in enumerate(slots):
                    self.k_blocks[layer, bid, off] = k[i]
                    self.v_blocks[layer, bid, off] = v[i]

    def write_window(self, seq_ids, layer, k, v):
        """Write one layer's K/V for a whole draft window into each
        sequence's last ``K`` reserved slots (``k``/``v``: ``(B, K,
        num_heads, head_dim)``) — the host-resident arm of speculative
        decode (ISSUE 16).  Device storage pays ONE scatter per pool for
        the whole ``B*K`` window (flattened rows), exactly like
        :meth:`write_batch` does for ``K == 1``."""
        kw = k.shape[1]
        bids, offs = self.window_slots(seq_ids, kw)
        with self._lock:
            if self.storage == "device":
                _, write_rows, _, _ = _dev_ops()
                flat = (len(seq_ids) * kw,) + k.shape[2:]
                self._k_dev[layer] = write_rows(
                    self._k_dev[layer], bids.ravel(), offs.ravel(),
                    np.asarray(k).reshape(flat))
                self._v_dev[layer] = write_rows(
                    self._v_dev[layer], bids.ravel(), offs.ravel(),
                    np.asarray(v).reshape(flat))
            else:
                for i in range(len(seq_ids)):
                    for j in range(kw):
                        self.k_blocks[layer, bids[i, j], offs[i, j]] = \
                            k[i, j]
                        self.v_blocks[layer, bids[i, j], offs[i, j]] = \
                            v[i, j]

    def free_sequence(self, seq_id):
        """Evict: drop one reference per block (copy-free — contents
        stay until reuse).  A block only this sequence held returns to
        the free list; one the prefix index or a fork sibling shares
        SURVIVES at its remaining count — freeing a preempted sequence
        can never evict a block another live sequence reads.  Returns
        the number of block references released."""
        with self._lock:
            entry = self._seqs.pop(seq_id, None)
            if entry is None:
                return 0
            self.allocator.free(entry.blocks, holder=entry.holder)
            return len(entry.blocks)

    def exclusive_blocks(self, seq_id):
        """How many of the sequence's blocks only IT holds (refcount
        1) — what freeing it would actually return to the pool.  The
        engine's preemption victim selection reads this: evicting a
        sequence whose blocks are all shared frees nothing."""
        with self._lock:
            entry = self._seqs.get(seq_id)
            if entry is None:
                return 0
            return sum(1 for b in entry.blocks
                       if self.allocator.refcount(b) == 1)

    def drop_prefix_cache(self):
        """Release EVERY prefix-index reference (teardown, tests, and
        the CI post-storm audit: after this plus freeing every sequence,
        ``allocator.refcounts()`` must be empty).  Returns the number of
        index entries dropped; 0 when sharing is off."""
        with self._lock:
            if self.prefix is None:
                return 0
            return self.prefix.drop_all(self.allocator)

    def prefix_stats(self):
        """Sharing observability: ``{sharing, prompt_tokens,
        cached_tokens, hit_ratio, prefill_bytes, prefill_bytes_saved,
        cow_copies}`` plus the index's own ``{nodes, lookups, hits,
        tokens_matched, evictions}`` when sharing is on."""
        with self._lock:
            out = {
                "sharing": self.prefix is not None,
                "prompt_tokens": self._prompt_tokens,
                "cached_tokens": self._cached_tokens,
                "hit_ratio": (self._cached_tokens / self._prompt_tokens
                              if self._prompt_tokens else 0.0),
                "prefill_bytes": ((self._prompt_tokens
                                   - self._cached_tokens)
                                  * self._token_bytes),
                "prefill_bytes_saved": (self._cached_tokens
                                        * self._token_bytes),
                "cow_copies": self._cow_copies,
            }
            if self.prefix is not None:
                out.update(self.prefix.stats())
            return out

    # -- reads: the paged-kernel operands ------------------------------------
    def pool(self, layer):
        """``layer``'s ``(num_blocks, block_size, H, D)`` K and V pools —
        the paged-attention kernel's HBM operands.  Device storage
        returns the resident jax arrays (an O(1) handle, no copy); host
        storage returns numpy views (the kernel's interpret-mode /
        parity-test arm pays the host->device copy per call, which is
        why production paged decode pairs with ``storage='device'``)."""
        if self.storage == "device":
            return self._k_dev[layer], self._v_dev[layer]
        return self.k_blocks[layer], self.v_blocks[layer]

    def pools(self):
        """EVERY layer's resident K and V pool handles, as two lists —
        the fused decode step's donated operands (serving/jax_model.py
        passes them into ONE jitted program that writes the window's
        K/V and returns the new buffers).  Device storage only: the
        whole point is that the handles are consumable device arrays."""
        if self.storage != "device":
            raise MXNetError(
                "PagedKVCache.pools: the fused decode step needs "
                "device-resident pools (storage='device')")
        return list(self._k_dev), list(self._v_dev)

    def adopt_pools(self, k_pools, v_pools):
        """Install the pool buffers a fused decode step returned — the
        other half of the donation handoff: the program CONSUMED the
        handles :meth:`pools` handed it, and these are their successors.
        Anything still holding a pre-step handle is stale by contract
        (module docstring: pool array access is step-thread-owned)."""
        if self.storage != "device":
            raise MXNetError(
                "PagedKVCache.adopt_pools: device storage only")
        if (len(k_pools) != self.num_layers
                or len(v_pools) != self.num_layers):
            raise ValueError(
                f"adopt_pools: expected {self.num_layers} pool pairs, "
                f"got {len(k_pools)}/{len(v_pools)}")
        self._k_dev = list(k_pools)
        self._v_dev = list(v_pools)

    def batch_tables(self, seq_ids):
        """The decode batch's raw block tables: int32 ``(B, NBpad)`` ids
        plus int32 ``(B,)`` true lengths — what the paged kernel walks.

        Rows are padded with block 0 past each sequence's real blocks
        (valid pool indices by construction — the kernel contract: the
        padded fetches are finite garbage the length mask excludes
        exactly), and NBpad is the batch max rounded up to a BUCKET —
        power of two up to 4 blocks, then multiples of 4 — so jitted
        consumers see a bounded set of shapes instead of recompiling at
        every block-boundary crossing.  The bucket is deliberately fine:
        pow2 buckets made the padded gather tail up to 2x the true
        context, which alone pushed the long-generation per-token
        receipt past the <=1.15x flatness bar (ROUND8_NOTES.md); at
        mult-4 the tail is <=3 blocks and a 4096-block pool still
        compiles at most ~1k shapes over its whole lifetime."""
        with self._lock:
            entries = [self._entry(s) for s in seq_ids]
            tables = [(list(e.blocks), e.length) for e in entries]
        nb = max(len(blocks) for blocks, _ in tables)
        nbpad = _next_pow2(nb) if nb <= 4 else -(-nb // 4) * 4
        ids = np.zeros((len(tables), nbpad), np.int32)
        for i, (blocks, _) in enumerate(tables):
            ids[i, :len(blocks)] = blocks
        lengths = np.array([length for _, length in tables], np.int32)
        return ids, lengths

    # -- reads (the dense-gather fallback) -----------------------------------
    def gather(self, seq_id, layer):
        """One sequence's dense ``(L, H, D)`` K/V for ``layer`` — the
        block table resolved in one fancy-index gather (a copy; device
        storage gathers on-device, then fetches the result)."""
        with self._lock:
            entry = self._entry(seq_id)
            blocks = list(entry.blocks)
            length = entry.length
        kp, vp = self.pool(layer)
        if self.storage == "device":
            import jax.numpy as jnp
            idx = jnp.asarray(blocks, jnp.int32)
            kp, vp = np.asarray(kp[idx]), np.asarray(vp[idx])
        else:
            kp, vp = kp[blocks], vp[blocks]
        k = kp.reshape(-1, self.num_heads, self.head_dim)
        v = vp.reshape(-1, self.num_heads, self.head_dim)
        return k[:length], v[:length]

    def gather_batch(self, seq_ids, layer):
        """Padded dense K/V for a decode batch: ``(B, Lpad, H, D)`` pair
        plus the int32 ``(B,)`` true lengths.

        ONE rectangular fancy-index gather for the whole batch (not a
        per-block or per-sequence loop): the O(context) term of the
        dense fallback is a single numpy memcpy pass per pool, which is
        what keeps the measured per-token decode cost near-flat at bench
        scale (docs/serving.md).  Positions >= length are padding — tail
        blocks and block-0-padded rows ride along stale-but-finite, fine
        BY CONTRACT: the attention mask excludes every key/value column
        past ``lengths`` exactly (finite garbage in, exactly-0
        probability out; blocks only ever hold finite writes)."""
        tables = []
        with self._lock:
            for s in seq_ids:
                entry = self._entry(s)
                tables.append((list(entry.blocks), entry.length))
        bs = self.block_size
        b = len(tables)
        nbmax = max(len(blocks) for blocks, _ in tables)
        # every table padded to nbmax with block 0 makes the whole batch
        # ONE rectangular fancy-index gather (a single memcpy pass per
        # pool) — the padding rows are arbitrary-but-finite real block
        # contents the length mask excludes exactly
        ids = np.zeros((b, nbmax), np.intp)
        for i, (blocks, _) in enumerate(tables):
            ids[i, :len(blocks)] = blocks
        shape = (b, nbmax * bs, self.num_heads, self.head_dim)
        kp, vp = self.pool(layer)
        if self.storage == "device":
            # reference arm on a device pool: gather on-device by table,
            # then commit the (B, Lpad, H, D) result to host once.  The
            # numpy index array crosses the dispatch boundary on the C++
            # fast path (no eager jnp.asarray op), and the single host
            # commit sits behind an isinstance guard — the guarded-
            # fallback idiom the hot-path-purity pass recognizes, which
            # retired the justified suppression that used to live here
            # (ISSUE 16; the O(context) cost itself is the documented
            # dense-fallback price, docs/DIVERGENCES.md #27)
            idx = np.asarray(ids.ravel(), np.int32)
            k, v = kp[idx], vp[idx]
            if not isinstance(k, np.ndarray):
                k, v = np.asarray(k), np.asarray(v)
            k = k.reshape(shape)
            v = v.reshape(shape)
        else:
            k = kp[ids.ravel()].reshape(shape)
            v = vp[ids.ravel()].reshape(shape)
        lengths = np.array([length for _, length in tables], np.int32)
        return k, v, lengths

    def stats(self):
        """``{sequences, used_blocks, free_blocks, utilization}``."""
        with self._lock:
            n = len(self._seqs)
        return {"sequences": n,
                "used_blocks": self.allocator.used,
                "free_blocks": self.allocator.available,
                "utilization": self.allocator.utilization()}

    # -- capacity accounting (ISSUE 14) --------------------------------------
    def audit(self):
        """Verify the capacity accounting identity — per block,
        attributed ledger refs == the allocator refcount; per tenant,
        amortized bytes sum EXACTLY to pool-used bytes — and return the
        audit report (raises :class:`~tpu_mx.base.MXNetError` on any
        violation).  The serve CI tier runs this after every chaos
        storm; with every sequence freed and the prefix index dropped
        the report must show zero used blocks and no tenants."""
        with self._lock:
            report = self.allocator.audit()
            report["sequences"] = len(self._seqs)
            return report

    def capacity_stats(self):
        """The live capacity view the server publishes as gauges and
        hands the scheduler as ``capacity_signal``: pool geometry,
        used/free/high-watermark bytes, free-list fragmentation, pinned
        blocks (plan holders), prefix-index resident bytes (amortized),
        the optimistic reclaimable-under-pressure bound, and the
        per-tenant amortized/exclusive byte attribution."""
        with self._lock:
            snap = self.allocator.capacity_snapshot()
            snap["block_size"] = self.block_size
            snap["used_bytes"] = snap["used_blocks"] * snap["block_bytes"]
            snap["high_watermark_bytes"] = (snap["high_watermark_blocks"]
                                            * snap["block_bytes"])
            snap["pinned_blocks"] = sum(h["blocks"]
                                        for h in snap["holders"]
                                        if h["pinned"])
            idx = snap["tenants"].get(INDEX_TENANT)
            snap["index_bytes"] = idx["bytes_amortized"] if idx else 0.0
            snap["reclaimable_blocks"] = (
                self.prefix.reclaimable(self.allocator)
                if self.prefix is not None else 0)
            return snap
