"""Paged KV cache: fixed-size blocks, a free-list allocator, block tables.

The serving runtime's memory manager (docs/serving.md).  A training step
owns one batch for its whole lifetime; a serving engine juggles thousands
of concurrent sequences whose lengths are unknown at admission.  Naive
per-sequence contiguous KV buffers either over-reserve (max_len for every
request — most of it never used) or reallocate-and-copy as sequences grow.
The paged design (vLLM's PagedAttention insight, applied to this stack's
layout) fixes both:

- **Blocks**: K and V live in ONE preallocated pool per layer, shaped
  ``(num_blocks, block_size, num_heads, head_dim)``.  A sequence's cache
  is a list of block ids — its **block table** — plus a length; logically
  contiguous, physically scattered.
- **Free-list allocator**: :class:`BlockAllocator` hands out block ids
  from a LIFO free list under one lock.  Exhaustion raises
  :class:`CacheExhausted` — the scheduler's backpressure signal (requeue /
  reject), NEVER an allocation attempt that OOMs the process.
- **O(1) append**: generating one token costs at most one free-list pop
  (amortized ``1/block_size`` pops) and one slot write — independent of
  how long the sequence already is.
- **Copy-free reuse**: finishing a sequence pushes its blocks straight
  back on the free list; the next sequence overwrites them.  No zeroing,
  no compaction, no copies.

Two storage modes share the allocator/table semantics (``storage=``):

- ``"host"`` (default): pools are host numpy — the CPU-testable layout
  tier-1 exercises, read through the dense-gather fallback.
- ``"device"``: pools are per-layer **device-resident** jax arrays
  (HBM on TPU); ``prefill``/``write``/``write_batch`` mutate them with
  jitted in-place index updates (buffer-donated where the backend
  supports donation) and the paged-attention decode kernel indexes them
  by raw block table (``tpu_mx/kernels/paged_attention.py``) — the
  cache never round-trips through the host on the decode path
  (docs/DIVERGENCES.md #27).  Same allocator, same block-table
  bookkeeping, same exhaustion-is-backpressure contract.

All public methods are thread-safe for BOOKKEEPING: the allocator has
its own lock and the table map is guarded by the cache lock, so a
scheduler thread can admit/evict while tests hammer alloc/free
concurrently (tests/test_serving.py).  Device-pool ARRAY access (writes
and :meth:`pool` readers) additionally belongs to the single engine
step thread: donation invalidates the previous buffer, so a reader
holding a stale pool reference across a write would observe a consumed
array — the serving data plane is single-threaded by design
(docs/serving.md), which is exactly this discipline.
"""
from __future__ import annotations

import functools
import threading

import numpy as np

from ..base import MXNetError

__all__ = ["CacheExhausted", "BlockAllocator", "PagedKVCache"]


def _next_pow2(n):
    return 1 if n <= 1 else 1 << (int(n) - 1).bit_length()


# Jitted device-pool updaters, built on first device-mode cache.  Python
# scalars/arrays trace as arguments, so repeated writes share one
# compilation per operand shape; donating the pool makes the update
# genuinely in-place (measured ~9us vs ~6ms copy-on-write for a 16 MiB
# pool on this host's CPU backend) — which is why pool handles are
# step-thread-owned: the pre-write array object is CONSUMED by every
# write (module docstring).
_DEV_OPS = None


def _dev_ops():
    global _DEV_OPS
    if _DEV_OPS is None:
        import jax

        donate = (0,)

        @functools.partial(jax.jit, donate_argnums=donate)
        def write_slot(pool, bid, off, val):
            return pool.at[bid, off].set(val.astype(pool.dtype))

        @functools.partial(jax.jit, donate_argnums=donate)
        def write_rows(pool, bids, offs, vals):
            return pool.at[bids, offs].set(vals.astype(pool.dtype))

        @functools.partial(jax.jit, donate_argnums=donate)
        def write_blocks(pool, bids, chunk):
            return pool.at[bids].set(chunk.astype(pool.dtype))

        _DEV_OPS = (write_slot, write_rows, write_blocks)
    return _DEV_OPS


class CacheExhausted(MXNetError):
    """The block pool has no room for this allocation.  This is the
    BACKPRESSURE signal, not an error to crash on: the scheduler catches
    it and requeues (decode append) or defers admission (prefill) —
    docs/serving.md "Backpressure"."""


class BlockAllocator:
    """LIFO free-list allocator over ``num_blocks`` fixed-size blocks.

    ``alloc(n)`` is all-or-nothing: either all ``n`` ids are handed out
    or :class:`CacheExhausted` is raised and the free list is untouched —
    a partial grab would leak blocks on the error path.  ``free`` rejects
    ids the allocator did not hand out (double-free corrupts the pool
    silently; loud is the only acceptable failure mode)."""

    def __init__(self, num_blocks):
        if int(num_blocks) < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        self.num_blocks = int(num_blocks)
        self._lock = threading.Lock()
        # LIFO: recently freed blocks are re-handed first (their pages are
        # the warmest — copy-free reuse on sequence completion)
        self._free = list(range(self.num_blocks - 1, -1, -1))
        self._held = set()

    def alloc(self, n=1):
        """``n`` block ids, or raise :class:`CacheExhausted` (free list
        untouched — all-or-nothing)."""
        n = int(n)
        with self._lock:
            if n > len(self._free):
                raise CacheExhausted(
                    f"KV cache exhausted: need {n} block(s), "
                    f"{len(self._free)}/{self.num_blocks} free — "
                    "backpressure, not OOM: requeue or reject")
            ids = [self._free.pop() for _ in range(n)]
            self._held.update(ids)
        return ids

    def free(self, block_ids):
        """Return blocks to the free list (copy-free: contents are left
        in place for the next owner to overwrite)."""
        with self._lock:
            for bid in block_ids:
                if bid not in self._held:
                    raise MXNetError(
                        f"BlockAllocator.free: block {bid} is not held "
                        "(double free or foreign id) — the pool would be "
                        "silently corrupted")
                self._held.discard(bid)
                self._free.append(bid)

    @property
    def available(self):
        """Blocks currently on the free list."""
        with self._lock:
            return len(self._free)

    @property
    def used(self):
        with self._lock:
            return len(self._held)

    def utilization(self):
        """Used fraction of the pool, in [0, 1]."""
        with self._lock:
            return len(self._held) / self.num_blocks


class _Sequence:
    __slots__ = ("blocks", "length")

    def __init__(self):
        self.blocks = []
        self.length = 0


class PagedKVCache:
    """Block-pooled K/V storage for many concurrent sequences.

    One pool pair per call site::

        cache = PagedKVCache(num_layers=2, num_heads=4, head_dim=16,
                             block_size=16, num_blocks=256)
        cache.prefill("req-1", k, v)        # bulk-fill: k/v (N, L, H, D)
        pos = cache.reserve("req-1")        # O(1) append: one slot
        cache.write("req-1", layer, k1, v1) # fill the reserved slot
        kd, vd, lens = cache.gather_batch(["req-1", ...], layer)
        cache.free_sequence("req-1")        # blocks back to the free list

    ``reserve`` + per-layer ``write`` split the append because a decoder
    computes layer i's K/V only after layer i-1's attention — the slot is
    reserved once per token (the O(1) step), then each layer writes its
    projection into it as the forward proceeds.

    ``gather_batch`` is the dense-gather decode fallback: it materializes
    a padded ``(B, Lmax, H, D)`` view by copying block slices — O(total
    context) per call, the documented cost of serving attention without
    the paged kernel (docs/DIVERGENCES.md #27).  The paged decode path
    instead reads :meth:`batch_tables` + :meth:`pool` and indexes the
    pool in-kernel.
    """

    def __init__(self, num_layers, num_heads, head_dim, block_size=16,
                 num_blocks=256, dtype=np.float32, storage="host"):
        if storage not in ("host", "device"):
            raise ValueError(f"storage must be 'host' or 'device', "
                             f"got {storage!r}")
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.block_size = int(block_size)
        if self.block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.allocator = BlockAllocator(num_blocks)
        self.storage = storage
        layer_shape = (self.allocator.num_blocks, self.block_size,
                       self.num_heads, self.head_dim)
        if storage == "device":
            try:
                import jax.numpy as jnp
            except ImportError:
                raise MXNetError(
                    "PagedKVCache: storage='device' needs jax — use the "
                    "default host storage (dense-gather decode) without "
                    "it") from None
            # per-layer pools (not one (L, N, ...) array): layer reads on
            # the decode hot path must be O(1) handle lookups, never a
            # per-step slice copy of the whole pool
            self._k_dev = [jnp.zeros(layer_shape, dtype)
                           for _ in range(self.num_layers)]
            self._v_dev = [jnp.zeros(layer_shape, dtype)
                           for _ in range(self.num_layers)]
            self.k_blocks = self.v_blocks = None
        else:
            shape = (self.num_layers,) + layer_shape
            self.k_blocks = np.zeros(shape, dtype)
            self.v_blocks = np.zeros(shape, dtype)
        self._lock = threading.RLock()
        self._seqs = {}

    @property
    def device_resident(self):
        """True when the block pools live on the accelerator (jax
        arrays) rather than in host numpy — the `serve.
        pool_device_resident` gauge's source of truth."""
        return self.storage == "device"

    # -- bookkeeping ---------------------------------------------------------
    def _entry(self, seq_id):
        try:
            return self._seqs[seq_id]
        except KeyError:
            raise MXNetError(f"PagedKVCache: unknown sequence {seq_id!r} "
                             "(never prefilled, or already freed)") from None

    def has_sequence(self, seq_id):
        with self._lock:
            return seq_id in self._seqs

    def length(self, seq_id):
        """Tokens currently cached for ``seq_id`` (reserved slots count)."""
        with self._lock:
            return self._entry(seq_id).length

    def block_table(self, seq_id):
        """The sequence's block-id table (a copy), in position order."""
        with self._lock:
            return list(self._entry(seq_id).blocks)

    def num_sequences(self):
        with self._lock:
            return len(self._seqs)

    def utilization(self):
        return self.allocator.utilization()

    def blocks_for(self, num_tokens):
        """Blocks a ``num_tokens``-long prefill needs (admission math)."""
        return -(-int(num_tokens) // self.block_size)

    # -- writes --------------------------------------------------------------
    def prefill(self, seq_id, k, v):
        """Bulk-fill a new sequence's blocks in one call.

        ``k``/``v``: ``(num_layers, L, num_heads, head_dim)``.  Allocates
        exactly ``ceil(L / block_size)`` blocks all-or-nothing — on
        :class:`CacheExhausted` nothing is registered, so the scheduler
        can requeue the request and retry after an eviction."""
        k = np.asarray(k)
        v = np.asarray(v)
        want = (self.num_layers, k.shape[1], self.num_heads, self.head_dim)
        if k.shape != want or v.shape != want:
            raise ValueError(
                f"prefill: k/v must be (num_layers={self.num_layers}, L, "
                f"H={self.num_heads}, D={self.head_dim}); got {k.shape} / "
                f"{v.shape}")
        length = k.shape[1]
        if length < 1:
            raise ValueError("prefill: empty prompt")
        with self._lock:
            if seq_id in self._seqs:
                raise MXNetError(f"prefill: sequence {seq_id!r} already "
                                 "cached (free it first)")
            blocks = self.allocator.alloc(self.blocks_for(length))
            # fill BEFORE publishing in _seqs: a concurrent gather must
            # never see a registered-but-empty sequence (all-zero K/V
            # would be silently wrong logits, not an error)
            bs = self.block_size
            if self.storage == "device":
                _, _, write_blocks = _dev_ops()
                nb = len(blocks)
                pad = nb * bs - length
                bids = np.asarray(blocks, np.int32)
                for layer in range(self.num_layers):
                    # one scatter per pool per layer: the prompt's K/V
                    # crosses to the device once, zero-padded to whole
                    # blocks (the tail slots are this sequence's own
                    # future append slots)
                    ck = np.pad(k[layer], ((0, pad), (0, 0), (0, 0)))
                    cv = np.pad(v[layer], ((0, pad), (0, 0), (0, 0)))
                    self._k_dev[layer] = write_blocks(
                        self._k_dev[layer], bids,
                        ck.reshape(nb, bs, *ck.shape[1:]))
                    self._v_dev[layer] = write_blocks(
                        self._v_dev[layer], bids,
                        cv.reshape(nb, bs, *cv.shape[1:]))
            else:
                for i, bid in enumerate(blocks):
                    lo = i * bs
                    hi = min(lo + bs, length)
                    self.k_blocks[:, bid, :hi - lo] = k[:, lo:hi]
                    self.v_blocks[:, bid, :hi - lo] = v[:, lo:hi]
            entry = _Sequence()
            entry.blocks = blocks
            entry.length = length
            self._seqs[seq_id] = entry

    def reserve(self, seq_id):
        """Reserve the next token's slot: the O(1) append.  At most one
        free-list pop (when the tail block is full); returns the position
        index the per-layer :meth:`write` calls will fill.  On
        :class:`CacheExhausted` the sequence is unchanged — the caller
        preempts it (free + requeue), never crashes."""
        with self._lock:
            entry = self._entry(seq_id)
            if entry.length % self.block_size == 0:
                entry.blocks.extend(self.allocator.alloc(1))
            pos = entry.length
            entry.length = pos + 1
            return pos

    def write(self, seq_id, layer, k, v):
        """Write one layer's K/V projection into the newest reserved slot
        (``k``/``v``: ``(num_heads, head_dim)``)."""
        with self._lock:
            entry = self._entry(seq_id)
            pos = entry.length - 1
            bid = entry.blocks[pos // self.block_size]
            off = pos % self.block_size
            if self.storage == "device":
                # numpy operands cross the jit boundary on the C++ fast
                # path; an eager jnp.asarray per operand costs ~73us of
                # dispatch each and dominated the per-token write cost
                write_slot, _, _ = _dev_ops()
                self._k_dev[layer] = write_slot(
                    self._k_dev[layer], bid, off, np.asarray(k))
                self._v_dev[layer] = write_slot(
                    self._v_dev[layer], bid, off, np.asarray(v))
            else:
                self.k_blocks[layer, bid, off] = k
                self.v_blocks[layer, bid, off] = v

    def write_batch(self, seq_ids, layer, k, v):
        """Write one layer's K/V for a whole decode batch into each
        sequence's newest reserved slot (``k``/``v``: ``(B, num_heads,
        head_dim)``).  On device storage this is ONE scatter per pool —
        the decode hot path's per-step write cost — instead of B
        round-trips; host storage loops the per-sequence slot writes."""
        with self._lock:
            slots = []
            for s in seq_ids:
                entry = self._entry(s)
                pos = entry.length - 1
                slots.append((entry.blocks[pos // self.block_size],
                              pos % self.block_size))
            if self.storage == "device":
                _, write_rows, _ = _dev_ops()
                bids = np.asarray([b for b, _ in slots], np.int32)
                offs = np.asarray([o for _, o in slots], np.int32)
                self._k_dev[layer] = write_rows(
                    self._k_dev[layer], bids, offs, np.asarray(k))
                self._v_dev[layer] = write_rows(
                    self._v_dev[layer], bids, offs, np.asarray(v))
            else:
                for i, (bid, off) in enumerate(slots):
                    self.k_blocks[layer, bid, off] = k[i]
                    self.v_blocks[layer, bid, off] = v[i]

    def free_sequence(self, seq_id):
        """Evict: push the sequence's blocks back on the free list
        (copy-free — contents stay until reuse).  Returns the number of
        blocks released."""
        with self._lock:
            entry = self._seqs.pop(seq_id, None)
            if entry is None:
                return 0
            self.allocator.free(entry.blocks)
            return len(entry.blocks)

    # -- reads: the paged-kernel operands ------------------------------------
    def pool(self, layer):
        """``layer``'s ``(num_blocks, block_size, H, D)`` K and V pools —
        the paged-attention kernel's HBM operands.  Device storage
        returns the resident jax arrays (an O(1) handle, no copy); host
        storage returns numpy views (the kernel's interpret-mode /
        parity-test arm pays the host->device copy per call, which is
        why production paged decode pairs with ``storage='device'``)."""
        if self.storage == "device":
            return self._k_dev[layer], self._v_dev[layer]
        return self.k_blocks[layer], self.v_blocks[layer]

    def batch_tables(self, seq_ids):
        """The decode batch's raw block tables: int32 ``(B, NBpad)`` ids
        plus int32 ``(B,)`` true lengths — what the paged kernel walks.

        Rows are padded with block 0 past each sequence's real blocks
        (valid pool indices by construction — the kernel contract: the
        padded fetches are finite garbage the length mask excludes
        exactly), and NBpad is the batch max rounded up to a BUCKET —
        power of two up to 4 blocks, then multiples of 4 — so jitted
        consumers see a bounded set of shapes instead of recompiling at
        every block-boundary crossing.  The bucket is deliberately fine:
        pow2 buckets made the padded gather tail up to 2x the true
        context, which alone pushed the long-generation per-token
        receipt past the <=1.15x flatness bar (ROUND8_NOTES.md); at
        mult-4 the tail is <=3 blocks and a 4096-block pool still
        compiles at most ~1k shapes over its whole lifetime."""
        with self._lock:
            entries = [self._entry(s) for s in seq_ids]
            tables = [(list(e.blocks), e.length) for e in entries]
        nb = max(len(blocks) for blocks, _ in tables)
        nbpad = _next_pow2(nb) if nb <= 4 else -(-nb // 4) * 4
        ids = np.zeros((len(tables), nbpad), np.int32)
        for i, (blocks, _) in enumerate(tables):
            ids[i, :len(blocks)] = blocks
        lengths = np.array([length for _, length in tables], np.int32)
        return ids, lengths

    # -- reads (the dense-gather fallback) -----------------------------------
    def gather(self, seq_id, layer):
        """One sequence's dense ``(L, H, D)`` K/V for ``layer`` — the
        block table resolved in one fancy-index gather (a copy; device
        storage gathers on-device, then fetches the result)."""
        with self._lock:
            entry = self._entry(seq_id)
            blocks = list(entry.blocks)
            length = entry.length
        kp, vp = self.pool(layer)
        if self.storage == "device":
            import jax.numpy as jnp
            idx = jnp.asarray(blocks, jnp.int32)
            kp, vp = np.asarray(kp[idx]), np.asarray(vp[idx])
        else:
            kp, vp = kp[blocks], vp[blocks]
        k = kp.reshape(-1, self.num_heads, self.head_dim)
        v = vp.reshape(-1, self.num_heads, self.head_dim)
        return k[:length], v[:length]

    def gather_batch(self, seq_ids, layer):
        """Padded dense K/V for a decode batch: ``(B, Lpad, H, D)`` pair
        plus the int32 ``(B,)`` true lengths.

        ONE rectangular fancy-index gather for the whole batch (not a
        per-block or per-sequence loop): the O(context) term of the
        dense fallback is a single numpy memcpy pass per pool, which is
        what keeps the measured per-token decode cost near-flat at bench
        scale (docs/serving.md).  Positions >= length are padding — tail
        blocks and block-0-padded rows ride along stale-but-finite, fine
        BY CONTRACT: the attention mask excludes every key/value column
        past ``lengths`` exactly (finite garbage in, exactly-0
        probability out; blocks only ever hold finite writes)."""
        tables = []
        with self._lock:
            for s in seq_ids:
                entry = self._entry(s)
                tables.append((list(entry.blocks), entry.length))
        bs = self.block_size
        b = len(tables)
        nbmax = max(len(blocks) for blocks, _ in tables)
        # every table padded to nbmax with block 0 makes the whole batch
        # ONE rectangular fancy-index gather (a single memcpy pass per
        # pool) — the padding rows are arbitrary-but-finite real block
        # contents the length mask excludes exactly
        ids = np.zeros((b, nbmax), np.intp)
        for i, (blocks, _) in enumerate(tables):
            ids[i, :len(blocks)] = blocks
        shape = (b, nbmax * bs, self.num_heads, self.head_dim)
        kp, vp = self.pool(layer)
        if self.storage == "device":
            # reference arm on a device pool: gather on-device by table,
            # fetch the (B, Lpad, H, D) result once — the parity tests'
            # honest dense baseline against the same resident pool
            import jax.numpy as jnp
            # tpumx-lint: disable=hot-path-purity -- dense REFERENCE arm
            # reading a device-resident pool: one index-array commit per
            # gather is the documented O(context) fallback cost, not the
            # production paged path (that one walks raw tables in-kernel;
            # docs/DIVERGENCES.md #27, docs/serving.md "decode arms")
            idx = jnp.asarray(ids.ravel(), jnp.int32)
            k = np.asarray(kp[idx]).reshape(shape)
            v = np.asarray(vp[idx]).reshape(shape)
        else:
            k = kp[ids.ravel()].reshape(shape)
            v = vp[ids.ravel()].reshape(shape)
        lengths = np.array([length for _, length in tables], np.int32)
        return k, v, lengths

    def stats(self):
        """``{sequences, used_blocks, free_blocks, utilization}``."""
        with self._lock:
            n = len(self._seqs)
        return {"sequences": n,
                "used_blocks": self.allocator.used,
                "free_blocks": self.allocator.available,
                "utilization": self.allocator.utilization()}
