"""Block-granular shared-prefix index: the trie behind KV reuse.

"Millions of users" traffic is dominated by shared system prompts and
templates (ROADMAP fleet-scale item): N requests carrying the same
leading tokens each used to pay a full prefill and a private copy of
identical KV blocks.  This module is the index that lets
:class:`~tpu_mx.serving.kv_cache.PagedKVCache` map those requests'
leading block-table entries onto the SAME physical blocks:

- **Trie keyed on full block contents**: each node is one FULL block of
  tokens (the ``block_size``-tuple is the key — a token hash via the
  dict), chained parent→child in prompt order, holding the physical
  block id whose K/V encodes exactly that token prefix.  Sharing is
  sound at this granularity because a position's K/V is a pure function
  of the tokens at and before it: same prefix tokens → bit-identical
  K/V, whichever request computed them first.
- **Only full blocks are indexed.**  A partial tail block is still being
  appended to — its contents are not final, so it is never shared
  through the index (a matched sequence writes its own tail; the
  copy-on-write path in ``PagedKVCache.reserve`` guards the residual
  case where a tail block IS shared, e.g. after ``fork``).
- **Refcounts, not ownership**: the index holds one reference on every
  block it indexes (``BlockAllocator`` refcounts — kv_cache.py), so a
  prefix outlives the sequence that prefilled it and the next request
  with the same template reuses it.  ``free_sequence`` decrements;
  physical reuse happens only at refcount zero.
- **Eviction under pressure**: when an allocation cannot be satisfied,
  the cache asks the index to release least-recently-matched LEAF nodes
  whose blocks no live sequence shares (refcount 1 — index-only) until
  the allocation fits.  Leaf-first keeps the trie reachable (evicting an
  interior node would orphan its descendants: matching walks from the
  root, so an unreachable child could never be handed out again but
  would hold its block forever).  The exhaustion contract is unchanged:
  if releasing every evictable prefix still cannot satisfy the
  allocation, :class:`~tpu_mx.serving.kv_cache.CacheExhausted`
  propagates — backpressure, never OOM.

Determinism: recency is a monotone integer clock (``itertools.count``),
not wall time — eviction order is a pure function of the request
sequence, which is what keeps the sharing-on vs sharing-off greedy
streams comparable under a fixed trace (tests/test_multitenant.py, the
bench prefix trace).

Thread-safety: the index has no lock of its own — every call happens
under the owning ``PagedKVCache``'s lock (the cache's documented
bookkeeping discipline), and allocator refcount mutations go through
the allocator's own lock beneath it.
"""
from __future__ import annotations

import heapq
import itertools
import os

from .accounting import INDEX_HOLDER, INDEX_TENANT

__all__ = ["PrefixIndex", "prefix_sharing_enabled"]

_SHARING_ENV = "TPUMX_PREFIX_SHARING"


def prefix_sharing_enabled():
    """The ``TPUMX_PREFIX_SHARING`` knob: ``1``/``on`` enables the
    shared-prefix index, unset/``0``/``off`` disables it (the default —
    sharing changes pool-residency behavior, so it is opt-in like
    ``TPUMX_PAGED_DECODE``).  Unknown values raise: a typo'd knob
    silently running the other arm would let a "sharing" receipt pass
    without ever exercising the trie."""
    v = os.environ.get(_SHARING_ENV, "0").strip().lower()
    if v in ("", "0", "off", "false"):
        return False
    if v in ("1", "on", "true", "share"):
        return True
    raise ValueError(
        f"{_SHARING_ENV}={v!r} is not a recognized setting — use 0 "
        "(private prefills, the default) or 1 (block-granular shared-"
        "prefix KV reuse)")


class _Node:
    """One indexed FULL block: ``key`` is its token tuple, ``block_id``
    the physical block whose K/V encodes the prefix ending here."""

    __slots__ = ("key", "block_id", "parent", "children", "last_used")

    def __init__(self, key, block_id, parent):
        self.key = key
        self.block_id = block_id
        self.parent = parent
        self.children = {}
        self.last_used = 0


class PrefixIndex:
    """See module docstring.  All methods are called under the owning
    cache's lock; ``allocator`` is the cache's refcounted
    :class:`~tpu_mx.serving.kv_cache.BlockAllocator`."""

    def __init__(self, block_size):
        self.block_size = int(block_size)
        self._root = _Node(None, None, None)
        self._clock = itertools.count(1)
        self._nodes = 0
        # observability counters (the cache publishes them as the
        # serve.prefix_* metrics — docs/observability.md)
        self.lookups = 0
        self.hits = 0
        self.tokens_matched = 0
        self.evictions = 0

    # -- matching ------------------------------------------------------------
    def match(self, tokens):
        """The longest indexed chain of full blocks that is a prefix of
        ``tokens`` AND leaves at least the final token uncovered —
        returns ``(block_ids, tokens_covered)`` (both empty/0 on a
        miss).

        The final-token cap is the engine's logits contract: the first
        generated token is the argmax at the LAST prompt position, so at
        least that position must be computed (suffix prefill) rather
        than served from cache.  Touches the whole matched chain's
        recency — a template's interior blocks must not age out while
        its tail is hot.  The caller pins the returned blocks (incref)
        before releasing the cache lock."""
        bs = self.block_size
        self.lookups += 1
        node, blocks = self._root, []
        limit = len(tokens) - 1
        while (len(blocks) + 1) * bs <= limit:
            lo = len(blocks) * bs
            child = node.children.get(tuple(tokens[lo:lo + bs]))
            if child is None:
                break
            blocks.append(child.block_id)
            node = child
        if blocks:
            stamp = next(self._clock)
            n = node
            while n is not self._root:
                n.last_used = stamp
                n = n.parent
            self.hits += 1
            self.tokens_matched += len(blocks) * bs
        return blocks, len(blocks) * bs

    # -- insertion -----------------------------------------------------------
    def insert(self, tokens, block_ids, allocator):
        """Index every FULL block of ``tokens`` (physical ids
        ``block_ids``, in table order).  New nodes take one index
        reference on their block (``allocator.incref``); chains that
        already exist are left pointing at their original block — the
        first writer wins, so concurrent identical prefills converge on
        one physical copy for all FUTURE requests even though each kept
        its own."""
        bs = self.block_size
        node = self._root
        stamp = next(self._clock)
        for i in range(len(tokens) // bs):
            if i >= len(block_ids):
                break
            key = tuple(tokens[i * bs:(i + 1) * bs])
            child = node.children.get(key)
            if child is None:
                child = _Node(key, block_ids[i], node)
                # the index's references are ledgered under its own
                # holder/pseudo-tenant: index-resident bytes belong to
                # the fleet, not to whichever tenant prefilled first
                allocator.incref([block_ids[i]], holder=INDEX_HOLDER)
                allocator.describe(INDEX_HOLDER, kind="index",
                                   tenant=INDEX_TENANT)
                node.children[key] = child
                self._nodes += 1
            child.last_used = stamp
            node = child

    # -- eviction ------------------------------------------------------------
    def release(self, allocator, need):
        """Release least-recently-matched evictable leaves until the
        free list holds at least ``need`` blocks (or nothing evictable
        remains).  Evictable = a leaf whose block only the index holds
        (refcount 1): releasing a block a live sequence shares would
        free no memory.  Returns the number of blocks released.

        One DFS collects every candidate leaf into a heap keyed on
        recency; parents that BECOME evictable leaves as their children
        go are pushed as they appear — amortized O(nodes + k log n) per
        relief pass, instead of a full-trie walk per victim (this runs
        under the owning cache's lock on the allocation path)."""
        if allocator.available >= need:
            return 0
        heap = []
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            if not n.children and allocator.refcount(n.block_id) == 1:
                heapq.heappush(heap, (n.last_used, id(n), n))
            stack.extend(n.children.values())
        released = 0
        while heap and allocator.available < need:
            _, _, victim = heapq.heappop(heap)
            if victim.key not in victim.parent.children or \
                    victim.children:
                continue   # stale entry (shouldn't happen; be safe)
            del victim.parent.children[victim.key]
            allocator.free([victim.block_id], holder=INDEX_HOLDER)
            self._nodes -= 1
            self.evictions += 1
            released += 1
            parent = victim.parent
            if parent is not self._root and not parent.children \
                    and allocator.refcount(parent.block_id) == 1:
                heapq.heappush(heap, (parent.last_used, id(parent),
                                      parent))
        return released

    def drop_all(self, allocator):
        """Release EVERY index reference (teardown / the post-storm
        refcount audit: with the index dropped and all sequences freed,
        every allocator refcount must be back at zero).  Returns the
        number of nodes released."""
        dropped = 0
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            allocator.free([n.block_id], holder=INDEX_HOLDER)
            dropped += 1
        self._root.children = {}
        self._nodes = 0
        return dropped

    def reclaimable(self, allocator):
        """How many indexed blocks no live sequence shares (refcount
        1 — index-only).  An OPTIMISTIC upper bound on what a pressure
        pass could release: an interior node above a live-shared child
        can never become an evictable leaf, so the true figure may be
        lower — callers (the scheduler's would-fit admission gate) must
        treat a miss as the ordinary defer path, not a promise."""
        refs = allocator.refcounts()   # ONE lock acquisition, not per node
        count = 0
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            if refs.get(n.block_id) == 1:
                count += 1
            stack.extend(n.children.values())
        return count

    # -- observables ---------------------------------------------------------
    @property
    def nodes(self):
        return self._nodes

    def stats(self):
        """``{nodes, lookups, hits, tokens_matched, evictions}``."""
        return {"nodes": self._nodes, "lookups": self.lookups,
                "hits": self.hits, "tokens_matched": self.tokens_matched,
                "evictions": self.evictions}
