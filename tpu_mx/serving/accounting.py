"""Capacity accounting: who holds every byte of the KV block pool.

PR 12's refcounted sharing made "how full is the pool" easy and "WHO is
holding it" genuinely hard: a physical block can simultaneously back
five tenants' sequences, the shared-prefix index, and a pinned
mid-prefill plan — yet the only live signal used to be a single scalar
``serve.cache_utilization`` gauge and a ``CacheExhausted`` with no
holder breakdown.  This module is the missing ledger (ISSUE 14):

- :class:`CapacityLedger` rides the refcounted
  :class:`~tpu_mx.serving.kv_cache.BlockAllocator`: every reference the
  allocator hands out is attributed to a named **holder** — a sequence
  (``seq:<id>``), the shared-prefix index (:data:`INDEX_HOLDER`), or a
  pinned prefill plan (``plan:<n>``) — each carrying a ``kind``, a
  ``tenant`` and a ``pinned`` flag.  Ledger mutations happen INSIDE the
  allocator's lock, next to the refcount mutation they mirror, so the
  per-block identity ``sum of holder refs == allocator refcount`` holds
  at every instant, not just at quiescence.
- **The accounting identity**: shared bytes are attributed two ways —
  *amortized* (each holder charged ``block_bytes × its refs / total
  refcount`` per block, so per-tenant bytes sum EXACTLY to pool-used
  bytes; computed in :class:`fractions.Fraction`, never floats) and
  *exclusive-if-forked* (each tenant charged the full ``block_bytes``
  per distinct block it references — what the tenant would cost if
  nothing were shared).  ``audit()`` verifies both the per-block and
  the per-tenant identity and raises loudly on any violation; the serve
  CI tier asserts it after every chaos storm.
- **Exhaustion forensics**: the cache records a forensic snapshot —
  every live holder with its block count, pinned/shared state and age —
  on every genuine ``CacheExhausted`` and every prefix-index pressure
  eviction, and (when armed with a path prefix) persists the rolling
  record set as ``<prefix>-capacity.json`` through the PR-7 black-box
  write discipline (``checkpoint.atomic_write``; strict JSON).
  ``tools/capacity_report.py`` renders and ``--validate``s it without
  importing jax.

Like ``telemetry.py`` and ``tracing.py``, this module imports ONLY the
stdlib at module level and degrades gracefully when loaded standalone
(``tools/capacity_report.py`` loads it by file path — it must work on a
machine with no accelerator stack at all).

Thread-safety: the ledger has no lock of its own — every mutation is
called by :class:`~tpu_mx.serving.kv_cache.BlockAllocator` under ITS
lock (the same discipline ``PrefixIndex`` follows under the cache
lock), and read snapshots are taken through allocator methods holding
that lock.
"""
from __future__ import annotations

import json
import time
from fractions import Fraction

try:
    from ..base import MXNetError as LedgerError
except ImportError:  # standalone load (tools/capacity_report.py):
    class LedgerError(Exception):
        """Capacity-accounting violation (standalone-load spelling)."""

__all__ = ["CapacityLedger", "LedgerError", "FORENSIC_FORMAT",
           "INDEX_HOLDER", "INDEX_TENANT", "UNATTRIBUTED",
           "FORENSIC_KINDS", "dump_forensics", "validate_forensic_record",
           "validate_forensic_doc"]

FORENSIC_FORMAT = "tpu_mx-capacity-forensic-v1"

# the shared-prefix index's holder id and pseudo-tenant: index-resident
# bytes belong to the fleet, not to the tenant that happened to prefill
# them first — they are attributed under their own name so the identity
# stays exact without inventing a per-tenant split the index cannot know
INDEX_HOLDER = "prefix-index"
INDEX_TENANT = "_index"

# references taken through the bare allocator API (tests, tools) with no
# holder named — still ledgered, still part of the identity
UNATTRIBUTED = "_anon"

FORENSIC_KINDS = ("exhaustion", "pressure_evict")

# relative tolerance for re-checking the float-rendered amortized-bytes
# identity in a persisted forensic record (the LIVE identity is exact
# Fraction math; the JSON rendering rounds each tenant to a float once)
FORENSIC_BYTES_RTOL = 1e-6


class CapacityLedger:
    """Holder-attribution ledger for one block allocator (module
    docstring).  ``block_bytes`` is the physical size of one pool block
    across every layer and both K/V pools — the unit every byte figure
    in the ledger is denominated in."""

    __slots__ = ("block_bytes", "_refs", "_meta", "high_watermark")

    def __init__(self, block_bytes=1):
        self.block_bytes = int(block_bytes)
        self._refs = {}   # holder -> {block_id: refs held}
        self._meta = {}   # holder -> {kind, tenant, pinned, created}
        self.high_watermark = 0   # peak distinct blocks ever held

    # -- mutation (called under the allocator's lock) ------------------------
    def _entry(self, holder):
        refs = self._refs.get(holder)
        if refs is None:
            refs = self._refs[holder] = {}
            self._meta.setdefault(holder, {
                "kind": "holder", "tenant": UNATTRIBUTED,
                "pinned": False, "created": time.monotonic()})
        return refs

    def describe(self, holder, kind=None, tenant=None, pinned=None):
        """Attach/refresh a holder's attribution metadata (kind /
        tenant / pinned).  Safe before or after its first reference."""
        holder = str(holder)
        self._entry(holder)
        meta = self._meta[holder]
        if kind is not None:
            meta["kind"] = str(kind)
        if tenant is not None:
            meta["tenant"] = str(tenant)
        if pinned is not None:
            meta["pinned"] = bool(pinned)

    def hold(self, block_ids, holder=None):
        """One more reference per block, attributed to ``holder``."""
        refs = self._entry(UNATTRIBUTED if holder is None else str(holder))
        for bid in block_ids:
            refs[bid] = refs.get(bid, 0) + 1

    def release(self, block_ids, holder=None):
        """Drop one attributed reference per block.  Releasing a
        reference the named holder does not hold is as loud as a
        double-free: a silent mismatch here would quietly break the
        refcount == sum-of-holder-refs identity the audit gates on."""
        holder = UNATTRIBUTED if holder is None else str(holder)
        refs = self._refs.get(holder, {})
        for bid in block_ids:
            if refs.get(bid, 0) < 1:
                raise LedgerError(
                    f"CapacityLedger: holder {holder!r} does not hold a "
                    f"reference to block {bid} — attribution and "
                    "refcounts would diverge")
        for bid in block_ids:
            refs[bid] -= 1
            if refs[bid] == 0:
                del refs[bid]
        if not refs:
            self._refs.pop(holder, None)
            self._meta.pop(holder, None)

    def transfer(self, block_ids, src, dst):
        """Move one reference per block from ``src`` to ``dst`` without
        touching the refcount — the commit-prefill ownership handoff
        (a plan's pins become the registered sequence's references)."""
        self.release(block_ids, src)
        self.hold(block_ids, dst)

    def note_used(self, used_blocks):
        """Advance the high watermark (called after every allocation)."""
        if used_blocks > self.high_watermark:
            self.high_watermark = used_blocks

    # -- reads (called under the allocator's lock) ---------------------------
    def _block_totals(self):
        totals = {}
        for refs in self._refs.values():
            for bid, n in refs.items():
                totals[bid] = totals.get(bid, 0) + n
        return totals

    def views(self):
        """``(holders, tenants)`` computed off ONE block-totals pass —
        what the per-step gauge publication reads (the separate
        :meth:`holders`/:meth:`tenants` accessors recompute totals and
        are fine for audits and forensics, which are rare)."""
        totals = self._block_totals()
        return self._holder_rows(totals), self._tenant_rows(totals)

    def holders(self):
        """Every live holder's attribution row: ``{kind, id, tenant,
        blocks, exclusive_blocks, shared_blocks, pinned, age_seconds}``
        (shared = the block's TOTAL refcount exceeds this holder's own
        references — someone else also reads it)."""
        return self._holder_rows(self._block_totals())

    def _holder_rows(self, totals):
        now = time.monotonic()
        out = []
        for holder, refs in self._refs.items():
            meta = self._meta[holder]
            excl = sum(1 for bid, n in refs.items() if totals[bid] == n)
            out.append({
                "kind": meta["kind"],
                "id": holder,
                "tenant": meta["tenant"],
                "blocks": sum(refs.values()),
                "exclusive_blocks": excl,
                "shared_blocks": len(refs) - excl,
                "pinned": meta["pinned"],
                "age_seconds": max(now - meta["created"], 0.0),
            })
        out.sort(key=lambda h: (-h["blocks"], h["id"]))
        return out

    def tenants(self):
        """Per-tenant attribution with EXACT amortized math:
        ``{tenant: {bytes_amortized, bytes_exclusive, blocks, refs,
        holders}}`` where ``bytes_amortized`` sums over blocks
        ``block_bytes × holder_refs / block_refcount`` (a
        :class:`fractions.Fraction` internally — the identity
        ``sum over tenants == used_blocks × block_bytes`` is exact, not
        within-epsilon) and ``bytes_exclusive`` charges the full block
        for every distinct block the tenant references (the
        exclusive-if-forked cost)."""
        return self._tenant_rows(self._block_totals())

    def _tenant_rows(self, totals):
        per = {}
        for holder, refs in self._refs.items():
            tenant = self._meta[holder]["tenant"]
            d = per.setdefault(tenant, {"_amortized": Fraction(0),
                                        "_blocks": set(), "refs": 0,
                                        "holders": 0})
            d["holders"] += 1
            for bid, n in refs.items():
                d["_amortized"] += Fraction(n, totals[bid])
                d["_blocks"].add(bid)
                d["refs"] += n
        out = {}
        for tenant, d in per.items():
            out[tenant] = {
                "bytes_amortized": float(d["_amortized"]
                                         * self.block_bytes),
                "bytes_exclusive": len(d["_blocks"]) * self.block_bytes,
                "blocks": len(d["_blocks"]),
                "refs": d["refs"],
                "holders": d["holders"],
            }
        return out

    def _tenant_amortized_exact(self):
        """{tenant: Fraction(amortized blocks)} — the audit's exact arm."""
        totals = self._block_totals()
        per = {}
        for holder, refs in self._refs.items():
            tenant = self._meta[holder]["tenant"]
            acc = per.setdefault(tenant, Fraction(0))
            for bid, n in refs.items():
                acc += Fraction(n, totals[bid])
            per[tenant] = acc
        return per

    def audit(self, refcounts):
        """Verify the accounting identity against the allocator's own
        refcounts (``{block_id: refcount}``) and return the audit
        report.  Raises :class:`LedgerError` naming every violation:

        1. per block: sum of attributed holder refs == the refcount;
        2. per tenant: amortized byte shares sum EXACTLY (Fraction
           arithmetic) to ``used_blocks × block_bytes``.
        """
        totals = self._block_totals()
        problems = []
        for bid, rc in refcounts.items():
            got = totals.get(bid, 0)
            if got != rc:
                problems.append(f"block {bid}: ledger attributes {got} "
                                f"ref(s) but the allocator counts {rc}")
        for bid, got in totals.items():
            if bid not in refcounts:
                problems.append(f"block {bid}: ledger attributes {got} "
                                "ref(s) to a block the allocator does "
                                "not hold")
        exact = self._tenant_amortized_exact()
        total_amortized = sum(exact.values(), Fraction(0))
        used = len(totals)
        if total_amortized != used:
            problems.append(
                f"amortized attribution sums to {float(total_amortized)} "
                f"blocks but {used} are held — per-tenant bytes would "
                "not sum to pool-used bytes")
        if problems:
            raise LedgerError("capacity accounting identity violated:\n  "
                              + "\n  ".join(problems))
        return {
            "used_blocks": used,
            "used_bytes": used * self.block_bytes,
            "total_refs": sum(totals.values()),
            "high_watermark_blocks": self.high_watermark,
            "block_bytes": self.block_bytes,
            "holders": self.holders(),
            "tenants": self.tenants(),
        }


# ---------------------------------------------------------------------------
# the forensic record (built by PagedKVCache, validated here + offline)
# ---------------------------------------------------------------------------
def dump_forensics(path, records):
    """Persist the rolling forensic record set as strict JSON through
    ``checkpoint.atomic_write`` (the PR-7 black-box discipline: a crash
    mid-dump leaves the previous complete file, never a torn one) and
    return the path.  Standalone loads fall back to a plain write."""
    doc = {"format": FORENSIC_FORMAT, "wall_time": time.time(),
           "records": list(records)}
    payload = json.dumps(doc, sort_keys=True, allow_nan=False)
    try:
        from ..checkpoint import atomic_write
    except ImportError:
        # standalone module load (no package -> no durability layer);
        # the packaged path below always uses atomic_write
        # tpumx-lint: disable=durability -- degraded standalone mode only
        with open(path, "w", encoding="utf-8") as f:
            f.write(payload)
    else:
        with atomic_write(path, "w") as f:
            f.write(payload)
    return path


def _num(v):
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def validate_forensic_record(rec):
    """Raise ValueError unless ``rec`` is a schema-valid capacity
    forensic record: a known ``kind``, numeric ``ts``/``need``/``free``/
    ``released``, a complete ``pool`` object, a ``holders`` list naming
    every live holder (their refs must sum to ``total_refs`` — the
    "100% of holders" gate), and a ``tenants`` attribution whose
    amortized bytes sum to pool-used bytes within float-rendering
    tolerance (the live identity is exact; the JSON rounds once)."""
    if not isinstance(rec, dict):
        raise ValueError(f"record is {type(rec).__name__}, not an object")
    kind = rec.get("kind")
    if kind not in FORENSIC_KINDS:
        raise ValueError(f"unknown forensic kind {kind!r} "
                         f"(want one of {FORENSIC_KINDS})")
    for field in ("ts", "need", "free", "released"):
        if not _num(rec.get(field)):
            raise ValueError(f"{kind}: missing numeric {field!r}")
    pool = rec.get("pool")
    if not isinstance(pool, dict):
        raise ValueError(f"{kind}: missing 'pool' object")
    for field in ("num_blocks", "block_bytes", "used_blocks",
                  "total_refs", "high_watermark_blocks", "fragmentation"):
        if not _num(pool.get(field)):
            raise ValueError(f"{kind}: pool missing numeric {field!r}")
    if not 0.0 <= pool["fragmentation"] <= 1.0:
        raise ValueError(f"{kind}: fragmentation "
                         f"{pool['fragmentation']} outside [0, 1]")
    holders = rec.get("holders")
    if not isinstance(holders, list):
        raise ValueError(f"{kind}: missing 'holders' list")
    refs = 0
    for i, h in enumerate(holders):
        if not isinstance(h, dict):
            raise ValueError(f"{kind}: holders[{i}] is not an object")
        for field in ("kind", "id", "tenant"):
            if not isinstance(h.get(field), str) or not h.get(field):
                raise ValueError(f"{kind}: holders[{i}] missing str "
                                 f"{field!r}")
        for field in ("blocks", "exclusive_blocks", "shared_blocks",
                      "age_seconds"):
            if not _num(h.get(field)) or h[field] < 0:
                raise ValueError(f"{kind}: holders[{i}] missing "
                                 f"non-negative {field!r}")
        if not isinstance(h.get("pinned"), bool):
            raise ValueError(f"{kind}: holders[{i}] missing bool 'pinned'")
        refs += h["blocks"]
    if refs != pool["total_refs"]:
        raise ValueError(
            f"{kind}: holders name {refs} block reference(s) but the "
            f"pool counts {pool['total_refs']} — the record does not "
            "name 100% of live holders")
    tenants = rec.get("tenants")
    if not isinstance(tenants, dict):
        raise ValueError(f"{kind}: missing 'tenants' attribution object")
    amortized = 0.0
    for tenant, d in tenants.items():
        if not isinstance(d, dict):
            raise ValueError(f"{kind}: tenants[{tenant!r}] is not an "
                             "object")
        for field in ("bytes_amortized", "bytes_exclusive", "blocks",
                      "refs", "holders"):
            if not _num(d.get(field)) or d[field] < 0:
                raise ValueError(f"{kind}: tenants[{tenant!r}] missing "
                                 f"non-negative {field!r}")
        amortized += d["bytes_amortized"]
    used_bytes = pool["used_blocks"] * pool["block_bytes"]
    if abs(amortized - used_bytes) > max(
            FORENSIC_BYTES_RTOL * used_bytes, 1e-6):
        raise ValueError(
            f"{kind}: per-tenant amortized bytes sum to {amortized} but "
            f"the pool holds {used_bytes} — the accounting identity is "
            "broken in this record")
    return rec


def validate_forensic_doc(doc):
    """Raise ValueError unless ``doc`` is a schema-valid forensic dump:
    the known format tag, numeric ``wall_time``, and a ``records`` list
    whose every entry passes :func:`validate_forensic_record`."""
    if not isinstance(doc, dict):
        raise ValueError(f"forensic doc is {type(doc).__name__}, "
                         "not an object")
    if doc.get("format") != FORENSIC_FORMAT:
        raise ValueError(f"unknown forensic format {doc.get('format')!r} "
                         f"(this build reads {FORENSIC_FORMAT})")
    if not _num(doc.get("wall_time")):
        raise ValueError("forensic doc missing numeric 'wall_time'")
    records = doc.get("records")
    if not isinstance(records, list):
        raise ValueError("forensic doc missing the 'records' list")
    for i, rec in enumerate(records):
        try:
            validate_forensic_record(rec)
        except ValueError as e:
            raise ValueError(f"records[{i}]: {e}") from e
    return doc
