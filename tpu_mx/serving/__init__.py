"""Inference serving runtime: paged KV cache, continuous batching, and a
self-healing request front-end (docs/serving.md).

The training stack (PRs 1-7) runs epochs of batches; this package runs
**streams of requests** — the "millions of users" workload the ROADMAP
names.  Layer map, bottom up:

- :mod:`.kv_cache` — fixed-size block pool + free-list allocator +
  per-sequence block tables; O(1) append per token, exhaustion is
  backpressure (:class:`CacheExhausted`), never OOM.
- :mod:`.attention` — flash-kernel prefill on supported TPU shapes,
  dense-gather decode fallback everywhere (docs/DIVERGENCES.md #27).
- :mod:`.model` — :class:`TinyLM`, the deterministic decode-protocol
  reference model tests/CI/bench drive.
- :mod:`.scheduler` — split prefill/decode queues, per-step continuous
  admission under a max-tokens budget, reject-with-reason backpressure,
  plus the naive :class:`StaticBatchingScheduler` baseline the bench
  measures against.
- :mod:`.engine` — model + cache = prefill/decode compute; chaos fault
  surface (``slow_decode_step``, NaN-poisoned logits health).
- :mod:`.server` — ``submit``/``stream``/``step``; watchdog +
  classified engine restart reusing ``tpu_mx.supervisor``'s patterns —
  queued requests survive a restart and re-run.
- :mod:`.timeline` — per-request latency attribution: every request's
  wall clock decomposed into typed phases (queue_wait/prefill/
  decode_gap/restart_penalty/defer_stall) that sum to the measured
  TTFT/latency.
- :mod:`.slo` — the live SLO monitor: declarative targets over the
  telemetry layer's sliding windows, multi-window error-budget burn
  rate, the ``serve.slo_*`` gauges and the scheduler signal hook —
  per-tenant burn included when tenant-labeled series exist.
- :mod:`.prefix_cache` — the shared-prefix index (ISSUE 12): a trie
  keyed on full block contents so N requests carrying a common template
  map their leading block-table entries onto the SAME physical blocks —
  one prefill, refcounted sharing, copy-on-write on divergence
  (``TPUMX_PREFIX_SHARING``).
- :mod:`.tenancy` — per-tenant weights/quotas and the bounded telemetry
  label: SLO-weighted fair admission, ``tenant_quota`` backpressure.
- :mod:`.accounting` — the capacity ledger (ISSUE 14): every block
  reference attributed to a holder (sequence/index/pinned plan) and a
  tenant, amortized + exclusive-if-forked byte views whose per-tenant
  sum equals pool-used bytes EXACTLY, exhaustion forensics naming every
  holder, and the scheduler's ``capacity_signal`` would-fit hook.

Telemetry (``serve.*`` in ``telemetry.KNOWN_METRICS``) and the request
lifecycle events (``serve.admit/prefill/decode/evict/reject/restart`` in
``tracing.KNOWN_EVENTS``, stamped with the request-scoped trace context)
make every claim here observable; ``tools/ci.py``'s ``serve`` tier
storms a chaos-faulted server and asserts zero lost requests.
"""
from .accounting import (CapacityLedger, FORENSIC_FORMAT,
                         validate_forensic_doc, validate_forensic_record)
from .kv_cache import (BlockAllocator, CacheExhausted, PagedKVCache,
                       PrefillPlan, prefix_sharing_enabled)
from .prefix_cache import PrefixIndex
from .tenancy import TenantConfig, TenantTable
from .attention import (dense_attention, dense_decode_attention,
                        decode_attention, decode_path, prefill_attention,
                        resolve_decode_path)
from .model import TinyLM
from .timeline import RequestTimeline
from .slo import SLO, SLOMonitor
from .scheduler import (AdmissionReject, ContinuousBatchingScheduler,
                        Request, StaticBatchingScheduler)
from .engine import EngineCore
from .server import Server

__all__ = ["BlockAllocator", "CacheExhausted", "PagedKVCache",
           "PrefillPlan", "PrefixIndex", "prefix_sharing_enabled",
           "TenantConfig", "TenantTable",
           "dense_attention", "dense_decode_attention", "decode_attention",
           "decode_path", "resolve_decode_path", "prefill_attention",
           "TinyLM", "AdmissionReject", "ContinuousBatchingScheduler",
           "Request", "StaticBatchingScheduler", "EngineCore", "Server",
           "RequestTimeline", "SLO", "SLOMonitor",
           "CapacityLedger", "FORENSIC_FORMAT",
           "validate_forensic_doc", "validate_forensic_record"]
