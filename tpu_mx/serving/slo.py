"""Live SLO monitor: declarative targets, windowed attainment, burn rate.

The SLO pair (``serve.ttft_seconds`` / ``serve.itl_seconds``) used to be
readable only as cumulative-since-start histograms — an offline receipt,
not an operating signal.  This module turns the telemetry layer's
sliding windows (tpu_mx/telemetry.py) into the three numbers an operator
(or the scheduler) actually acts on, per declared target:

- **estimate** — the windowed quantile ("p99 ITL over the last minute"),
  an O(buckets) bucket-merge read;
- **attainment** — the fraction of window samples inside the threshold;
- **burn rate** — attainment converted to error-budget language: an
  ``itl_p99 < 50ms`` target allows 1% of tokens over 50 ms, so a window
  where 3% ran over burns the budget at 3×.  Classic multi-window
  alerting: the monitor evaluates every window in ``windows`` (default a
  fast 10 s and a slow 60 s) and declares a **breach** only when the
  burn bar is exceeded in ALL of them — the fast window gives reaction
  time, the slow one kills flapping.

:meth:`SLOMonitor.refresh` publishes the state as the cataloged
``serve.slo_*`` gauges (so every flush, scrape and black box carries the
live SLO window — a restarted engine's box shows what the SLOs looked
like at fault time), emits a ``serve.slo`` event on each breach
*transition*, and returns the signal dict the ``Server`` hands to
``scheduler.slo_signal`` — the hook the fleet-scale SLO-weighted
fairness item consumes (ROADMAP).

Targets are declarative: ``SLOMonitor(("itl_p99 < 50ms",
"ttft_p99 < 500ms"))`` — the spec grammar lives in
``telemetry.parse_slo_spec`` so ``tools/slo_report.py`` (jax-less)
parses the same strings.
"""
from __future__ import annotations

import logging
import time

from .. import telemetry as _telemetry
from .. import tracing as _tracing

log = logging.getLogger(__name__)

__all__ = ["SLO", "SLOMonitor", "DEFAULT_SLOS", "DEFAULT_WINDOWS",
           "NO_DATA"]

DEFAULT_SLOS = _telemetry.DEFAULT_SLOS   # the serving pair (one source)
DEFAULT_WINDOWS = (10.0, 60.0)

# sentinel published to serve.slo_estimate_seconds / serve.slo_attainment
# when the evaluation window holds no samples: estimates are positive and
# attainment lives in [0, 1], so -1 is unambiguous, survives strict JSON
# (NaN does not), and can never be mistaken for a live measurement
NO_DATA = -1.0


class SLO:
    """One declarative target: ``metric``'s ``quantile`` must stay under
    ``threshold_seconds``; equivalently, at least ``objective`` of the
    samples must land at or under the threshold (objective defaults to
    the quantile — "p99 < X" allows a 1% error budget)."""

    __slots__ = ("name", "metric", "quantile", "threshold_seconds",
                 "objective")

    def __init__(self, metric, quantile, threshold_seconds, name=None,
                 objective=None):
        self.metric = str(metric)
        self.quantile = float(quantile)
        self.threshold_seconds = float(threshold_seconds)
        self.objective = float(quantile if objective is None else objective)
        if not 0.0 < self.objective < 1.0:
            raise ValueError(f"SLO objective must be in (0, 1), "
                             f"got {self.objective}")
        if self.threshold_seconds <= 0:
            raise ValueError("SLO threshold must be positive")
        self.name = name or f"{self.metric}_p{self.quantile * 100:g}"

    @classmethod
    def parse(cls, spec):
        """``"itl_p99 < 50ms"`` → an :class:`SLO` (grammar:
        ``telemetry.parse_slo_spec``)."""
        d = _telemetry.parse_slo_spec(spec)
        return cls(d["metric"], d["quantile"], d["threshold_seconds"],
                   name=d["name"], objective=d["objective"])

    def __repr__(self):
        return (f"SLO({self.name}: {self.metric} p{self.quantile * 100:g}"
                f" < {self.threshold_seconds * 1e3:g}ms)")


class SLOMonitor:
    """See module docstring.

    ``slos``: SLO objects or spec strings; ``windows``: the trailing
    windows (seconds) evaluated — must fit inside the histograms' ring
    horizon (``telemetry.WINDOW_SECONDS`` unless reconfigured);
    ``breach_burn``: the burn-rate bar (1.0 = exactly consuming the
    budget); ``min_refresh_seconds`` rate-limits :meth:`refresh` so a
    per-step caller costs one clock read between evaluations
    (``force=True`` bypasses it — the restart path does, so black boxes
    capture fault-time state)."""

    def __init__(self, slos=DEFAULT_SLOS, windows=DEFAULT_WINDOWS,
                 breach_burn=1.0, min_refresh_seconds=0.25):
        self.slos = [s if isinstance(s, SLO) else SLO.parse(s)
                     for s in slos]
        names = [s.name for s in self.slos]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {sorted(names)}")
        self.windows = tuple(sorted(float(w) for w in windows))
        if not self.windows:
            raise ValueError("SLOMonitor needs at least one window")
        if self.windows[-1] > _telemetry.WINDOW_SECONDS:
            # the ring silently clamps an oversized window to its
            # horizon, degenerating the multi-window anti-flapping AND
            # into near-identical windows — make that loud (a warning,
            # not an error: a caller may configure_window() individual
            # histograms to a larger horizon)
            log.warning(
                "SLOMonitor window %gs exceeds the default %gs histogram "
                "ring horizon; unless the SLO metrics' windows are "
                "reconfigured larger, reads will be clamped",
                self.windows[-1], _telemetry.WINDOW_SECONDS)
        self.breach_burn = float(breach_burn)
        self.min_refresh_seconds = float(min_refresh_seconds)
        self._last_refresh = None
        self._breaching = {}
        self._signal = {"breaching": False, "max_burn_rate": 0.0,
                        "slos": {}}

    # -- evaluation (pure read; no gauges, no events) -------------------------
    def _eval_windows(self, h, slo, allowed):
        """One histogram's multi-window read for one target:
        ``(attainments, burns, sampled)`` aligned with
        ``self.windows`` (attainment None / burn 0 for an empty
        window).  The ONE place the burn rule lives — the global and
        per-tenant evaluations must never drift apart."""
        atts, burns, sampled = [], [], False
        for w in self.windows:
            frac = (h.window_fraction_le(slo.threshold_seconds,
                                         window=w) if h else None)
            if frac is None:
                atts.append(None)
                burns.append(0.0)
            else:
                sampled = True
                atts.append(frac)
                burns.append((1.0 - frac) / allowed)
        return atts, burns, sampled

    def evaluate(self):
        """The full state dict, computed from the live telemetry
        windows: ``{breaching, max_burn_rate, breaching_tenants,
        slos: {name: {...}}}``.  An SLO with no samples in a window is
        healthy-by-absence there (attainment None, burn 0) — breach
        requires evidence in every window, never its lack.

        **Per-tenant** (ISSUE 12): when tenant-labeled series of a
        target's histogram exist (``serve.itl_seconds{tenant=...}`` —
        the bounded labels tenancy.label_for mints), each is evaluated
        with the same multi-window rule into ``slos[name]["tenants"]
        [tenant] = {burn_rate, attainment, breaching}``, and the union
        of breaching tenants lands in ``breaching_tenants`` — the
        signal the scheduler's SLO-weighted boost consumes."""
        out = {"breaching": False, "max_burn_rate": 0.0,
               "breaching_tenants": [], "slos": {}}
        breaching_tenants = set()
        for slo in self.slos:
            h = _telemetry.get(slo.metric)
            if getattr(h, "kind", None) != "histogram":
                h = None
            allowed = 1.0 - slo.objective
            # read the estimate over the SLOWEST evaluation window so it
            # describes the same time range as the attainment/burn it is
            # published next to — window=None would read the histogram's
            # full ring horizon (60s default), showing a long-recovered
            # p99 beside an already-clean attainment
            est = (h.window_quantile(slo.quantile, window=self.windows[-1])
                   if h else None)
            atts, burns, sampled = self._eval_windows(h, slo, allowed)
            windows = {w: {"attainment": atts[i], "burn_rate": burns[i]}
                       for i, w in enumerate(self.windows)}
            breaching = sampled and all(b >= self.breach_burn
                                        for b in burns)
            tenants = {}
            for labels, th in _telemetry.series(slo.metric):
                tenant = labels.get("tenant")
                if tenant is None or getattr(th, "kind", None) \
                        != "histogram":
                    continue
                tatts, tburns, tsampled = self._eval_windows(th, slo,
                                                             allowed)
                tbreach = tsampled and all(b >= self.breach_burn
                                           for b in tburns)
                seen = [a for a in tatts if a is not None]
                tenants[tenant] = {
                    "burn_rate": max(tburns),
                    "attainment": min(seen) if seen else None,
                    "breaching": tbreach,
                }
                if tbreach:
                    breaching_tenants.add(tenant)
            out["slos"][slo.name] = {
                "metric": slo.metric,
                "quantile": slo.quantile,
                "threshold_seconds": slo.threshold_seconds,
                "estimate_seconds": est,
                "breaching": breaching,
                "windows": windows,
                "tenants": tenants,
            }
            out["breaching"] = out["breaching"] or breaching
            out["max_burn_rate"] = max(out["max_burn_rate"], *burns)
        out["breaching_tenants"] = sorted(breaching_tenants)
        return out

    # -- publication ---------------------------------------------------------
    def refresh(self, force=False):
        """Evaluate (rate-limited unless ``force``), publish the
        ``serve.slo_*`` gauges, emit ``serve.slo`` on breach
        transitions, and return (and remember) the signal dict."""
        now = time.monotonic()
        if (not force and self._last_refresh is not None
                and now - self._last_refresh < self.min_refresh_seconds):
            return self._signal
        self._last_refresh = now
        result = self.evaluate()
        # an empty window publishes the NO_DATA sentinel (-1.0): a gauge
        # frozen at its last non-empty value would let a dashboard read
        # a stale estimate as live after traffic stops, and NaN — the
        # Prometheus idiom — is invalid strict JSON, which would break
        # the black-box/JSONL "read it anywhere" contract
        for name, st in result["slos"].items():
            est = st["estimate_seconds"]
            _telemetry.gauge("serve.slo_estimate_seconds",
                             slo=name).set(NO_DATA if est is None else est)
            _telemetry.gauge("serve.slo_breaching", slo=name).set(
                1.0 if st["breaching"] else 0.0)
            worst_att, worst_burn = None, 0.0
            for w, pw in st["windows"].items():
                wl = f"{w:g}s"
                att = pw["attainment"]
                _telemetry.gauge("serve.slo_attainment", slo=name,
                                 window=wl).set(
                                     NO_DATA if att is None else att)
                if att is not None:
                    worst_att = (att if worst_att is None
                                 else min(worst_att, att))
                _telemetry.gauge("serve.slo_burn_rate", slo=name,
                                 window=wl).set(pw["burn_rate"])
                worst_burn = max(worst_burn, pw["burn_rate"])
            # per-tenant worst-window burn (ISSUE 12): one gauge per
            # (slo, tenant) — tenant labels are already cardinality-
            # bounded at the source (tenancy.label_for)
            for tenant, ts in st.get("tenants", {}).items():
                _telemetry.gauge("serve.slo_tenant_burn_rate", slo=name,
                                 tenant=tenant).set(ts["burn_rate"])
            if st["breaching"] != self._breaching.get(name, False):
                _tracing.emit(
                    "serve.slo", slo=name, breaching=st["breaching"],
                    burn_rate=worst_burn,
                    estimate_seconds=(NO_DATA if est is None
                                      else float(est)),
                    attainment=float(NO_DATA if worst_att is None
                                     else worst_att),
                    threshold_seconds=st["threshold_seconds"])
            self._breaching[name] = st["breaching"]
        self._signal = result
        return result

    def signal(self):
        """The most recent :meth:`refresh` result (the scheduler-facing
        hook; cheap — no evaluation)."""
        return self._signal
