"""Weight initializers (REF:python/mxnet/initializer.py).

String-registered like the reference (`init='xavier'`); produce numpy arrays
so Parameter can place them on any context. Name-based aux handling matches
the reference convention (running_mean→0, running_var→1, bias→0, gamma→1).
"""
from __future__ import annotations

import math
import os

import numpy as np

from .base import Registry
from .random import host_rng as _host_rng

__all__ = ["Initializer", "Uniform", "Normal", "Zero", "One", "Constant",
           "Xavier", "MSRAPrelu", "Orthogonal", "Bilinear", "LSTMBias", "registry"]

registry = Registry("initializer")


def _aux_value(name):
    """Name-convention constant for aux/affine params, or None for weights."""
    if name.endswith(("running_mean", "moving_mean")):
        return 0.0
    if name.endswith(("running_var", "moving_var")):
        return 1.0
    if name.endswith("gamma"):
        return 1.0
    if name.endswith(("beta", "bias")):
        return 0.0
    return None


class Initializer:
    """Base: dispatch on parameter-name convention, like the reference's
    InitDesc-driven `__call__`."""

    def __call__(self, name, shape, dtype="float32"):
        aux = _aux_value(name)
        if aux is not None:
            return np.full(shape, aux, dtype)
        return self._init_weight(name, shape).astype(dtype)

    def _init_weight(self, name, shape):
        raise NotImplementedError

    def device_sample(self, name, shape, dtype="float32"):
        """Sample this parameter ON DEVICE, or return None for the
        host-numpy path.

        No reference analog — the reference fills host buffers and copies
        (REF:python/mxnet/initializer.py); over the tunneled TPU that
        means ~100 MB (ResNet-50) to ~440 MB (BERT-base) of host→device
        parameter traffic before the first step.  Standard initializers
        instead sample with the chip's own PRNG (seeded by
        `mx.random.seed`).  Falls back to host (None) when:
        - TPUMX_HOST_INIT=1 (global revert knob),
        - the subclass overrides __call__ (its name-dispatch semantics
          are unknown here, e.g. LSTMBias),
        - the active PRNG key is traced (deferred init firing inside a
          jit trace must not capture a tracer in Parameter._data),
        - the subclass has no closed-form device rule (Orthogonal's SVD,
          Bilinear's loop)."""
        if os.environ.get("TPUMX_HOST_INIT") == "1":
            return None
        if type(self).__call__ is not Initializer.__call__:
            return None
        import jax
        import jax.numpy as jnp
        from . import random as _random
        # the trace guard must come BEFORE any jnp call: inside a trace
        # (hybridize-before-first-forward, eval_shape) even jnp.full
        # stages into the jaxpr, and a tracer stored in Parameter._data
        # outlives the trace
        try:
            from jax._src.core import trace_state_clean
            if not trace_state_clean():
                return None
        except Exception:
            # jax moved the internal: probe with a key split instead
            if isinstance(_random.take_key(), jax.core.Tracer):
                return None
        aux = _aux_value(name)
        if aux is not None:
            return jnp.full(shape, aux, dtype)
        if self._device_weight.__func__ is Initializer._device_weight:
            return None  # no device rule; skip the key split
        key = _random.take_key() if self._needs_key else None
        out = self._device_weight(key, shape)
        return None if out is None else out.astype(dtype)

    _needs_key = True  # Zero/One/Constant ignore the PRNG: no key split

    def _device_weight(self, key, shape):
        return None


@registry.register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        self.scale = scale

    def _init_weight(self, name, shape):
        return _host_rng().uniform(-self.scale, self.scale, size=shape)

    def _device_weight(self, key, shape):
        import jax
        return jax.random.uniform(key, shape, minval=-self.scale,
                                  maxval=self.scale)


@registry.register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        self.sigma = sigma

    def _init_weight(self, name, shape):
        return _host_rng().normal(0, self.sigma, size=shape)

    def _device_weight(self, key, shape):
        import jax
        return self.sigma * jax.random.normal(key, shape)


@registry.register(aliases=("zeros",))
class Zero(Initializer):
    _needs_key = False

    def _init_weight(self, name, shape):
        return np.zeros(shape)

    def _device_weight(self, key, shape):
        import jax.numpy as jnp
        return jnp.zeros(shape)


@registry.register(aliases=("ones",))
class One(Initializer):
    _needs_key = False

    def _init_weight(self, name, shape):
        return np.ones(shape)

    def _device_weight(self, key, shape):
        import jax.numpy as jnp
        return jnp.ones(shape)


@registry.register
class Constant(Initializer):
    _needs_key = False

    def __init__(self, value=0.0):
        self.value = value

    def _init_weight(self, name, shape):
        return np.full(shape, self.value)

    def _device_weight(self, key, shape):
        import jax.numpy as jnp
        # no dtype pin: device_sample's astype(dtype) converts exactly
        # like the host np.full path (a float32 detour would round large
        # ints differently per path)
        return jnp.full(shape, self.value)


class Mixed:
    """Pattern-routed initializer (REF initializer.py:Mixed): first regex
    matching the parameter name picks the initializer."""

    def __init__(self, patterns, initializers):
        import re as _re
        if len(patterns) != len(initializers):
            raise ValueError("patterns and initializers must pair up")
        self._map = [(_re.compile(p), i if not isinstance(i, str)
                      else registry.create(i))
                     for p, i in zip(patterns, initializers)]

    def __call__(self, name, shape, dtype="float32"):
        for pat, init in self._map:
            if pat.search(name):
                return init(name, shape, dtype)
        raise ValueError(f"no initializer pattern matches {name!r}; "
                         "add a '.*' catch-all")


class Load:
    """Initialize from saved arrays (REF initializer.py:Load): dict or
    .npz/.params path; falls back to default_init for absent names."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            from .ndarray import load as nd_load
            param = nd_load(param)
        self._param = {k.split(":", 1)[-1]: v for k, v in param.items()}
        self._default = default_init
        self._verbose = verbose

    def __call__(self, name, shape, dtype="float32"):
        if name in self._param:
            arr = self._param[name]
            arr = arr.asnumpy() if hasattr(arr, "asnumpy") else np.asarray(arr)
            if tuple(arr.shape) != tuple(shape):
                raise ValueError(
                    f"Load: shape mismatch for {name}: saved "
                    f"{arr.shape} vs wanted {tuple(shape)}")
            return arr.astype(dtype)
        if self._default is None:
            raise ValueError(f"Load: {name!r} not in saved params and no "
                             "default_init given")
        return self._default(name, shape, dtype)


def _fan(shape, factor_type):
    hw = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    fan_in = shape[1] * hw if len(shape) > 1 else shape[0]
    fan_out = shape[0] * hw
    if factor_type == "in":
        return fan_in
    if factor_type == "out":
        return fan_out
    return (fan_in + fan_out) / 2.0


@registry.register
class Xavier(Initializer):
    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = magnitude

    def _init_weight(self, name, shape):
        factor = _fan(shape, self.factor_type)
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            return _host_rng().uniform(-scale, scale, size=shape)
        return _host_rng().normal(0, scale, size=shape)

    def _device_weight(self, key, shape):
        import jax
        factor = _fan(shape, self.factor_type)
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            return jax.random.uniform(key, shape, minval=-scale,
                                      maxval=scale)
        return scale * jax.random.normal(key, shape)


@registry.register(name="msraprelu")
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        super().__init__("gaussian", factor_type, 2.0 / (1 + slope ** 2))


@registry.register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, shape):
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        if self.rand_type == "uniform":
            tmp = _host_rng().uniform(-1.0, 1.0, (rows, cols))
        else:
            tmp = _host_rng().normal(0.0, 1.0, (rows, cols))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == (rows, cols) else v
        return (self.scale * q).reshape(shape)


@registry.register
class Bilinear(Initializer):
    def _init_weight(self, name, shape):
        weight = np.zeros(int(np.prod(shape)))
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        return weight.reshape(shape)


@registry.register(name="lstmbias")
class LSTMBias(Initializer):
    """Forget-gate bias = 1 (reference: initializer.LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        self.forget_bias = forget_bias

    def __call__(self, name, shape, dtype="float32"):
        b = np.zeros(shape, dtype)
        n = shape[0] // 4
        b[n:2 * n] = self.forget_bias
        return b

    def _init_weight(self, name, shape):
        return np.zeros(shape)


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    return registry.create(name, **kwargs)
