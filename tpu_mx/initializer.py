"""Weight initializers (REF:python/mxnet/initializer.py).

String-registered like the reference (`init='xavier'`); produce numpy arrays
so Parameter can place them on any context. Name-based aux handling matches
the reference convention (running_mean→0, running_var→1, bias→0, gamma→1).
"""
from __future__ import annotations

import math

import numpy as np

from .base import Registry

__all__ = ["Initializer", "Uniform", "Normal", "Zero", "One", "Constant",
           "Xavier", "MSRAPrelu", "Orthogonal", "Bilinear", "LSTMBias", "registry"]

registry = Registry("initializer")


class Initializer:
    """Base: dispatch on parameter-name convention, like the reference's
    InitDesc-driven `__call__`."""

    def __call__(self, name, shape, dtype="float32"):
        if name.endswith("running_mean") or name.endswith("moving_mean"):
            return np.zeros(shape, dtype)
        if name.endswith("running_var") or name.endswith("moving_var"):
            return np.ones(shape, dtype)
        if name.endswith("gamma"):
            return np.ones(shape, dtype)
        if name.endswith("beta") or name.endswith("bias"):
            return np.zeros(shape, dtype)
        return self._init_weight(name, shape).astype(dtype)

    def _init_weight(self, name, shape):
        raise NotImplementedError


@registry.register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        self.scale = scale

    def _init_weight(self, name, shape):
        return np.random.uniform(-self.scale, self.scale, size=shape)


@registry.register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        self.sigma = sigma

    def _init_weight(self, name, shape):
        return np.random.normal(0, self.sigma, size=shape)


@registry.register(aliases=("zeros",))
class Zero(Initializer):
    def _init_weight(self, name, shape):
        return np.zeros(shape)


@registry.register(aliases=("ones",))
class One(Initializer):
    def _init_weight(self, name, shape):
        return np.ones(shape)


@registry.register
class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def _init_weight(self, name, shape):
        return np.full(shape, self.value)


class Mixed:
    """Pattern-routed initializer (REF initializer.py:Mixed): first regex
    matching the parameter name picks the initializer."""

    def __init__(self, patterns, initializers):
        import re as _re
        if len(patterns) != len(initializers):
            raise ValueError("patterns and initializers must pair up")
        self._map = [(_re.compile(p), i if not isinstance(i, str)
                      else registry.create(i))
                     for p, i in zip(patterns, initializers)]

    def __call__(self, name, shape, dtype="float32"):
        for pat, init in self._map:
            if pat.search(name):
                return init(name, shape, dtype)
        raise ValueError(f"no initializer pattern matches {name!r}; "
                         "add a '.*' catch-all")


class Load:
    """Initialize from saved arrays (REF initializer.py:Load): dict or
    .npz/.params path; falls back to default_init for absent names."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            from .ndarray import load as nd_load
            param = nd_load(param)
        self._param = {k.split(":", 1)[-1]: v for k, v in param.items()}
        self._default = default_init
        self._verbose = verbose

    def __call__(self, name, shape, dtype="float32"):
        if name in self._param:
            arr = self._param[name]
            arr = arr.asnumpy() if hasattr(arr, "asnumpy") else np.asarray(arr)
            if tuple(arr.shape) != tuple(shape):
                raise ValueError(
                    f"Load: shape mismatch for {name}: saved "
                    f"{arr.shape} vs wanted {tuple(shape)}")
            return arr.astype(dtype)
        if self._default is None:
            raise ValueError(f"Load: {name!r} not in saved params and no "
                             "default_init given")
        return self._default(name, shape, dtype)


def _fan(shape, factor_type):
    hw = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    fan_in = shape[1] * hw if len(shape) > 1 else shape[0]
    fan_out = shape[0] * hw
    if factor_type == "in":
        return fan_in
    if factor_type == "out":
        return fan_out
    return (fan_in + fan_out) / 2.0


@registry.register
class Xavier(Initializer):
    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = magnitude

    def _init_weight(self, name, shape):
        factor = _fan(shape, self.factor_type)
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            return np.random.uniform(-scale, scale, size=shape)
        return np.random.normal(0, scale, size=shape)


@registry.register(name="msraprelu")
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        super().__init__("gaussian", factor_type, 2.0 / (1 + slope ** 2))


@registry.register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, shape):
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        if self.rand_type == "uniform":
            tmp = np.random.uniform(-1.0, 1.0, (rows, cols))
        else:
            tmp = np.random.normal(0.0, 1.0, (rows, cols))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == (rows, cols) else v
        return (self.scale * q).reshape(shape)


@registry.register
class Bilinear(Initializer):
    def _init_weight(self, name, shape):
        weight = np.zeros(int(np.prod(shape)))
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        return weight.reshape(shape)


@registry.register(name="lstmbias")
class LSTMBias(Initializer):
    """Forget-gate bias = 1 (reference: initializer.LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        self.forget_bias = forget_bias

    def __call__(self, name, shape, dtype="float32"):
        b = np.zeros(shape, dtype)
        n = shape[0] // 4
        b[n:2 * n] = self.forget_bias
        return b

    def _init_weight(self, name, shape):
        return np.zeros(shape)


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    return registry.create(name, **kwargs)
