"""Runtime-compiled custom kernels (REF:python/mxnet/rtc.py CudaModule over
NVRTC, REF:src/common/rtc.cc).

TPU divergence, stated plainly: there is no C-source JIT on TPU — the
runtime kernel language is **Pallas** (Python → Mosaic), compiled at first
call like NVRTC compiled CUDA C at CudaModule construction.  This module
keeps the reference's *shape* — build a module, `get_kernel(name, ...)`,
`kernel.launch(args, grid, ...)` — so ported code changes its kernel
bodies, not its scaffolding.

    def scale_kernel(x_ref, o_ref, *, alpha):
        o_ref[:] = x_ref[:] * alpha

    mod = mx.rtc.PallasModule({"scale": scale_kernel})
    k = mod.get_kernel("scale", alpha=3.0)
    y = k.launch((x,), out_shape=x.shape, out_dtype=x.dtype)
"""
from __future__ import annotations

import functools

from .base import MXNetError
from .ndarray import NDArray

__all__ = ["PallasModule", "Kernel"]


class Kernel:
    """A launchable kernel (reference: CudaKernel).  ``launch`` mirrors
    ``CudaKernel.launch(args, ctx, grid_dims, block_dims)`` with TPU-native
    block semantics: ``grid`` + Pallas BlockSpecs instead of thread dims."""

    def __init__(self, name, fn, static_kwargs):
        self.name = name
        self._fn = fn
        self._static = static_kwargs

    def launch(self, args, out_shape=None, out_dtype="float32", grid=None,
               in_specs=None, out_specs=None, interpret=None):
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        if out_shape is None:
            out_shape = args[0].shape
        raw = [a._data if isinstance(a, NDArray) else jnp.asarray(a)
               for a in args]
        kern = functools.partial(self._fn, **self._static) if self._static \
            else self._fn
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        kwargs = {}
        if grid is not None:
            kwargs["grid"] = grid
        if in_specs is not None:
            kwargs["in_specs"] = in_specs
        if out_specs is not None:
            kwargs["out_specs"] = out_specs
        out = pl.pallas_call(
            kern,
            out_shape=jax.ShapeDtypeStruct(tuple(out_shape),
                                           jnp.dtype(out_dtype)),
            interpret=interpret,
            **kwargs,
        )(*raw)
        return NDArray(out)

    __call__ = launch


class PallasModule:
    """Holds named kernels (reference: CudaModule holds compiled source).
    ``exports`` filters which names are visible, as in the reference."""

    def __init__(self, kernels, exports=None):
        if callable(kernels):
            kernels = {kernels.__name__: kernels}
        self._kernels = dict(kernels)
        self._exports = set(exports) if exports is not None else None

    def get_kernel(self, name, **static_kwargs):
        if name not in self._kernels or (
                self._exports is not None and name not in self._exports):
            raise MXNetError(
                f"kernel {name!r} not found/exported "
                f"(have: {sorted(self._kernels)})")
        return Kernel(name, self._kernels[name], static_kwargs)
