"""Global conv data-layout switch (TPU-first redesign).

MXNet threads ``layout=`` through every conv/pool constructor
(REF:python/mxnet/gluon/nn/conv_layers.py).  We keep those kwargs, but add a
thread-local *default* so a whole model (e.g. the NCHW-written model zoo) can
be instantiated channels-last without editing each constructor:

    with tpu_mx.layout.default_layout("NHWC"):
        net = vision.resnet50_v1()
    # net now expects NHWC input and runs channels-last end-to-end.

Why: XLA:TPU keeps the minor-most dimension in the 128-wide lane registers.
Channels-last puts C (a multiple of 128 through most of ResNet) in the lanes,
so convolutions tile straight onto the MXU with no layout copies; NCHW puts
W there instead and the compiler has to relayout around every conv.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

_state = threading.local()

_CHANNELS_FIRST = {1: "NCW", 2: "NCHW", 3: "NCDHW"}
_CHANNELS_LAST = {1: "NWC", 2: "NHWC", 3: "NDHWC"}


def get_default_layout(ndim: int = 2) -> str:
    """Current default data layout for an ``ndim``-spatial-dim conv."""
    mode = getattr(_state, "mode", "channels_first")
    return (_CHANNELS_LAST if mode == "channels_last" else _CHANNELS_FIRST)[ndim]


_KNOWN = (set(_CHANNELS_FIRST.values()) | set(_CHANNELS_LAST.values())
          | {"channels_first", "channels_last"})


def is_channels_last(layout: str | None) -> bool:
    return layout is not None and layout.endswith("C")


def channel_axis() -> int:
    """Channel axis under the current layout mode (for concat, BatchNorm,
    any channel-wise op): 1 channels-first, -1 channels-last."""
    return -1 if getattr(_state, "mode", "channels_first") == "channels_last" \
        else 1


def bn_axis() -> int:
    """Default BatchNorm channel axis — alias of `channel_axis()`."""
    return channel_axis()


@contextmanager
def default_layout(layout: str):
    """Set the default conv/pool/BatchNorm layout for blocks built inside.

    ``layout`` is any MXNet layout string ("NHWC", "NCHW", "NWC", ...) or a
    Keras-style "channels_first"/"channels_last"; only the orientation is
    recorded.
    """
    if layout not in _KNOWN:
        raise ValueError(
            f"unknown layout {layout!r}; expected one of {sorted(_KNOWN)}")
    prev = getattr(_state, "mode", "channels_first")
    _state.mode = "channels_last" \
        if layout == "channels_last" or layout.endswith("C") \
        else "channels_first"
    try:
        yield
    finally:
        _state.mode = prev
