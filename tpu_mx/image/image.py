"""mx.image — image IO, augmenters, ImageIter (REF:python/mxnet/image/image.py).

TPU-native design: the reference decodes/augments with OpenCV into NCHW
float batches on the CPU, then copies to device.  Here decode is PIL (no
OpenCV in the image), augment is pure numpy on the host — augmentation
stays off the TPU on purpose: the chip's MXU time is for the model, and
host-side numpy keeps the input pipeline overlappable with device compute
(the iterator returns host arrays; `device_put` happens at the training
step, double-buffered by JAX's async dispatch)."""
from __future__ import annotations

import io as _io
import os
import random as _pyrandom

import numpy as np

from ..random import host_rng as _host_rng
from ..base import MXNetError
from ..io.io import DataBatch, DataDesc, DataIter
from ..ndarray import NDArray, array
from .. import recordio as _recordio

__all__ = ["random_size_crop", "HueJitterAug", "LightingAug",
           "RandomGrayAug", "RandomOrderAug", "SequentialAug",
           "RandomSizedCropAug",
           "imread", "imdecode", "imresize", "resize_short", "fixed_crop",
           "random_crop", "center_crop", "color_normalize", "HorizontalFlipAug",
           "ResizeAug", "ForceResizeAug", "RandomCropAug", "CenterCropAug",
           "CastAug", "ColorNormalizeAug", "BrightnessJitterAug",
           "ContrastJitterAug", "SaturationJitterAug", "ColorJitterAug",
           "CreateAugmenter", "Augmenter", "ImageIter"]


def _pil():
    try:
        from PIL import Image
        return Image
    except ImportError as e:  # pragma: no cover
        raise MXNetError("mx.image requires Pillow in this build") from e


# --------------------------------------------------------------------------
# IO — numpy HWC uint8/float arrays in, NDArray out (reference convention)
# --------------------------------------------------------------------------

def imdecode(buf, to_rgb=True, flag=1, **kw):
    """Decode an encoded image buffer -> NDArray HWC (RGB order like the
    reference's default to_rgb=1)."""
    Image = _pil()
    img = Image.open(_io.BytesIO(buf if isinstance(buf, (bytes, bytearray))
                                 else bytes(buf)))
    if flag == 0:
        img = img.convert("L")
        arr = np.asarray(img)[:, :, None]
    else:
        img = img.convert("RGB")
        arr = np.asarray(img)
        if not to_rgb:
            arr = arr[:, :, ::-1]
    return array(arr.astype(np.uint8), dtype="uint8")


def imread(filename, to_rgb=True, flag=1, **kw):
    with open(filename, "rb") as f:
        return imdecode(f.read(), to_rgb=to_rgb, flag=flag)


def imresize(src, w, h, interp=1):
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    if arr.dtype != np.uint8:
        # float pixels (post-Cast/normalize): resize WITHOUT truncating to
        # uint8 — the reference preserves dtype through crops/resizes
        try:
            import cv2
            flags = {0: cv2.INTER_NEAREST, 1: cv2.INTER_LINEAR,
                     2: cv2.INTER_CUBIC, 3: cv2.INTER_LANCZOS4}
            out = cv2.resize(arr.astype(np.float32), (int(w), int(h)),
                             interpolation=flags.get(interp,
                                                     cv2.INTER_LINEAR))
            if out.ndim == 2:
                out = out[:, :, None]
            return array(out.astype(np.float32))
        except ImportError:
            pass  # fall through to the PIL uint8 path
    Image = _pil()
    squeeze = arr.shape[-1] == 1
    img = Image.fromarray(arr.squeeze(-1).astype(np.uint8) if squeeze
                          else arr.astype(np.uint8))
    resample = {0: Image.NEAREST, 1: Image.BILINEAR, 2: Image.BICUBIC,
                3: Image.LANCZOS}.get(interp, Image.BILINEAR)
    out = np.asarray(img.resize((int(w), int(h)), resample))
    if squeeze:
        out = out[:, :, None]
    return array(out.astype(np.uint8), dtype="uint8")


def resize_short(src, size, interp=1):
    h, w = (src.shape[0], src.shape[1])
    if h > w:
        new_w, new_h = size, int(size * h / w)
    else:
        new_w, new_h = int(size * w / h), size
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=1):
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    out = arr[y0:y0 + h, x0:x0 + w]
    dtype = "uint8" if arr.dtype == np.uint8 else None  # preserve floats
    if size is not None and (w, h) != tuple(size):
        return imresize(array(out, dtype=dtype), size[0], size[1], interp)
    return array(out, dtype=dtype)


def random_crop(src, size, interp=1):
    h, w = src.shape[0], src.shape[1]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = _pyrandom.randint(0, w - new_w)
    y0 = _pyrandom.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=1):
    h, w = src.shape[0], src.shape[1]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=1):
    """Random area/aspect crop (REF image.py:random_size_crop — the
    Inception-style crop): `area` is (min,max) fraction (scalar = min),
    `ratio` the (min,max) aspect range; falls back to center_crop when no
    candidate fits in 10 draws, like the reference."""
    h, w = src.shape[0], src.shape[1]
    src_area = h * w
    if not isinstance(area, (list, tuple)):
        area = (area, 1.0)
    for _ in range(10):
        target = _pyrandom.uniform(area[0], area[1]) * src_area
        log_r = (np.log(ratio[0]), np.log(ratio[1]))
        ar = float(np.exp(_pyrandom.uniform(*log_r)))
        new_w = int(round(np.sqrt(target * ar)))
        new_h = int(round(np.sqrt(target / ar)))
        if new_w <= w and new_h <= h:
            x0 = _pyrandom.randint(0, w - new_w)
            y0 = _pyrandom.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    arr = (src.asnumpy() if isinstance(src, NDArray)
           else np.asarray(src)).astype(np.float32)
    arr = arr - np.asarray(mean, np.float32)
    if std is not None:
        arr = arr / np.asarray(std, np.float32)
    return array(arr)


# --------------------------------------------------------------------------
# augmenters (host-side numpy; reference: image.py Augmenter family)
# --------------------------------------------------------------------------

class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, src):
        raise NotImplementedError

    def dumps(self):
        import json
        return json.dumps([type(self).__name__, self._kwargs])


class ResizeAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            arr = src.asnumpy() if isinstance(src, NDArray) else src
            return array(np.ascontiguousarray(arr[:, ::-1]), dtype="uint8")
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(typ=typ)
        self.typ = typ

    def __call__(self, src):
        arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
        return array(arr.astype(self.typ))


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean, self.std = mean, std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.brightness, self.brightness)
        arr = (src.asnumpy() if isinstance(src, NDArray)
               else np.asarray(src)).astype(np.float32)
        return array(arr * alpha)


class ContrastJitterAug(Augmenter):
    _coef = np.array([0.299, 0.587, 0.114], np.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.contrast, self.contrast)
        arr = (src.asnumpy() if isinstance(src, NDArray)
               else np.asarray(src)).astype(np.float32)
        gray = (arr[..., :3] * self._coef).sum(axis=-1).mean()
        return array(arr * alpha + gray * (1 - alpha))


class SaturationJitterAug(Augmenter):
    _coef = ContrastJitterAug._coef

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.saturation, self.saturation)
        arr = (src.asnumpy() if isinstance(src, NDArray)
               else np.asarray(src)).astype(np.float32)
        gray = (arr[..., :3] * self._coef).sum(axis=-1, keepdims=True)
        return array(arr * alpha + gray * (1 - alpha))


class ColorJitterAug(Augmenter):
    def __init__(self, brightness=0, contrast=0, saturation=0):
        super().__init__(brightness=brightness, contrast=contrast,
                         saturation=saturation)
        self._augs = []
        if brightness:
            self._augs.append(BrightnessJitterAug(brightness))
        if contrast:
            self._augs.append(ContrastJitterAug(contrast))
        if saturation:
            self._augs.append(SaturationJitterAug(saturation))

    def __call__(self, src):
        augs = list(self._augs)
        _pyrandom.shuffle(augs)
        for a in augs:
            src = a(src)
        return src


class HueJitterAug(Augmenter):
    """REF image.py:HueJitterAug — rotate hue via the YIQ linear approx
    the reference uses (no HSV conversion on the hot path)."""

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self.tyiq = np.array([[0.299, 0.587, 0.114],
                              [0.596, -0.274, -0.321],
                              [0.211, -0.523, 0.311]], np.float32)
        self.ityiq = np.array([[1.0, 0.956, 0.621],
                               [1.0, -0.272, -0.647],
                               [1.0, -1.107, 1.705]], np.float32)

    def __call__(self, src):
        alpha = _pyrandom.uniform(-self.hue, self.hue)
        u = np.cos(alpha * np.pi)
        wv = np.sin(alpha * np.pi)
        bt = np.array([[1.0, 0.0, 0.0],
                       [0.0, u, -wv],
                       [0.0, wv, u]], np.float32)
        t = (self.ityiq @ bt @ self.tyiq).T
        arr = (src.asnumpy() if isinstance(src, NDArray)
               else np.asarray(src)).astype(np.float32)
        return array(arr @ t)


class LightingAug(Augmenter):
    """REF image.py:LightingAug — AlexNet-style PCA noise."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval, np.float32)
        self.eigvec = np.asarray(eigvec, np.float32)

    def __call__(self, src):
        alpha = _host_rng().normal(0, self.alphastd, size=(3,)).astype(
            np.float32)
        rgb = self.eigvec @ (alpha * self.eigval)
        arr = (src.asnumpy() if isinstance(src, NDArray)
               else np.asarray(src)).astype(np.float32)
        return array(arr + rgb)


class RandomGrayAug(Augmenter):
    """REF image.py:RandomGrayAug — grayscale with probability p."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p
        self.mat = np.array([[0.21, 0.21, 0.21],
                             [0.72, 0.72, 0.72],
                             [0.07, 0.07, 0.07]], np.float32)

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            arr = (src.asnumpy() if isinstance(src, NDArray)
                   else np.asarray(src)).astype(np.float32)
            return array(arr @ self.mat)
        return src


class RandomOrderAug(Augmenter):
    """REF image.py:RandomOrderAug — apply children in random order."""

    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def dumps(self):  # recurse like the reference's composite dumps
        return [type(self).__name__, [t.dumps() for t in self.ts]]

    def __call__(self, src):
        ts = list(self.ts)
        _pyrandom.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class SequentialAug(Augmenter):
    """REF image.py:SequentialAug — apply children in order."""

    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def dumps(self):
        return [type(self).__name__, [t.dumps() for t in self.ts]]

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class RandomSizedCropAug(Augmenter):
    """REF image.py:RandomSizedCropAug over random_size_crop."""

    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size = size
        self.area = area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """REF:python/mxnet/image/image.py CreateAugmenter — same flag set."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3 / 4.0, 4 / 3.0), inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        auglist.append(LightingAug(
            pca_noise,
            np.array([55.46, 4.794, 1.148], np.float32),
            np.array([[-0.5675, 0.7192, 0.4009],
                      [-0.5808, -0.0045, -0.8140],
                      [-0.5836, -0.6948, 0.4203]], np.float32)))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(
            mean if mean is not None else np.zeros(3, np.float32), std))
    return auglist


# --------------------------------------------------------------------------
# ImageIter — RecordIO (.rec) or .lst/root file lists -> NCHW batches
# (REF:python/mxnet/image/image.py ImageIter; the C++ twin is
#  REF:src/io/iter_image_recordio_2.cc)
# --------------------------------------------------------------------------

class ImageIter(DataIter):

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 shuffle=False, aug_list=None, imglist=None,
                 data_name="data", label_name="softmax_label", dtype="float32",
                 last_batch_handle="pad", **kwargs):
        super().__init__(batch_size)
        assert len(data_shape) == 3, "data_shape must be (C, H, W)"
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.dtype = dtype
        self._data_name = data_name
        self._label_name = label_name
        self.auglist = (aug_list if aug_list is not None
                        else CreateAugmenter(data_shape))
        self._record = None
        self.seq = []
        self.imglist = {}
        if path_imgrec:
            self._record = _recordio.MXIndexedRecordIO(
                path_imgrec[:-4] + ".idx" if path_imgrec.endswith(".rec")
                else path_imgrec + ".idx", path_imgrec, "r")
            self.seq = list(self._record.keys)
        elif path_imglist or imglist is not None:
            if path_imglist:
                with open(path_imglist) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        idx = int(parts[0])
                        label = np.array([float(v) for v in parts[1:-1]],
                                         np.float32)
                        self.imglist[idx] = (label, parts[-1])
            else:
                for i, (label, fname) in enumerate(imglist):
                    self.imglist[i] = (np.array(np.atleast_1d(label),
                                               np.float32), fname)
            self.path_root = path_root
            self.seq = list(self.imglist.keys())
        else:
            raise MXNetError("ImageIter needs path_imgrec, path_imglist or "
                             "imglist")
        if last_batch_handle not in ("pad", "discard"):
            raise MXNetError("ImageIter supports last_batch_handle 'pad' or "
                             f"'discard', got {last_batch_handle!r}")
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.cursor = 0
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self._data_name,
                         (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = ((self.batch_size,) if self.label_width == 1
                 else (self.batch_size, self.label_width))
        return [DataDesc(self._label_name, shape)]

    def reset(self):
        if self.shuffle:
            _pyrandom.shuffle(self.seq)
        self.cursor = 0

    def _read_sample(self, idx, want_img=True):
        if self._record is not None:
            header, img_bytes = _recordio.unpack(self._record.read_idx(idx))
            label = np.atleast_1d(np.asarray(header.label, np.float32))
            img = imdecode(img_bytes) if want_img else None
        else:
            label, fname = self.imglist[idx]
            img = (imread(os.path.join(self.path_root, fname))
                   if want_img else None)
        return label, img

    def _augment(self, img):
        for aug in self.auglist:
            img = aug(img)
        return img

    def next(self):
        if self.cursor >= len(self.seq):
            raise StopIteration
        n = self.batch_size
        C, H, W = self.data_shape
        data = np.zeros((n, C, H, W), self.dtype)
        lw = self.label_width
        label = np.zeros((n,) if lw == 1 else (n, lw), np.float32)
        pad = 0
        for i in range(n):
            if self.cursor >= len(self.seq):
                if self.last_batch_handle == "discard":
                    raise StopIteration
                # wrap-around padding, reference semantics
                src = self.seq[pad % len(self.seq)]
                pad += 1
            else:
                src = self.seq[self.cursor]
                self.cursor += 1
            lab, img = self._read_sample(src)
            img = self._augment(img)
            arr = (img.asnumpy() if isinstance(img, NDArray)
                   else np.asarray(img)).astype(self.dtype)
            data[i] = arr.transpose(2, 0, 1)
            label[i] = lab if lw > 1 else lab[0]
        return DataBatch([array(data)], [array(label)], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)
