"""mx.image.detection — detection data iterator + box-aware augmenters
(REF:python/mxnet/image/detection.py ImageDetIter; C++ twin
REF:src/io/iter_image_det_recordio.cc + image_det_aug_default.cc).

Label layout follows the reference's padded header format: each sample's
label is a fixed-width (max_objects, 5) float block of [cls, x1, y1, x2, y2]
rows (normalized corners), padded with -1 — which is exactly the fixed-shape
input `MultiBoxTarget` wants on TPU (no dynamic shapes, SURVEY §7.3)."""
from __future__ import annotations

import random as _pyrandom

import numpy as np

from ..base import MXNetError
from ..io.io import DataBatch, DataDesc
from ..ndarray import NDArray, array
from .image import (Augmenter, CastAug, ColorJitterAug, ColorNormalizeAug,
                    ForceResizeAug, ImageIter)

__all__ = ["DetAugmenter", "DetBorrowAug", "DetHorizontalFlipAug",
           "DetRandomCropAug", "DetForceResizeAug", "CreateDetAugmenter",
           "ImageDetIter"]


class DetAugmenter:
    """Augmenter over (img, label) pairs; label rows [cls, x1, y1, x2, y2]."""

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap a plain image augmenter that doesn't move pixels spatially."""

    def __init__(self, augmenter):
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetHorizontalFlipAug(DetAugmenter):
    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src, label):
        if _pyrandom.random() < self.p:
            arr = src.asnumpy() if isinstance(src, NDArray) else src
            src = array(np.ascontiguousarray(arr[:, ::-1]), dtype="uint8")
            label = label.copy()
            valid = label[:, 0] >= 0
            x1 = label[:, 1].copy()
            label[valid, 1] = 1.0 - label[valid, 3]
            label[valid, 3] = 1.0 - x1[valid]
        return src, label


class DetRandomCropAug(DetAugmenter):
    """IoU-constrained random crop (SSD data augmentation)."""

    def __init__(self, min_object_covered=0.3, aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.3, 1.0), max_attempts=20):
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts

    def __call__(self, src, label):
        h, w = src.shape[0], src.shape[1]
        valid = label[:, 0] >= 0
        if not valid.any():
            return src, label
        for _ in range(self.max_attempts):
            scale = _pyrandom.uniform(*self.area_range)
            ratio = _pyrandom.uniform(*self.aspect_ratio_range)
            cw = min(1.0, np.sqrt(scale * ratio))
            ch = min(1.0, np.sqrt(scale / ratio))
            cx0 = _pyrandom.uniform(0, 1 - cw)
            cy0 = _pyrandom.uniform(0, 1 - ch)
            crop = np.array([cx0, cy0, cx0 + cw, cy0 + ch])
            boxes = label[valid, 1:5]
            ix1 = np.maximum(boxes[:, 0], crop[0])
            iy1 = np.maximum(boxes[:, 1], crop[1])
            ix2 = np.minimum(boxes[:, 2], crop[2])
            iy2 = np.minimum(boxes[:, 3], crop[3])
            inter = np.maximum(ix2 - ix1, 0) * np.maximum(iy2 - iy1, 0)
            areas = ((boxes[:, 2] - boxes[:, 0]) *
                     (boxes[:, 3] - boxes[:, 1]))
            cover = np.where(areas > 0, inter / np.maximum(areas, 1e-12), 0)
            keep = cover >= self.min_object_covered
            if not keep.any():
                continue
            # crop pixels
            px0, py0 = int(crop[0] * w), int(crop[1] * h)
            px1, py1 = int(crop[2] * w), int(crop[3] * h)
            arr = (src.asnumpy() if isinstance(src, NDArray)
                   else np.asarray(src))[py0:py1, px0:px1]
            # remap surviving boxes into crop coords, drop the rest
            new_label = -np.ones_like(label)
            rows = label[valid][keep].copy()
            rows[:, 1] = np.clip((rows[:, 1] - crop[0]) / cw, 0, 1)
            rows[:, 2] = np.clip((rows[:, 2] - crop[1]) / ch, 0, 1)
            rows[:, 3] = np.clip((rows[:, 3] - crop[0]) / cw, 0, 1)
            rows[:, 4] = np.clip((rows[:, 4] - crop[1]) / ch, 0, 1)
            new_label[:rows.shape[0]] = rows
            return array(np.ascontiguousarray(arr), dtype="uint8"), new_label
        return src, label


class DetForceResizeAug(DetAugmenter):
    """Resize to exact (w, h); normalized boxes are unchanged."""

    def __init__(self, size, interp=1):
        self._resize = ForceResizeAug(size, interp)

    def __call__(self, src, label):
        return self._resize(src), label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_mirror=False,
                       mean=None, std=None, brightness=0, contrast=0,
                       saturation=0, min_object_covered=0.3,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.3, 1.0), max_attempts=20,
                       inter_method=2, **kwargs):
    """REF:python/mxnet/image/detection.py CreateDetAugmenter flag set."""
    auglist = []
    if rand_crop > 0:
        auglist.append(DetRandomCropAug(min_object_covered,
                                        aspect_ratio_range, area_range,
                                        max_attempts))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    auglist.append(DetForceResizeAug((data_shape[2], data_shape[1]),
                                     inter_method))
    auglist.append(DetBorrowAug(CastAug()))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(ColorJitterAug(brightness, contrast,
                                                   saturation)))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(DetBorrowAug(ColorNormalizeAug(
            mean if mean is not None else np.zeros(3, np.float32), std)))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator: batches are (data (B,C,H,W),
    label (B, max_objects, 5)) — the SSD training input pair.

    If `max_objects` is not given, construction scans every record's label
    header once (no image decode) to find the widest sample; pass it
    explicitly for large datasets to skip the scan."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", imglist=None, shuffle=False,
                 aug_list=None, max_objects=None, data_name="data",
                 label_name="label", last_batch_handle="pad", **kwargs):
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape, **kwargs)
        super().__init__(batch_size, data_shape, label_width=-1,
                         path_imgrec=path_imgrec, path_imglist=path_imglist,
                         path_root=path_root, imglist=imglist,
                         shuffle=shuffle, aug_list=aug_list,
                         data_name=data_name, label_name=label_name,
                         last_batch_handle=last_batch_handle)
        self.max_objects = max_objects or self._scan_max_objects()

    def _scan_max_objects(self):
        mx_obj = 1
        for idx in self.seq:
            lab, _ = self._peek_label(idx)
            mx_obj = max(mx_obj, lab.shape[0])
        return mx_obj

    def _peek_label(self, idx):
        label, _img = self._read_sample(idx, want_img=False)
        return self._reshape_label(label), None

    @staticmethod
    def _reshape_label(label):
        """Accept flat [cls,x1,y1,x2,y2]*m or (m,5); return (m,5)."""
        lab = np.asarray(label, np.float32)
        if lab.ndim == 1:
            if lab.size % 5:
                raise MXNetError("det label width must be a multiple of 5")
            lab = lab.reshape(-1, 5)
        return lab

    @property
    def provide_label(self):
        return [DataDesc(self._label_name,
                         (self.batch_size, self.max_objects, 5))]

    def next(self):
        if self.cursor >= len(self.seq):
            raise StopIteration
        n = self.batch_size
        C, H, W = self.data_shape
        data = np.zeros((n, C, H, W), self.dtype)
        label = -np.ones((n, self.max_objects, 5), np.float32)
        pad = 0
        for i in range(n):
            if self.cursor >= len(self.seq):
                if self.last_batch_handle == "discard":
                    raise StopIteration
                src = self.seq[pad % len(self.seq)]
                pad += 1
            else:
                src = self.seq[self.cursor]
                self.cursor += 1
            raw_label, img = self._read_sample(src)
            lab = self._reshape_label(raw_label)
            full = -np.ones((self.max_objects, 5), np.float32)
            m = min(lab.shape[0], self.max_objects)
            full[:m] = lab[:m]
            for aug in self.auglist:
                img, full = aug(img, full) if isinstance(aug, DetAugmenter) \
                    else (aug(img), full)
            arr = (img.asnumpy() if isinstance(img, NDArray)
                   else np.asarray(img)).astype(self.dtype)
            data[i] = arr.transpose(2, 0, 1)
            label[i] = full
        return DataBatch([array(data)], [array(label)], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)
