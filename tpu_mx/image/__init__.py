"""mx.image — image IO/augment/iterators (REF:python/mxnet/image/)."""
from .image import *  # noqa: F401,F403
from .detection import (CreateDetAugmenter, DetAugmenter, DetBorrowAug,
                        DetHorizontalFlipAug, DetForceResizeAug,
                        DetRandomCropAug, ImageDetIter)
