"""tpu_mx — a TPU-native deep learning framework with the capabilities of the
reference (`anandj91/anand-mxnet`, an Apache MXNet 1.x fork), built on
JAX/XLA/Pallas/pjit.  See SURVEY.md for the capability blueprint.

Import surface mirrors the reference's `import mxnet as mx`:
    mx.nd, mx.autograd, mx.gluon, mx.optimizer, mx.metric, mx.init,
    mx.context / mx.cpu() / mx.gpu(i) / mx.tpu(i), mx.kvstore, mx.random,
    mx.profiler, mx.io, mx.recordio, mx.test_utils, mx.runtime
"""
__version__ = "0.1.0"

# Multi-process boot must precede any JAX computation, so it happens at
# import time from the launcher's env protocol — the analog of the
# reference's LibraryInitializer reading DMLC_ROLE (REF:src/initialize.cc).
from .base import dist_boot as _dist_boot
_dist_boot()

from . import base
from .base import MXNetError
from .context import (Context, cpu, cpu_pinned, current_context, gpu, num_gpus,
                      num_tpus, tpu)
from . import ndarray
from . import ndarray as nd
from . import symbol
from . import symbol as sym
from . import autograd
from . import random
from . import initializer
from . import initializer as init
from . import optimizer
from . import metric
from . import gluon
from . import kvstore
from . import kvstore as kv
from . import attribute
from .attribute import AttrScope
from . import name
from . import util
from .optimizer import lr_scheduler
from . import executor
from . import libinfo
from . import module
from . import visualization
from . import visualization as viz
from . import model
from . import callback
from . import numpy as np
from . import npx
from . import contrib
from . import recordio
from . import io
from . import image
from . import test_utils
from . import telemetry
from . import tracing
from . import profiler
from . import monitor
from . import runtime
from . import fusion
from . import engine
from . import layout
from . import checkpoint
from . import elastic
from . import resume
from . import supervisor
from . import operator
from . import rtc
