"""Elastic fleet membership: the preemption→reshard→rejoin loop (ISSUE 17).

Everything below PR 2–5's durability stack assumed the world size never
changes: a preempted worker meant "restart the same N processes or give
up."  On preemptible pools that is wrong twice over — capacity comes and
goes, and the job should keep training on whatever is healthy.  This
module closes the loop with a *membership-epoch protocol*:

- The fleet has a monotone **generation** (the membership epoch), stored
  in ``<root>/gen.json`` together with the member list it admits.  Every
  world-size transition is a generation bump; nothing about membership
  is ever communicated out-of-band.
- Workers **join** by writing ``<root>/members/<rank>.json`` and renew it
  with **heartbeats**; a member whose heartbeat is older than the lease
  is *lost* (a partitioned process is evicted exactly like a dead one —
  liveness is the lease, not the exit code).
- The **controller** (one per fleet: ``tools/launch.py --supervise``, or
  the test harness) reconciles: lost members are evicted, pending
  joiners admitted, each change advancing the generation.
- Workers poll the generation at **step boundaries** (:meth:`Fleet.on_step`)
  and quiesce by raising :class:`MembershipChange` — a
  :class:`~tpu_mx.elastic.WorkerFailure`, so the supervisor's classify
  discipline catches it mid-collective too — then reshard onto the new
  world: rebuild the mesh, drive ``CompiledTrainStep.load_state_dict``
  (which re-places every host leaf on the *current* mesh — the seam
  proven by parallel/train_step.py), and re-partition the data stream
  from its GLOBAL cursor (io.NDArrayIter ``set_shard`` / capsule v2,
  tpu_mx/resume.py).

The store is plain files under one directory because that is what the
single-host fleet (``--launcher local``, subprocess workers) and the CI
churn proof can share without a network service; the protocol — monotone
epoch, lease-based liveness, admission only at an epoch bump,
generation-tagged barriers (``elastic.barrier(..., fleet=...)``) — is
what a jax.distributed KV-store backend would implement identically.

Zombie safety: a worker that missed an epoch bump still holds the OLD
generation; every barrier it enters is tagged ``tag@gen`` and checked
against the current epoch first, so it raises ``WorkerFailure`` loudly
instead of satisfying — or wedging — the new cohort's rendezvous.

See docs/robustness.md ("Elastic fleets") for the full protocol and the
degrade ladder, docs/parallelism.md for the mesh-rebuild side.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time

from .. import checkpoint as _ckpt
from .. import telemetry as _telemetry
from .. import tracing as _tracing
from ..elastic import WorkerFailure

__all__ = ["FLEET_FORMAT", "Fleet", "MembershipChange", "generation_token",
           "live_world_size", "note_reshard", "reshard_live"]

log = logging.getLogger(__name__)

FLEET_FORMAT = "tpu_mx-fleet-v1"

#: env protocol (set by tools/launch.py --supervise for every worker)
ENV_DIR = "TPUMX_FLEET_DIR"
ENV_MEMBER = "TPUMX_FLEET_MEMBER"
ENV_LEASE = "TPUMX_FLEET_LEASE"


class MembershipChange(WorkerFailure):
    """The fleet's membership epoch moved: quiesce and reshard.

    Raised at a step boundary by :meth:`Fleet.check` (and therefore by
    :meth:`Fleet.on_step` inside a supervised step) when the fleet
    generation no longer matches the one this worker adopted.  It IS a
    :class:`~tpu_mx.elastic.WorkerFailure`, so a membership change that
    first surfaces as a failed collective (barrier timeout because the
    peer died) lands in the same supervisor except-path — which then
    classifies it as ``membership``, not a fault: restore from the last
    verified manifest onto the new mesh without burning the restart
    budget (tpu_mx/supervisor.py)."""

    def __init__(self, message, generation=0, world_size=0):
        super().__init__(message)
        self.generation = int(generation)
        self.world_size = int(world_size)


# ---------------------------------------------------------------------------
# process-global generation token (kvstore cache invalidation)
# ---------------------------------------------------------------------------
# kvstore.py caches rank/world-size at init (they are jax-process-level
# constants in a static world).  In an elastic world they are membership
# facts: every generation this process observes bumps the token, and the
# kvstore re-reads its cached world on the next access (the ISSUE 17
# bugfix — a resharded run must never aggregate with the stale count).
_note_lock = threading.Lock()
_generation_token = 0
_live_world = None


def generation_token():
    """Monotone count of membership-epoch observations in this process."""
    return _generation_token


def live_world_size():
    """World size of the most recently observed membership epoch, or None
    when no fleet epoch has been observed (static-world processes)."""
    return _live_world


def _note_generation(generation, world_size):
    global _generation_token, _live_world
    with _note_lock:
        _generation_token += 1
        _live_world = int(world_size)
    _telemetry.gauge("fleet.membership_epoch").set(int(generation))


def note_reshard(from_world, to_world, source, generation=0):
    """Record a world-size transition driven through the reshard seam.

    ``source`` is ``"manifest"`` (fault recovery: state reloaded from the
    last verified checkpoint + capsule) or ``"live"`` (planned change: the
    in-memory state was valid, no disk round-trip).  Both the supervisor's
    membership branch and :func:`reshard_live` funnel through here so the
    ``fleet.reshards`` counter and the ``fleet.reshard`` event mean one
    thing."""
    _telemetry.counter("fleet.reshards").inc()
    _tracing.emit("fleet.reshard", generation=int(generation),
                  from_world=int(from_world), to_world=int(to_world),
                  source=str(source))


def reshard_live(old_step, step_factory, *, from_world, to_world, fleet=None):
    """Planned scale-up/down: rebuild the train step at the new world size
    from IN-MEMORY state.  No fault happened, so no manifest round-trip —
    ``state_dict()`` off the old step, a fresh step on the new mesh, and
    ``load_state_dict`` re-places every leaf on it (the reshard seam).
    Returns the new step; records the transition with ``source="live"``."""
    sd = old_step.state_dict()
    new_step = step_factory()
    new_step.load_state_dict(sd)
    note_reshard(from_world, to_world, source="live",
                 generation=0 if fleet is None else fleet.acked_generation)
    return new_step


# ---------------------------------------------------------------------------
# the membership store
# ---------------------------------------------------------------------------
class Fleet:
    """One worker's (or the controller's) handle on the membership store.

    ``member`` is this process's rank slot (None for a pure controller);
    ``controller=True`` additionally grants the reconcile/advance side —
    exactly ONE controller per fleet (the launcher, or the worker that
    doubles as one in single-process tests): ``advance`` is a
    read-modify-write of ``gen.json``, serialized only by that
    convention.

    Worker lifecycle::

        f = Fleet.from_env()          # or Fleet(root, member=rank)
        f.join()
        f.await_admission()           # blocks until an epoch admits us
        rank, world = f.shard()       # position for iterator/mesh setup
        ...
        sup = Supervisor(..., fleet=f)   # on_step() at every boundary
    """

    def __init__(self, root, member=None, controller=False, lease=10.0):
        self.root = os.fspath(root)
        self.member = None if member is None else int(member)
        self.controller = bool(controller)
        self.lease = float(lease)
        self._beat = 0
        self._acked_gen = None      # generation this process adopted
        self._acked_world = None    # member list of that generation
        self._shipper = None        # lazy fleet_obs.ObsShipper (workers)
        os.makedirs(os.path.join(self.root, "members"), exist_ok=True)

    @classmethod
    def from_env(cls, env=None):
        """Build from the ``TPUMX_FLEET_*`` env protocol, or None when no
        fleet directory is advertised (static-world processes)."""
        env = os.environ if env is None else env
        root = env.get(ENV_DIR)
        if not root:
            return None
        member = env.get(ENV_MEMBER)
        return cls(root, member=None if member is None else int(member),
                   lease=float(env.get(ENV_LEASE, "10.0")))

    # -- files ------------------------------------------------------------
    def _epoch_path(self):
        return os.path.join(self.root, "gen.json")

    def _member_path(self, member):
        return os.path.join(self.root, "members", f"{int(member)}.json")

    def _quarantine_path(self, member):
        return os.path.join(self.root, "quarantine", f"{int(member)}.json")

    @staticmethod
    def _read_json(path):
        try:
            with open(path, encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def read_epoch(self):
        """The current membership record, or None before the first
        :meth:`advance`."""
        ep = self._read_json(self._epoch_path())
        if not isinstance(ep, dict) or ep.get("format") != FLEET_FORMAT:
            return None
        return ep

    # -- views ------------------------------------------------------------
    @property
    def generation(self):
        """The CURRENT membership epoch on disk (0 before the first)."""
        ep = self.read_epoch()
        return 0 if ep is None else int(ep.get("generation", 0))

    @property
    def acked_generation(self):
        """The membership epoch this process has ADOPTED (0 if none)."""
        return 0 if self._acked_gen is None else self._acked_gen

    @property
    def acked_world_size(self):
        return 0 if not self._acked_world else len(self._acked_world)

    def world(self):
        """Member list of the current on-disk epoch."""
        ep = self.read_epoch()
        return [] if ep is None else [int(m) for m in ep.get("world", [])]

    def members(self):
        """All member records on disk: {rank: record} (stale ones too)."""
        out = {}
        mdir = os.path.join(self.root, "members")
        try:
            names = os.listdir(mdir)
        except OSError:
            return out
        for name in names:
            if not name.endswith(".json"):
                continue  # *.tmp.* debris from a beat that died mid-write
            rec = self._read_json(os.path.join(mdir, name))
            if isinstance(rec, dict) and "member" in rec:
                out[int(rec["member"])] = rec
        return out

    def _fresh(self, rec, now):
        return rec is not None and (now - float(rec.get("wall_time", 0.0))
                                    <= self.lease)

    def live(self):
        """Members with a fresh heartbeat (within the lease)."""
        now = time.time()
        return sorted(m for m, rec in self.members().items()
                      if self._fresh(rec, now))

    def lost(self):
        """In-world members whose heartbeat lease has expired.

        A member with NO record at all is *pending*, not lost: admission
        at launch is optimistic (the controller opens the epoch before
        the workers finish booting), so liveness judgment starts at the
        first join.  A worker that dies before ever joining is the
        launcher's exit-code path to catch, not the lease's."""
        now = time.time()
        recs = self.members()
        return sorted(m for m in self.world()
                      if recs.get(m) is not None
                      and not self._fresh(recs[m], now))

    def joiners(self):
        """Live members NOT in the current world (pending admission)."""
        in_world = set(self.world())
        return [m for m in self.live() if m not in in_world]

    # -- worker side ------------------------------------------------------
    def _write_member(self, fsync=False):
        self._beat += 1
        body = {"member": self.member, "pid": os.getpid(),
                "beat": self._beat, "generation": self.acked_generation,
                "wall_time": time.time()}
        with _ckpt.atomic_write(self._member_path(self.member), mode="w",
                                fsync=fsync) as f:
            f.write(json.dumps(body))

    def join(self):
        """Announce this member.  If the current epoch already admits it
        (the initial cohort), adopt that epoch immediately; otherwise the
        member is pending until the controller advances
        (:meth:`await_admission`).  Returns the current generation."""
        if self.member is None:
            raise ValueError("Fleet.join: this handle has no member slot")
        self._write_member(fsync=True)
        ep = self.read_epoch()
        _tracing.emit("fleet.join", member=self.member,
                      generation=0 if ep is None else int(ep["generation"]))
        if ep is not None and self.member in [int(m) for m in ep["world"]]:
            self._adopt(ep)
        return self.generation

    def await_admission(self, timeout=60.0, poll=0.05):
        """Block until a membership epoch admits this member (late joiners
        are admitted only at the NEXT epoch — that is the protocol), then
        adopt it and return the epoch record."""
        deadline = time.monotonic() + float(timeout)
        while True:
            ep = self.read_epoch()
            if ep is not None and self.member in [int(m)
                                                  for m in ep["world"]]:
                self._adopt(ep)
                return ep
            if time.monotonic() >= deadline:
                raise WorkerFailure(
                    f"fleet member {self.member}: no membership epoch "
                    f"admitted this worker within {timeout:.0f}s "
                    f"(current generation {self.generation})")
            self.heartbeat()
            time.sleep(poll)

    def heartbeat(self):
        """Renew this member's lease — unless the ``partition_worker``
        chaos fault says this member is network-partitioned, in which case
        the beat is silently dropped (its *absence* is the fault)."""
        if self.member is None:
            return
        from ..contrib import chaos
        if chaos.partitioned(self.member):
            return
        self._write_member(fsync=False)
        _telemetry.counter("fleet.heartbeats").inc()

    def leave(self, reason="completed"):
        """Clean departure: withdraw the member record.  Does NOT advance
        the generation — eviction/admission epochs are the controller's
        call; a clean leaver simply stops renewing its lease."""
        _tracing.emit("fleet.leave", member=self.member,
                      generation=self.generation, reason=str(reason))
        self._ship_obs(force=True)   # final snapshot before departure
        try:
            os.remove(self._member_path(self.member))
        except OSError:
            pass

    def _adopt(self, ep):
        self._acked_gen = int(ep["generation"])
        self._acked_world = [int(m) for m in ep["world"]]
        _note_generation(self._acked_gen, len(self._acked_world))
        # stamp the fleet identity onto every telemetry record and trace
        # event this process emits from here on: the cross-rank merge
        # (fleet_obs) keys stale-generation exclusion and step
        # correlation on these two fields
        _telemetry.set_fleet_identity(rank=self.member,
                                      generation=self._acked_gen)
        _tracing.set_context(rank=self.member,
                             fleet_generation=self._acked_gen)

    def ack(self):
        """Adopt the current on-disk epoch (after the reshard that a
        :class:`MembershipChange` demanded).  Returns the epoch record."""
        ep = self.read_epoch()
        if ep is None:
            raise WorkerFailure(
                f"fleet at {self.root}: no membership epoch to ack")
        self._adopt(ep)
        return ep

    def check(self):
        """Raise :class:`MembershipChange` if the membership epoch moved
        past the one this process adopted (the step-boundary quiesce)."""
        gen = self.generation
        if gen != self.acked_generation:
            ep = self.read_epoch() or {}
            world = len(ep.get("world", ()))
            raise MembershipChange(
                f"fleet membership epoch moved: generation "
                f"{self.acked_generation} -> {gen} (world size {world}, "
                f"reason {ep.get('reason', '?')!r}) — quiesce and reshard",
                generation=gen, world_size=world)

    def poll_changed(self):
        """True when the epoch moved (controller handles also reconcile
        first, so a WorkerFailure raised by a dying peer's collective is
        recognized as a membership event the moment the lease expires)."""
        if self.controller:
            self.reconcile()
        return self.generation != self.acked_generation

    def on_step(self):
        """The per-step fleet duty cycle, called by the supervisor at
        every step boundary: fire a pending chaos preemption, renew the
        lease, reconcile (controller only), and quiesce if the epoch
        moved."""
        if self.member is not None:
            from ..contrib import chaos
            chaos.maybe_preempt(self.member)
            self.heartbeat()
            self._ship_obs()
        if self.controller:
            self.reconcile()
        self.check()

    def _ship_obs(self, force=False):
        """Export this worker's observability snapshot into the fleet
        store (rate-limited inside the shipper).  Best-effort: a full
        disk or torn store must never fail a train step."""
        if self.member is None:
            return
        if self._shipper is None:
            try:
                from . import fleet_obs
            except ImportError:
                return
            self._shipper = fleet_obs.ObsShipper(self)
        try:
            self._shipper.ship(force=force)
        except OSError:
            pass

    def shard(self):
        """``(rank, num_workers)`` of this member in its ADOPTED epoch —
        the re-partition coordinates for ``NDArrayIter.set_shard`` and
        the mesh rebuild."""
        if not self._acked_world or self.member not in self._acked_world:
            raise WorkerFailure(
                f"fleet member {self.member} is not in the adopted "
                f"membership epoch {self.acked_generation} "
                f"(world {self._acked_world}) — join/await_admission first")
        return self._acked_world.index(self.member), len(self._acked_world)

    def barrier_tag(self, tag):
        """Generation-tagged rendezvous name (``tag@gen``): a zombie from
        a previous epoch can never pair with the current cohort.  Prefer
        passing ``fleet=`` to :func:`tpu_mx.elastic.barrier`, which also
        raises loudly on a stale generation instead of waiting out the
        timeout."""
        return f"{tag}@{self.acked_generation}"

    # -- controller side --------------------------------------------------
    def advance(self, world=None, reason="advance"):
        """Open the next membership epoch admitting exactly ``world``
        (default: every member with a live lease).  The ONE write that
        changes membership — monotone generation, atomic commit."""
        prev = self.read_epoch()
        gen = (0 if prev is None else int(prev["generation"])) + 1
        if world is None:
            world = self.live()
        world = sorted({int(m) for m in world})
        body = {"format": FLEET_FORMAT, "generation": gen, "world": world,
                "world_size": len(world), "reason": str(reason),
                "wall_time": time.time()}
        with _ckpt.atomic_write(self._epoch_path(), mode="w") as f:
            f.write(json.dumps(body))
        _tracing.emit("fleet.epoch", generation=gen, world_size=len(world),
                      reason=str(reason))
        log.warning("fleet: membership epoch %d opened (world %s, %s)",
                    gen, world, reason)
        if self.member is None:
            # pure controller: observe the epoch it just opened (members
            # adopt via ack()/await_admission after their reshard)
            _note_generation(gen, len(world))
            self._acked_gen, self._acked_world = gen, world
        return body

    def reconcile(self, reason=None):
        """Evict lease-expired members, admit pending joiners; advance the
        generation if (and only if) membership changed.  Returns the new
        epoch record, or None when the world is unchanged."""
        lost, joiners = self.lost(), self.joiners()
        q = self.quarantined()
        if q:
            # quarantine is permanent: a quarantined rank still beating
            # (healed partition, zombie process) must neither stay in
            # the world nor rejoin it — distinct from lease eviction,
            # which a healed member survives
            barred = [m for m in joiners if m in q]
            if barred:
                log.warning("fleet: refusing re-admission of quarantined "
                            "member(s) %s", barred)
            joiners = [m for m in joiners if m not in q]
            lost = sorted(set(lost) | (set(self.world()) & set(q)))
        if not lost and not joiners:
            return None
        now = time.time()
        recs = self.members()
        for m in lost:
            rec = recs.get(m)
            age = now - float(rec.get("wall_time", 0.0)) if rec else self.lease
            _tracing.emit("fleet.lost", member=m, age_seconds=float(age))
            _telemetry.counter("fleet.lost_workers").inc()
            log.warning("fleet: member %d lost (lease expired %.2fs ago)",
                        m, age - self.lease)
        new_world = sorted((set(self.world()) - set(lost)) | set(joiners))
        if reason is None:
            reason = "lost" if lost and not joiners else (
                "rejoin" if joiners and not lost else "churn")
        ep = self.advance(world=new_world, reason=reason)
        for m in joiners:
            _tracing.emit("fleet.rejoin", member=m,
                          generation=int(ep["generation"]))
            _telemetry.counter("fleet.rejoins").inc()
        return ep

    def evict(self, member, reason="preempted"):
        """Launcher fast path: it SAW the worker die (exit code), no need
        to wait out the lease.  Advances the generation without it."""
        _tracing.emit("fleet.leave", member=int(member),
                      generation=self.generation, reason=str(reason))
        _telemetry.counter("fleet.lost_workers").inc()
        world = [m for m in self.world() if m != int(member)]
        # Drop the corpse's member record too: its last heartbeat may
        # still be inside the lease, and a fresh-looking record would
        # make the next reconcile() re-admit a worker the controller
        # KNOWS is dead.  (Lease-path eviction keeps the record — a
        # partitioned worker that heals resumes beating and rejoins.)
        try:
            os.remove(self._member_path(int(member)))
        except OSError:
            pass
        return self.advance(world=world, reason=reason)

    # -- quarantine (ISSUE 20: SDC defense) --------------------------------
    def quarantine(self, member, reason="corruption", step=0):
        """Permanently bar ``member`` from the fleet: a corruption
        verdict (parallel/integrity.py) named this rank's hardware, and
        — unlike a lease eviction, where a healed partition resumes
        beating and rejoins — a flaky chip must NEVER be re-admitted.
        The record under ``<root>/quarantine/`` is the durable verdict:
        :meth:`reconcile`, :meth:`admit` and the launcher's restart path
        all refuse quarantined ranks against it.  Any member may write
        it (the corrupt worker self-reports before dying; the controller
        writes it when it holds the vote) — writing is idempotent."""
        body = {"member": int(member), "reason": str(reason)[:500],
                "step": int(step), "generation": self.generation,
                "wall_time": time.time()}
        os.makedirs(os.path.join(self.root, "quarantine"), exist_ok=True)
        with _ckpt.atomic_write(self._quarantine_path(member),
                                mode="w") as f:
            f.write(json.dumps(body))
        # drop the member record too: its last heartbeat may still be
        # fresh, and a fresh-looking lease would keep the rank "live"
        try:
            os.remove(self._member_path(int(member)))
        except OSError:
            pass
        _telemetry.counter("integrity.quarantined").inc()
        _tracing.emit("integrity.quarantine", rank=int(member),
                      reason=str(reason)[:300], step=int(step))
        log.error("fleet: member %d QUARANTINED (%s) — permanent, never "
                  "re-admitted", int(member), reason)
        if self.controller and int(member) in self.world():
            self.advance(world=[m for m in self.world()
                                if m != int(member)],
                         reason="quarantine")
        return body

    def quarantined(self):
        """All quarantine verdicts on disk: {rank: record}."""
        out = {}
        qdir = os.path.join(self.root, "quarantine")
        try:
            names = os.listdir(qdir)
        except OSError:
            return out
        for name in names:
            if not name.endswith(".json"):
                continue
            rec = self._read_json(os.path.join(qdir, name))
            if isinstance(rec, dict) and "member" in rec:
                out[int(rec["member"])] = rec
        return out

    def is_quarantined(self, member):
        return self._read_json(self._quarantine_path(member)) is not None

    def admit(self, member, reason="rejoin"):
        """Launcher fast path: admit a (re)started worker at the next
        membership epoch.  Quarantined ranks are REFUSED — corruption
        verdicts are permanent (:meth:`quarantine`)."""
        if self.is_quarantined(member):
            raise WorkerFailure(
                f"fleet member {int(member)} is quarantined (data "
                f"corruption verdict) — re-admission refused; the "
                f"quarantine record under {self.root}/quarantine is "
                f"permanent")
        world = sorted(set(self.world()) | {int(member)})
        ep = self.advance(world=world, reason=reason)
        _tracing.emit("fleet.rejoin", member=int(member),
                      generation=int(ep["generation"]))
        _telemetry.counter("fleet.rejoins").inc()
        return ep

    def wait_member(self, member, timeout=30.0, poll=0.05):
        """Block until ``member`` has a live heartbeat (a restarted worker
        has come up and joined).  Returns True on success."""
        deadline = time.monotonic() + float(timeout)
        while time.monotonic() < deadline:
            if int(member) in self.live():
                return True
            time.sleep(poll)
        return False

    def __repr__(self):
        return (f"Fleet(root={self.root!r}, member={self.member}, "
                f"generation={self.generation}, "
                f"acked={self.acked_generation}, world={self.world()})")
